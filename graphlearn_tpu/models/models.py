"""Model stacks: GraphSAGE / GCN / GAT and a hetero (RGNN-style) wrapper.

Counterparts of the reference's example models
(/root/reference/examples/train_sage_ogbn_products.py SAGE stack,
examples/igbh/rgnn.py RGNN) implemented natively in flax over the padded
batch format. `HeteroConv` aggregates per-edge-type messages into per-node-
type embeddings (sum across relations), mirroring rgnn.py's HeteroConv use.
"""
from typing import Any, Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..typing import EdgeType, NodeType
from .conv import GATConv, GCNConv, SAGEConv

_CONVS = {'sage': SAGEConv, 'gcn': GCNConv, 'gat': GATConv}


def freeze_etype_items(d):
  """Tuple-keyed dict -> ((key, value), ...) pair tuple, for flax Module
  fields. flax >= 0.10 walks every Module attribute through its
  state-dict machinery at submodule registration, which asserts that
  dict keys are strings — so EdgeType-keyed mappings (convs,
  hop_edge_offsets) must be stored as pair tuples on Modules. Pass-through
  for None / already-converted values."""
  if isinstance(d, dict):
    return tuple((tuple(k) if isinstance(k, (tuple, list)) else k, v)
                 for k, v in d.items())
  return d


def thaw_etype_items(d):
  """Inverse of freeze_etype_items at call time: pair tuple -> dict
  (pass-through for dicts / None, so un-frozen callers keep working)."""
  if d is None or isinstance(d, dict):
    return d
  return dict(d)


def check_hetero_offsets(x_dict, edge_index_dict, hop_node_offsets,
                         hop_edge_offsets, num_layers):
  """Trace-time layout validation shared by the hierarchical hetero
  forwards (RGNN/HGT): jnp never errors on oversized slices, so a
  mismatched layout would silently slice wrong blocks."""
  for t, x in x_dict.items():
    assert t in hop_node_offsets, (
        f'hierarchical forward: batch has node type {t!r} but '
        f'hop_node_offsets only covers {list(hop_node_offsets)}')
    assert len(hop_node_offsets[t]) >= num_layers + 1, (
        f'hierarchical forward: hop_node_offsets for {t!r} has '
        f'{len(hop_node_offsets[t])} entries, need num_layers+1='
        f'{num_layers + 1} — layout fanouts must cover every layer')
    assert hop_node_offsets[t][-1] == x.shape[0], (
        f'hierarchical forward: node offsets for {t!r} '
        f'({hop_node_offsets[t]}) do not match the batch buffer '
        f'({x.shape[0]}); build them with sampler.hetero_tree_layout '
        'from the SAME seed caps/fanouts as the tree-mode loader')
  for et in edge_index_dict:
    assert tuple(et) in hop_edge_offsets, (
        f'hierarchical forward: batch has edge type {tuple(et)!r} but '
        f'hop_edge_offsets only covers {list(hop_edge_offsets)} — '
        'check the edge_dir orientation the layout was built with '
        '(batches key edges by the message-flow/reversed type)')
    assert len(hop_edge_offsets[tuple(et)]) >= num_layers, (
        f'hierarchical forward: hop_edge_offsets for {tuple(et)!r} must '
        f'cover {num_layers} hops')


def hetero_trim(x_dict, edge_index_dict, edge_mask_dict,
                hop_node_offsets, hop_edge_offsets, hops_used):
  """Slice the typed node/edge prefixes layer ``hops_used`` needs (the
  trim-per-layer step shared by RGNN and HGT hierarchical forwards)."""
  x_in = {t: x[:hop_node_offsets[t][hops_used]]
          for t, x in x_dict.items()}
  ei = {et: v[:, :hop_edge_offsets[tuple(et)][hops_used - 1]]
        for et, v in edge_index_dict.items()}
  em = {et: v[:hop_edge_offsets[tuple(et)][hops_used - 1]]
        for et, v in edge_mask_dict.items()}
  return x_in, ei, em


def _tree_blocks(node_offsets, fanouts, n_rows):
  """(blocks, edge_offsets) of a tree layout slice, with the
  un-truncated-layout guard shared by the dense-tree convs: a truncated
  (node_budget) layout can accidentally satisfy any divisibility check,
  so blocks are validated against the REAL fanouts."""
  no = tuple(node_offsets)
  assert no[-1] == n_rows, (no, n_rows)
  blocks = (no[0],) + tuple(no[i + 1] - no[i] for i in range(len(no) - 1))
  assert fanouts is not None and len(fanouts) >= len(blocks) - 1, (
      'dense-tree convs require the true fanouts to validate the layout')
  eo = [0]
  for d in range(len(blocks) - 1):
    assert blocks[d + 1] == blocks[d] * fanouts[d], (
        'dense-tree aggregation requires un-truncated tree blocks '
        f'(block {d + 1} = {blocks[d + 1]} != parent block '
        f'{blocks[d]} * fanout {fanouts[d]}); node_budget batches must '
        'use the segment-op path')
    eo.append(eo[-1] + blocks[d + 1])
  return blocks, eo


def _masked_run_softmax(e, mask, out_dtype, negative_slope):
  """Per-run masked attention softmax over axis 1 of [runs, k, H]
  logits — the shared kernel of the dense-run GAT convs (TreeGATConv /
  MergeGATConv): leaky_relu, mask to -inf, TRUE per-run max
  stabilization (clamping at 0 would underflow exp when every valid
  logit is very negative — the same stabilization GATConv's segment
  softmax uses; all-masked runs fall back to 0), exp, denom floor.
  Dispatches on RUN_SOFTMAX_IMPL (see above): 'window' keeps the whole
  f32 chain on the flat [runs*k, H] layout."""
  if RUN_SOFTMAX_IMPL == 'window':
    f, k, h = e.shape
    ef = nn.leaky_relu(e.reshape(f * k, h), negative_slope)
    mf = mask.reshape(f * k)
    ef = jnp.where(mf[:, None], ef, -jnp.inf)
    mx = jax.lax.reduce_window(ef, -jnp.inf, jax.lax.max, (k, 1), (k, 1),
                               'VALID')                          # [f, h]
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    ex = jnp.where(mf[:, None],
                   jnp.exp(ef - jnp.repeat(mx, k, axis=0)), 0.0)
    denom = jnp.maximum(
        jax.lax.reduce_window(ex, 0.0, jax.lax.add, (k, 1), (k, 1),
                              'VALID'), 1e-9)
    return (ex / jnp.repeat(denom, k, axis=0)).reshape(
        f, k, h).astype(out_dtype)
  e = nn.leaky_relu(e, negative_slope)
  e = jnp.where(mask[..., None], e, -jnp.inf)
  mx = e.max(axis=1, keepdims=True)
  e = e - jnp.where(jnp.isfinite(mx), mx, 0.0)
  ex = jnp.where(mask[..., None], jnp.exp(e), 0.0)
  denom = jnp.maximum(ex.sum(axis=1, keepdims=True), 1e-9)
  return (ex / denom).astype(out_dtype)


def _masked_run_mean(vals, mask):
  """Masked mean over axis 1 of a [runs, k, F] block ([runs, k] mask) —
  the shared aggregation kernel of the dense-run convs (TreeSAGEConv /
  MergeSAGEConv)."""
  s = jnp.where(mask[..., None], vals, jnp.zeros((), vals.dtype)).sum(1)
  inv = (1.0 / jnp.maximum(mask.sum(1), 1)).astype(vals.dtype)
  return s * inv[:, None]


def _impl_from_env(var: str, default: str, allowed) -> str:
  """Flat-layout decision machinery: the measured default below can be
  overridden per run (GLT_RUN_MEAN_IMPL / GLT_RUN_SOFTMAX_IMPL) — the
  deployment-side half of bench.py's ``run_mean_impl_decision`` key,
  which records the A/B winner so the next round can flip the default
  here with a one-line, evidence-linked change."""
  import os
  v = os.environ.get(var, '').strip()
  if not v:
    return default
  if v not in allowed:
    raise ValueError(f'{var}={v!r}: expected one of {sorted(allowed)}')
  return v


def run_impl_decision(reshape_ms, window_ms, rel_margin: float = 0.03):
  """The auto-land rule shared by bench.py's RUN_MEAN_IMPL A/B section:
  'window' wins only on a > ``rel_margin`` relative improvement (a
  within-noise tie keeps the incumbent 'reshape', the measured round-4
  configuration). Returns (decision, evidence-string); None inputs
  (a failed leg) return (None, reason)."""
  if reshape_ms is None or window_ms is None:
    return None, 'undecided: missing ' + (
        'both legs' if reshape_ms is None and window_ms is None else
        ('reshape leg' if reshape_ms is None else 'window leg'))
  if window_ms < reshape_ms * (1.0 - rel_margin):
    return 'window', (f'window {window_ms:.3f} ms beats reshape '
                      f'{reshape_ms:.3f} ms by >{rel_margin:.0%}')
  return 'reshape', (f'reshape {reshape_ms:.3f} ms holds (window '
                     f'{window_ms:.3f} ms, margin {rel_margin:.0%})')


# Run-aggregation implementation for the dense convs' mean kernels.
# 'reshape' (default): reduce over axis 1 of a [runs, k, F] view — the
# 3D reshape forces a relayout copy on TPU when k is not tile-aligned
# (fanouts 15/10/5 never are), part of the measured ~3.7 ms/step
# reshape tax (PERF.md 'MFU and the roofline'). 'window': keep the flat
# [runs*k, F] layout and reduce k-runs with lax.reduce_window
# (window/stride k on the row axis) — no 3D view materialized.
# Numerically identical (equivalence tests run under both); A/B traced
# by benchmarks/prof_copytax.py on the chip and auto-decided by
# bench.py's ``run_mean_impl_decision`` key (run_impl_decision above).
RUN_MEAN_IMPL = _impl_from_env('GLT_RUN_MEAN_IMPL', 'reshape',
                               ('reshape', 'window'))

# Same fork for the dense GAT convs' run softmax (TreeGATConv /
# MergeGATConv): the f32 [runs, k, H] softmax chain carries the same
# never-tile-aligned k as the mean kernels, and the round-4 trace left a
# ~1 ms/step tail of softmax-backward transposed layouts. 'window' runs
# the whole chain (leaky_relu -> per-run max -> exp -> per-run sum ->
# normalize) on the FLAT [runs*k, H] layout with lax.reduce_window
# reductions — the further flat-layout rewrite of ISSUE 13(c);
# equivalence-tested under both, A/B'd by prof_copytax --softmax-ab.
RUN_SOFTMAX_IMPL = _impl_from_env('GLT_RUN_SOFTMAX_IMPL', 'reshape',
                                  ('reshape', 'window'))


def _masked_flat_run_mean(x, mask, k):
  """Masked mean over k-runs of a FLAT [f*k, F] block with a [f, k]
  mask, dispatching on RUN_MEAN_IMPL (see above)."""
  f = mask.shape[0]
  if RUN_MEAN_IMPL == 'window':
    xz = jnp.where(mask.reshape(-1)[:, None], x,
                   jnp.zeros((), x.dtype))
    s = jax.lax.reduce_window(xz, jnp.zeros((), x.dtype), jax.lax.add,
                              (k, 1), (k, 1), 'VALID')
    inv = (1.0 / jnp.maximum(mask.sum(1), 1)).astype(x.dtype)
    return s * inv[:, None]
  return _masked_run_mean(x.reshape(f, k, -1), mask)


class TreeSAGEConv(nn.Module):
  """SAGEConv over tree-positional batches, aggregation as DENSE reshape.

  In ``dedup='tree'`` layout the children of the node at slot ``s`` of
  depth block ``d`` occupy the CONTIGUOUS slots ``[o_d + s*k_d,
  o_d + (s+1)*k_d)`` of block ``d+1`` — so mean aggregation needs no
  edge gather and no segment scatter at all: reshape each child block to
  ``[parents, k, F]`` and take a masked mean over axis 1. Both ops (and
  their gradients) are dense — the TPU-shaped replacement for the
  scatter-add path, valid ONLY for un-truncated tree batches (no
  node_budget).

  Parameter names match ``SAGEConv`` (``lin_self``/``lin_nbr``) so the
  two are checkpoint-interchangeable.
  """
  out_dim: int
  node_offsets: Any    # (o_0..o_H) tree block offsets covering the input
  fanouts: Any = None  # true per-depth fanouts; guards against truncation
  use_bias: bool = True
  dtype: Any = None
  # out_rows: produce only the leading ``out_rows`` output rows (the
  # consumer's prefix). The DEEPEST block is pure child input — its conv
  # output is never read — so the layered forward passes the
  # parents-prefix width here and layer 0 skips ~80% of its matmul rows
  # (938k -> 170k at products scale). None = full input width.
  out_rows: Any = None

  @nn.compact
  def __call__(self, x, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    blocks, eo = _tree_blocks(self.node_offsets, self.fanouts, x.shape[0])
    no = tuple(self.node_offsets)
    r = x.shape[0] if self.out_rows is None else int(self.out_rows)
    aggs = []
    covered = 0
    for d in range(len(blocks) - 1):   # target block d <- child block d+1
      if covered >= r:
        break
      b, k = blocks[d], self.fanouts[d]
      ch = jax.lax.dynamic_slice_in_dim(x, no[d], blocks[d + 1])
      m = edge_mask[eo[d]:eo[d + 1]].reshape(b, k)
      aggs.append(_masked_flat_run_mean(ch, m, k))
      covered += b
    if covered < r:
      # remaining rows are childless in this slice: aggregate = 0
      aggs.append(jnp.zeros((r - covered, x.shape[-1]), x.dtype))
    agg = jnp.concatenate(aggs) if len(aggs) > 1 else aggs[0]
    assert agg.shape[0] == r, (
        f'out_rows={r} must align with the tree block structure '
        f'{no} (got coverage {agg.shape[0]})')
    h = nn.Dense(self.out_dim, use_bias=self.use_bias, dtype=self.dtype,
                 name='lin_self')(x[:r])
    return h + nn.Dense(self.out_dim, use_bias=False, dtype=self.dtype,
                        name='lin_nbr')(agg)


class MergeSAGEConv(nn.Module):
  """SAGEConv over exact-dedup (merge-layout) batches: per-hop blocked
  mean aggregation instead of segment scatter-adds.

  The merge engine emits each hop's edges in frontier order — every
  frontier node's ``k`` draws occupy CONSECUTIVE edge slots — so each
  hop's target column is k-CONSTANT runs. Mean aggregation becomes: one
  source-row gather, a ``[frontier, k]`` masked reshape-mean (dense VPU
  work), and a dense block write per hop (``dynamic_update_slice`` at
  the hop's contiguous target base — ZERO scatter transactions,
  replacing the segment scatter-add over the full edge width). Exact
  for every merge batch, including calibrated frontier caps (targets
  are unique across hops: dedup expands each node at most once).
  Parameter names match ``SAGEConv`` (``lin_self``/``lin_nbr``) —
  checkpoint-interchangeable.
  """
  out_dim: int
  edge_offsets: Any   # prefix sums of the hop edge blocks IN USE
  fanouts: Any        # per-hop fanout k_i (block run length)
  use_bias: bool = True
  dtype: Any = None
  # out_rows: produce only the leading prefix (see TreeSAGEConv) — the
  # last hop's appended nodes are childless, so their conv output is
  # never read. Every targeted row provably lies below the clamped
  # occupancy bound before the last hop (merge_layout_from_caps
  # prefix), which is what the layered forward passes here.
  out_rows: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0] if self.out_rows is None else int(self.out_rows)
    row, col = edge_index[0], edge_index[1]
    # per-hop targets are a contiguous block with valid runs leading
    # (see MergeGATConv): the row scatter is a dense block write at the
    # dynamic base — zero HBM scatter transactions in the aggregation
    acc = jnp.zeros((n, x.shape[-1]), x.dtype)
    e0 = 0
    for i, e1 in enumerate(self.edge_offsets):
      k = self.fanouts[i]
      width = e1 - e0
      assert width % k == 0, (
          f'hop {i} edge block {width} not a multiple of fanout {k}; '
          'edge_offsets/fanouts must come from the SAME plan as the '
          'merge-mode loader (models.train.merge_hop_offsets)')
      f = width // k
      src = jax.lax.dynamic_slice_in_dim(row, e0, width)
      tgt_blk = jax.lax.dynamic_slice_in_dim(col, e0, width).reshape(f, k)
      m = jax.lax.dynamic_slice_in_dim(edge_mask, e0, width).reshape(f, k)
      mean = _masked_flat_run_mean(x[jnp.maximum(src, 0)], m, k)
      # the k-run's target local idx (masked slots carry -1: take max)
      tgt = tgt_blk.max(1)
      ok = m.any(1) & (tgt >= 0)
      # base from tgt[j] - j: immune to leading all-masked runs
      # (zero-degree frontier nodes read tgt = -1) — see MergeGATConv
      base = jnp.min(jnp.where(
          ok, tgt - jnp.arange(f, dtype=tgt.dtype), n)).astype(jnp.int32)
      acc = jax.lax.dynamic_update_slice(
          acc, jnp.where(ok[:, None], mean, 0), (base, 0))
      e0 = e1
    agg = acc
    h = nn.Dense(self.out_dim, use_bias=self.use_bias, dtype=self.dtype,
                 name='lin_self')(x[:n])
    return h + nn.Dense(self.out_dim, use_bias=False, dtype=self.dtype,
                        name='lin_nbr')(agg)


class TreeGATConv(nn.Module):
  """GATConv over tree-positional batches: per-parent DENSE softmax.

  On tree batches every target's in-edges are exactly its contiguous
  child block, so GAT's segment softmax over in-edges becomes a plain
  masked softmax over the ``[parents, k]`` reshape — no segment ops, no
  gathers (children are a slice), dense gradients. Numerically matches
  ``GATConv`` on tree batches (same param names: ``lin``/``att_src``/
  ``att_dst``); valid only for un-truncated layouts (no node_budget).
  """
  out_dim: int
  node_offsets: Any
  fanouts: Any
  heads: int = 1
  negative_slope: float = 0.2
  concat: bool = True
  dtype: Any = None

  @nn.compact
  def __call__(self, x, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    no = tuple(self.node_offsets)
    blocks, eo = _tree_blocks(no, self.fanouts, x.shape[0])
    n, heads, hd = x.shape[0], self.heads, self.out_dim
    w = nn.Dense(heads * hd, use_bias=False, dtype=self.dtype,
                 name='lin')(x).reshape(n, heads, hd)
    a_src = self.param('att_src', nn.initializers.glorot_uniform(),
                       (heads, hd))
    a_dst = self.param('att_dst', nn.initializers.glorot_uniform(),
                       (heads, hd))
    wf = w.astype(jnp.float32)
    alpha_src = (wf * a_src[None]).sum(-1)        # [n, H]
    alpha_dst = (wf * a_dst[None]).sum(-1)
    outs = []
    for d in range(len(blocks) - 1):   # parents block d <- children d+1
      b, k = blocks[d], self.fanouts[d]
      lo = 0 if d == 0 else no[d - 1]
      ch = slice(no[d], no[d] + blocks[d + 1])
      e = (alpha_src[ch].reshape(b, k, heads) +
           alpha_dst[lo:lo + b][:, None, :])      # [b, k, H]
      m = edge_mask[eo[d]:eo[d + 1]].reshape(b, k)
      attn = _masked_run_softmax(e, m, w.dtype, self.negative_slope)
      msgs = w[ch].reshape(b, k, heads, hd)
      outs.append((msgs * attn[..., None]).sum(axis=1))  # [b, H, D]
    outs.append(jnp.zeros((blocks[-1], heads, hd), w.dtype))
    out = jnp.concatenate(outs)
    if self.concat:
      return out.reshape(n, heads * hd)
    return out.mean(axis=1)


class MergeGATConv(nn.Module):
  """GATConv over exact-dedup (merge-layout) batches: per-target DENSE
  softmax over its k-run.

  Dedup expands every node at most once, so a target's COMPLETE in-edge
  set is exactly its contiguous k-run in the hop that expanded it —
  GAT's segment softmax (scatter-max + scatter-sum per layer, the most
  scatter-bound op in the model zoo, PERF.md) becomes a masked softmax
  over the ``[frontier, k]`` reshape plus one frontier-sized row
  scatter per hop. Numerically matches ``GATConv`` on merge batches
  (same param names: ``lin``/``att_src``/``att_dst``), calibrated caps
  included.
  """
  out_dim: int
  edge_offsets: Any
  fanouts: Any
  heads: int = 1
  negative_slope: float = 0.2
  concat: bool = True
  dtype: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n, heads, hd = x.shape[0], self.heads, self.out_dim
    # w stays FLAT [n, heads*hd]: gathering (and the backward's
    # scatter-add) on 2D rows keeps XLA's standard T(8,128) layout —
    # gathering the [n, H, D] reshape instead puts the whole
    # grad-accumulation on a T(2,128)-tiled 3D layout that costs ~4x
    # (device-trace: 29 of a 42 ms backward, round 4)
    w = nn.Dense(heads * hd, use_bias=False, dtype=self.dtype,
                 name='lin')(x)
    a_src = self.param('att_src', nn.initializers.glorot_uniform(),
                       (heads, hd))
    a_dst = self.param('att_dst', nn.initializers.glorot_uniform(),
                       (heads, hd))
    # dst-alphas over the node buffer (f32 accumulation on the MXU);
    # src-alphas are computed from the GATHERED messages below — random
    # HBM gathers are transaction-bound (~150M rows/s, PERF.md), so one
    # [width]-row gather per hop is the whole random-access budget
    alpha_dst = jnp.einsum('nhd,hd->nh', w.reshape(n, heads, hd), a_dst,
                           preferred_element_type=jnp.float32)
    row, col = edge_index[0], edge_index[1]
    # merge-layout structure: hop i's valid runs target the CONTIGUOUS
    # block the inducer appended for them (frontier_idx = count +
    # arange), with valid runs leading — so the per-hop "scatter" is a
    # dense block write at the dynamic base (min valid target). Zero
    # rows past a hop's valid range land in the NEXT hop's block
    # (overwritten: bases ascend and writes apply in hop order) or in
    # the never-targeted tail, which must be zero anyway; an empty hop
    # writes zeros clamped into the padding tail (provably past every
    # targeted row).
    acc = jnp.zeros((n, heads * hd), w.dtype)
    e0 = 0
    for i, e1 in enumerate(self.edge_offsets):
      k = self.fanouts[i]
      width = e1 - e0
      assert width % k == 0, (
          f'hop {i} edge block {width} not a multiple of fanout {k}; '
          'build edge_offsets with models.train.merge_hop_offsets')
      f = width // k
      src = jnp.maximum(jax.lax.dynamic_slice_in_dim(row, e0, width), 0)
      tgt = jax.lax.dynamic_slice_in_dim(col, e0, width).reshape(f, k
                                                                 ).max(1)
      m = jax.lax.dynamic_slice_in_dim(edge_mask, e0, width
                                       ).reshape(f, k)
      msgs = w[src]                                # the one gather, 2D
      msgs4 = msgs.reshape(f, k, heads, hd)
      e = (jnp.einsum('fkhd,hd->fkh', msgs4.astype(jnp.float32), a_src) +
           alpha_dst[jnp.maximum(tgt, 0)][:, None, :])
      attn = _masked_run_softmax(e, m, w.dtype, self.negative_slope)
      outv = (msgs4 * attn[..., None]).sum(axis=1)  # [f, H, D]
      ok = m.any(1) & (tgt >= 0)
      # block base from tgt[j] - j (invariant across valid runs): a
      # zero-degree frontier node's run has ALL edges masked, so its
      # tgt reads -1 — min(valid tgt) alone would mis-base the write
      # when such runs lead the block
      base = jnp.min(jnp.where(
          ok, tgt - jnp.arange(f, dtype=tgt.dtype), n)).astype(jnp.int32)
      vals = jnp.where(ok[:, None], outv.reshape(f, heads * hd), 0)
      acc = jax.lax.dynamic_update_slice(acc, vals, (base, 0))
      e0 = e1
    if self.concat:
      return acc
    return acc.reshape(n, heads, hd).mean(axis=1)


class GraphSAGE(nn.Module):
  """Multi-layer GraphSAGE (reference example: 3 layers, hidden 256).

  ``hop_node_offsets`` / ``hop_edge_offsets`` (static prefix sums of the
  tree-mode sampler's positional hop blocks: node offsets
  ``[b, b+c0*k0, ...]`` and edge offsets ``[c0*k0, c0*k0+c1*k1, ...]``)
  enable the LAYERED forward: layer l only processes the node/edge
  prefix its depth needs (a depth-d node's layer-l state matters only
  when d <= L - l), so a [15,10,5] batch computes ~938k + 170k + 16k
  node-rows instead of 3 x 938k — device-trace-measured 2.4x on the
  products-scale train step (PERF.md). Requires dedup='tree' batches
  (positional layout).
  """
  hidden_dim: int
  out_dim: int
  num_layers: int = 3
  dropout: float = 0.0
  aggr: str = 'mean'
  hop_node_offsets: Any = None
  hop_edge_offsets: Any = None
  dtype: Any = None
  # tree_dense: aggregate via TreeSAGEConv's reshape path (no gathers or
  # segment scatters; requires un-truncated tree batches + aggr='mean'
  # + the true `fanouts`, which guard against node_budget truncation)
  tree_dense: bool = False
  # merge_dense: blocked aggregation over exact-dedup (merge-layout)
  # batches via MergeSAGEConv — k-constant target runs per hop replace
  # the segment scatter-add (requires merge_hop_offsets + fanouts +
  # aggr='mean'; exact incl. calibrated frontier caps)
  merge_dense: bool = False
  fanouts: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask, train: bool = False,
               layers=None):
    layered = self.hop_node_offsets is not None
    if layers is not None:
      # layer slice (serving tier): run only conv layers [lo, hi) of the
      # SAME forward definition — the full-graph materializer and the
      # final-layer refresh call this, so trained and served models can
      # never drift (models.train.make_layer_slice_fn). Slices keep the
      # full-width segment path: the layered/dense forwards are batch-
      # layout optimizations that have no meaning on full-graph blocks.
      assert not layered and not self.tree_dense and not self.merge_dense, (
          'layer slices run the plain segment forward — build the '
          'serving model without hop offsets / dense flags')
      lo, hi = layers
      assert 0 <= lo <= hi <= self.num_layers, (layers, self.num_layers)
    if self.tree_dense:
      assert layered, 'tree_dense requires hop_node/edge_offsets'
      assert self.aggr == 'mean', 'tree_dense implements mean aggregation'
      assert self.fanouts is not None, (
          'tree_dense requires fanouts=... (the loader fanouts) so a '
          'node_budget-truncated layout cannot slip through the layout '
          'check')
    if self.merge_dense:
      assert layered and not self.tree_dense, (
          'merge_dense requires hop offsets (merge_hop_offsets) and is '
          'mutually exclusive with tree_dense')
      assert self.aggr == 'mean', 'merge_dense implements mean aggregation'
      assert self.fanouts is not None, (
          'merge_dense requires fanouts=... (the loader fanouts: the '
          'per-hop k-run lengths of the merge edge layout)')
    if layered:
      assert len(self.hop_node_offsets) >= self.num_layers + 1 and \
          len(self.hop_edge_offsets) >= self.num_layers
      # trace-time layout check: a mismatched batch (different
      # batch_size/fanouts, or a non-tree dedup mode) would slice wrong
      # blocks SILENTLY — jnp never errors on oversized slices
      assert self.hop_node_offsets[self.num_layers] == x.shape[0], (
          f'layered forward: hop offsets {self.hop_node_offsets} do not '
          f'match the batch node buffer ({x.shape[0]}); build them from '
          'the SAME batch_size/fanouts/node_budget as the loader — '
          'models.train.tree_hop_offsets for tree batches, '
          'merge_hop_offsets for exact-dedup batches')
    for i in range(self.num_layers):
      if layers is not None and not (layers[0] <= i < layers[1]):
        continue   # homo convs carry explicit names (conv{i}): safe skip
      dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
      if layered:
        hops_used = self.num_layers - i
        n_in = self.hop_node_offsets[hops_used]
        e_used = self.hop_edge_offsets[hops_used - 1]
        # deepest-block rows are pure child input — no consumer reads
        # their conv output, so the dense convs only produce the next
        # layer's prefix (layer 0 skips ~80% of its matmul rows at
        # products scale). The LAST layer keeps full width: its output
        # is the public logits buffer (consumers slice by label cap).
        out_rows = (self.hop_node_offsets[hops_used - 1]
                    if i < self.num_layers - 1 else None)
        if self.tree_dense:
          x = TreeSAGEConv(
              dim, node_offsets=tuple(self.hop_node_offsets[:hops_used + 1]),
              fanouts=tuple(self.fanouts[:hops_used]),
              dtype=self.dtype, out_rows=out_rows, name=f'conv{i}')(
              x[:n_in], edge_mask[:e_used])
        elif self.merge_dense:
          x = MergeSAGEConv(
              dim, edge_offsets=tuple(self.hop_edge_offsets[:hops_used]),
              fanouts=tuple(self.fanouts[:hops_used]),
              dtype=self.dtype, out_rows=out_rows, name=f'conv{i}')(
              x[:n_in], edge_index[:, :e_used], edge_mask[:e_used])
        else:
          x = SAGEConv(dim, aggr=self.aggr, dtype=self.dtype,
                       name=f'conv{i}')(
              x[:n_in], edge_index[:, :e_used], edge_mask[:e_used])
      else:
        x = SAGEConv(dim, aggr=self.aggr, dtype=self.dtype,
                     name=f'conv{i}')(x, edge_index, edge_mask)
      if i < self.num_layers - 1:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x


class GCN(nn.Module):
  hidden_dim: int
  out_dim: int
  num_layers: int = 2
  dropout: float = 0.0
  dtype: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask, train: bool = False,
               layers=None):
    for i in range(self.num_layers):
      if layers is not None and not (layers[0] <= i < layers[1]):
        continue   # layer slice (see GraphSAGE): explicit conv{i} names
      dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
      x = GCNConv(dim, dtype=self.dtype, name=f'conv{i}')(
          x, edge_index, edge_mask)
      if i < self.num_layers - 1:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x


class GAT(nn.Module):
  """Multi-head GAT stack; like GraphSAGE, tree-mode batches unlock the
  layered forward (``hop_node_offsets``/``hop_edge_offsets``) and the
  dense per-parent attention (``tree_dense=True`` + ``fanouts``)."""
  hidden_dim: int
  out_dim: int
  num_layers: int = 2
  heads: int = 4
  dropout: float = 0.0
  dtype: Any = None
  hop_node_offsets: Any = None
  hop_edge_offsets: Any = None
  tree_dense: bool = False
  # merge_dense: per-target k-run softmax on exact-dedup batches
  # (MergeGATConv; requires merge_hop_offsets + fanouts)
  merge_dense: bool = False
  fanouts: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask, train: bool = False,
               layers=None):
    layered = self.hop_node_offsets is not None
    if layers is not None:
      # layer slice (see GraphSAGE): serving's full-graph blocks run the
      # plain segment forward only
      assert not layered and not self.tree_dense and not self.merge_dense, (
          'layer slices run the plain segment forward — build the '
          'serving model without hop offsets / dense flags')
      assert 0 <= layers[0] <= layers[1] <= self.num_layers
    if self.tree_dense:
      assert layered and self.fanouts is not None, (
          'tree_dense GAT requires hop offsets + the true fanouts')
    if self.merge_dense:
      assert layered and not self.tree_dense and           self.fanouts is not None, (
              'merge_dense GAT requires merge hop offsets + fanouts and '
              'is mutually exclusive with tree_dense')
    if layered:
      # trace-time layout check (see GraphSAGE): jnp never errors on
      # oversized slices, so a mismatched batch would slice garbage
      assert len(self.hop_node_offsets) >= self.num_layers + 1 and \
          len(self.hop_edge_offsets) >= self.num_layers
      assert self.hop_node_offsets[self.num_layers] == x.shape[0], (
          f'layered GAT: hop offsets {self.hop_node_offsets} do not '
          f'match the batch node buffer ({x.shape[0]}); build them from '
          'the SAME batch_size/fanouts as the loader — '
          'models.train.tree_hop_offsets for tree batches, '
          'merge_hop_offsets for exact-dedup batches')
    for i in range(self.num_layers):
      if layers is not None and not (layers[0] <= i < layers[1]):
        continue   # explicit conv{i} names: safe skip
      last = i == self.num_layers - 1
      dim = self.out_dim if last else self.hidden_dim
      heads = 1 if last else self.heads
      if layered:
        hops_used = self.num_layers - i
        n_in = self.hop_node_offsets[hops_used]
        e_used = self.hop_edge_offsets[hops_used - 1]
        if self.tree_dense:
          x = TreeGATConv(
              dim, node_offsets=tuple(self.hop_node_offsets[:hops_used + 1]),
              fanouts=tuple(self.fanouts[:hops_used]), heads=heads,
              concat=not last, dtype=self.dtype, name=f'conv{i}')(
              x[:n_in], edge_mask[:e_used])
        elif self.merge_dense:
          x = MergeGATConv(
              dim, edge_offsets=tuple(self.hop_edge_offsets[:hops_used]),
              fanouts=tuple(self.fanouts[:hops_used]), heads=heads,
              concat=not last, dtype=self.dtype, name=f'conv{i}')(
              x[:n_in], edge_index[:, :e_used], edge_mask[:e_used])
        else:
          x = GATConv(dim, heads=heads, concat=not last,
                      dtype=self.dtype, name=f'conv{i}')(
              x[:n_in], edge_index[:, :e_used], edge_mask[:e_used])
      else:
        x = GATConv(dim, heads=heads, concat=not last,
                    dtype=self.dtype, name=f'conv{i}')(
            x, edge_index, edge_mask)
      if not last:
        x = nn.elu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x


class HeteroConv(nn.Module):
  """Per-edge-type convs summed into per-node-type outputs
  (RGNN layer; reference examples/igbh/rgnn.py).

  ``convs`` maps EdgeType -> nn.Module; a dict passed in is stored as
  (etype, conv) pairs (flax forbids tuple dict keys on Module fields —
  see freeze_etype_items)."""
  convs: Any  # {EdgeType: nn.Module} or ((EdgeType, nn.Module), ...)

  def __post_init__(self):
    object.__setattr__(self, 'convs', freeze_etype_items(self.convs))
    super().__post_init__()

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict):
    out: Dict[NodeType, Any] = {}
    for et, conv in self.convs:
      src_t, _, dst_t = et
      if et not in edge_index_dict or src_t not in x_dict:
        continue
      if dst_t not in x_dict:
        continue
      # bipartite message passing: messages flow src_t -> dst_t; convs
      # consume a single x so we splice src features into a combined view
      ei = edge_index_dict[et]
      em = edge_mask_dict[et]
      n_dst = x_dict[dst_t].shape[0]
      n_src = x_dict[src_t].shape[0]
      x_cat = jnp.concatenate([x_dict[dst_t], x_dict[src_t]], axis=0)
      row = jnp.where(ei[0] >= 0, ei[0] + n_dst, -1)
      ei2 = jnp.stack([row, ei[1]])
      h = conv(x_cat, ei2, em)[:n_dst]
      out[dst_t] = out.get(dst_t, 0) + h
    return out


def walk_hetero_records(recs, edge_mask_dict, r_out, per_record):
  """Shared parent-coverage walk over hetero tree records (consumed by
  TreeHeteroConv and the dense HGTConv path): for each hop record,
  slice the edge-mask segment, emit ``per_record(r, m)`` ([f, ...]
  values), and track coverage of the key type's parent axis — etypes
  inactive at an earlier hop leave ('gap', n) placeholders
  ``resolve_hetero_parts`` fills with zeros."""
  parts, covered = [], 0
  for r in recs:
    if r['parent_base'] >= r_out:
      break
    f, k = r['fcap'], r['k']
    m = jax.lax.slice_in_dim(edge_mask_dict[r['out_et']],
                             r['edge_base'], r['edge_base'] + f * k
                             ).reshape(f, k)
    if r['parent_base'] > covered:
      parts.append(('gap', r['parent_base'] - covered))
      covered = r['parent_base']
    assert r['parent_base'] == covered, (
        f'hetero tree records for {recs[0]["et"]} overlap parents '
        f'({r["parent_base"]} vs {covered}); build them with '
        'sampler.hetero_tree_blocks from the SAME seed caps/fanouts '
        'as the loader')
    parts.append(per_record(r, m))
    covered += f
  if covered < r_out:
    parts.append(('gap', r_out - covered))
  return parts


def resolve_hetero_parts(parts, feat_shape, dtype):
  """Replace ('gap', n) placeholders with zeros of [n, *feat_shape] and
  concatenate along the parent axis. Empty walks (a target type with a
  zero-width output prefix, e.g. a non-seed type at the last layer)
  resolve to a [0, ...] array."""
  if not parts:
    return jnp.zeros((0,) + tuple(feat_shape), dtype)
  parts = [jnp.zeros((p[1],) + tuple(feat_shape), dtype)
           if isinstance(p, tuple) else p for p in parts]
  return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


class TreeHeteroConv(nn.Module):
  """One hetero layer over TYPED tree batches with dense k-run
  aggregation — the typed counterpart of TreeSAGEConv/TreeGATConv.

  The hetero tree layout (sampler.hetero_tree_blocks) puts each
  (hop, edge-type)'s children in a CONTIGUOUS block of the result
  type's buffer, their targets in the key type's contiguous frontier
  block, and the edges in the out-etype's hop segment — so per-etype
  aggregation is slice + reshape + masked mean (or masked run softmax),
  with NO per-edge gathers, no segment scatters, and no src/dst buffer
  concatenation (HeteroConv materializes [n_dst+n_src, F] per etype per
  layer). Semantics match HeteroConv over per-etype SAGEConv/GATConv
  (per-etype lin_self/lin_nbr or lin/att params, summed per target
  type) — equivalence-tested on tree batches.

  ``records``: hop records from sampler.hetero_tree_blocks, restricted
  by the caller to the hops this layer consumes. ``out_rows``: per-type
  output widths (the NEXT layer's typed prefix; deepest blocks are pure
  child input — the homo out_rows argument, per type).

  ``mode='merge'``: the same dense k-run aggregation over CALIBRATED
  exact-dedup (merge) hetero batches — records from
  ``hetero_tree_blocks(etype_caps=...)``. Clamped merge states pack
  nodes by DYNAMIC valid counts, so nothing is positional: children
  are gathered through the edge rows and each record's parent run
  block lands at a dynamically computed base (``min(tgt - j)``, the
  MergeSAGEConv pattern) via a read-modify-write slice on the
  accumulator; requires ``edge_index_dict``. Valid runs stay
  arithmetic because the clamped engine re-compacts per-type frontiers
  across etype parts each hop.
  """
  out_dim: int
  records: Any                    # tuple of per-hop record tuples
  conv: str = 'sage'              # 'sage' | 'gat'
  heads: int = 1
  negative_slope: float = 0.2
  concat: bool = True             # gat: concat heads
  dtype: Any = None
  out_rows: Any = None            # {ntype: rows} or None = input widths
  mode: str = 'tree'              # 'tree' | 'merge'

  @nn.compact
  def __call__(self, x_dict, edge_mask_dict, edge_index_dict=None):
    assert self.mode in ('tree', 'merge')
    if self.mode == 'merge':
      assert edge_index_dict is not None, (
          "TreeHeteroConv(mode='merge') gathers children through the "
          'edge rows — pass edge_index_dict')
    if self.dtype is not None:
      x_dict = {t: x.astype(self.dtype) for t, x in x_dict.items()}
    rows = {t: (x.shape[0] if self.out_rows is None
                else min(int(self.out_rows[t]), x.shape[0]))
            for t, x in x_dict.items()}
    etypes = sorted({r['et'] for recs in self.records for r in recs})
    out = {}
    for et in etypes:
      if self.mode == 'merge':
        fn = (self._gat_et_merge if self.conv == 'gat'
              else self._sage_et_merge)
        h = fn(et, x_dict, edge_mask_dict, rows, edge_index_dict)
      else:
        fn = self._gat_et if self.conv == 'gat' else self._sage_et
        h = fn(et, x_dict, edge_mask_dict, rows)
      if h is None:
        continue
      t, val = h
      out[t] = out.get(t, 0) + val
    return out

  # ------------------------------------------------------- merge mode
  @staticmethod
  def _run_layout(r, edge_mask_dict, edge_index_dict, n_out):
    """(mask [f,k], child rows [f*k], run-target base scalar, run-ok
    [f]) of record ``r``'s edge segment. The base is dynamic (clamped
    states pack by valid counts): ``min(tgt - j)`` over valid runs —
    immune to leading all-masked runs (MergeSAGEConv pattern)."""
    f, k = r['fcap'], r['k']
    ei = edge_index_dict[r['out_et']]
    m = jax.lax.slice_in_dim(edge_mask_dict[r['out_et']], r['edge_base'],
                             r['edge_base'] + f * k).reshape(f, k)
    src = jnp.maximum(jax.lax.slice_in_dim(ei[0], r['edge_base'],
                                           r['edge_base'] + f * k), 0)
    tgt = jax.lax.slice_in_dim(ei[1], r['edge_base'],
                               r['edge_base'] + f * k
                               ).reshape(f, k).max(1)
    ok = m.any(1) & (tgt >= 0)
    base = jnp.min(jnp.where(
        ok, tgt - jnp.arange(f, dtype=tgt.dtype), n_out)).astype(
            jnp.int32)
    return m, src, base, ok

  @staticmethod
  def _acc_add(acc, vals, base):
    """acc[base:base+f] += vals via read-modify-write slice (records
    targeting the same type within a hop overlap, so no overwrite)."""
    f = vals.shape[0]
    cur = jax.lax.dynamic_slice_in_dim(acc, base, f)
    return jax.lax.dynamic_update_slice(acc, cur + vals, (base, 0))

  def _sage_et_merge(self, et, x_dict, edge_mask_dict, rows,
                     edge_index_dict):
    ename = '__'.join(et)
    recs = self._et_recs(et, x_dict)
    if not recs:
      return None
    key_t = recs[0]['key_t']
    n_out = rows[key_t]
    x_key = x_dict[key_t]
    agg = jnp.zeros((n_out, x_key.shape[-1]), x_key.dtype)
    for r in recs:
      if r['parent_base'] >= n_out:
        break
      m, src, base, ok = self._run_layout(r, edge_mask_dict,
                                          edge_index_dict, n_out)
      mean = _masked_flat_run_mean(x_dict[r['res_t']][src], m, r['k'])
      agg = self._acc_add(agg, jnp.where(ok[:, None], mean, 0), base)
    return self._sage_out(ename, key_t, x_key, n_out, agg)

  def _gat_et_merge(self, et, x_dict, edge_mask_dict, rows,
                    edge_index_dict):
    ename = '__'.join(et)
    recs = self._et_recs(et, x_dict)
    if not recs:
      return None
    key_t, res_ts = recs[0]['key_t'], {r['res_t'] for r in recs}
    heads, hd = self.heads, self.out_dim
    w, alpha_src, alpha_dst_key = self._gat_setup(ename, key_t, res_ts,
                                                  x_dict)
    n_out = rows[key_t]
    acc = jnp.zeros((n_out, heads * hd), w[key_t].dtype)
    for r in recs:
      if r['parent_base'] >= n_out:
        break
      f, k = r['fcap'], r['k']
      m, src, base, ok = self._run_layout(r, edge_mask_dict,
                                          edge_index_dict, n_out)
      wch = w[r['res_t']][src]
      a_ch = alpha_src[r['res_t']][src]
      # parents are arithmetic from the dynamic base (compacted
      # frontier), so one dynamic slice reads the run alphas
      a_par = jax.lax.dynamic_slice_in_dim(alpha_dst_key, base, f)
      e = a_ch.reshape(f, k, heads) + a_par[:, None, :]
      attn = _masked_run_softmax(e, m, wch.dtype, self.negative_slope)
      msgs = wch.reshape(f, k, heads, hd)
      vals = (msgs * attn[..., None]).sum(axis=1).reshape(f, heads * hd)
      acc = self._acc_add(acc, jnp.where(ok[:, None], vals, 0), base)
    if not self.concat:
      acc = acc.reshape(n_out, heads, hd).mean(axis=1)
    return key_t, acc

  def _et_recs(self, et, x_dict):
    """Records for ``et`` whose types exist in this layer's input —
    leaf-only types (never message targets) drop out of x_dict after
    layer 0, and the segment HeteroConv skips such relations too."""
    return [r for recs in self.records for r in recs if r['et'] == et
            and r['res_t'] in x_dict and r['key_t'] in x_dict]

  def _walk(self, recs, edge_mask_dict, rows, per_record):
    key_t = recs[0]['key_t']
    return walk_hetero_records(recs, edge_mask_dict, rows[key_t],
                               per_record), key_t

  @staticmethod
  def _resolve(parts, fdim, dtype):
    return resolve_hetero_parts(parts, (fdim,), dtype)

  def _sage_out(self, ename, key_t, x_key, n_rows, agg):
    """Shared SAGE tail: self projection on the output prefix + the
    neighbor projection on the aggregated messages (tree and merge
    paths must stay parameter- and semantics-identical)."""
    h = nn.Dense(self.out_dim, dtype=self.dtype,
                 name=f'lin_self_{ename}')(x_key[:n_rows])
    return key_t, h + nn.Dense(self.out_dim, use_bias=False,
                               dtype=self.dtype,
                               name=f'lin_nbr_{ename}')(agg)

  def _gat_setup(self, ename, key_t, res_ts, x_dict):
    """Shared GAT preamble: per-etype attention params, ONE projection
    per participating type (flat rows: PERF.md layout rule), and
    SEPARATE src-/dst-alpha maps — a self-relation (e.g.
    paper-cites-paper) needs BOTH for the same type: children read
    a_src, parents read a_dst. Tree and merge paths must share this
    exactly or the segment-equivalence guarantee diverges."""
    heads, hd = self.heads, self.out_dim
    a_src = self.param(f'att_src_{ename}',
                       nn.initializers.glorot_uniform(), (heads, hd))
    a_dst = self.param(f'att_dst_{ename}',
                       nn.initializers.glorot_uniform(), (heads, hd))
    lin = nn.Dense(heads * hd, use_bias=False, dtype=self.dtype,
                   name=f'lin_{ename}')
    w = {t: lin(x_dict[t]) for t in res_ts | {key_t}}
    alpha_src = {t: jnp.einsum('nhd,hd->nh',
                               w[t].reshape(-1, heads, hd), a_src,
                               preferred_element_type=jnp.float32)
                 for t in res_ts}
    alpha_dst_key = jnp.einsum('nhd,hd->nh',
                               w[key_t].reshape(-1, heads, hd), a_dst,
                               preferred_element_type=jnp.float32)
    return w, alpha_src, alpha_dst_key

  def _sage_et(self, et, x_dict, edge_mask_dict, rows):
    ename = '__'.join(et)
    recs = self._et_recs(et, x_dict)
    if not recs:
      return None

    def per_record(r, m):
      ch = jax.lax.slice_in_dim(x_dict[r['res_t']], r['child_base'],
                                r['child_base'] + r['fcap'] * r['k'])
      return _masked_flat_run_mean(ch, m, r['k'])

    parts, key_t = self._walk(recs, edge_mask_dict, rows, per_record)
    x_key = x_dict[key_t]
    agg_all = self._resolve(parts, x_key.shape[-1], x_key.dtype)
    return self._sage_out(ename, key_t, x_key, rows[key_t], agg_all)

  def _gat_et(self, et, x_dict, edge_mask_dict, rows):
    ename = '__'.join(et)
    recs = self._et_recs(et, x_dict)
    if not recs:
      return None
    key_t, res_ts = recs[0]['key_t'], {r['res_t'] for r in recs}
    heads, hd = self.heads, self.out_dim
    w, alpha_src, alpha_dst_key = self._gat_setup(ename, key_t, res_ts,
                                                  x_dict)

    def per_record(r, m):
      f, k = r['fcap'], r['k']
      wch = jax.lax.slice_in_dim(w[r['res_t']], r['child_base'],
                                 r['child_base'] + f * k)
      e = (jax.lax.slice_in_dim(alpha_src[r['res_t']], r['child_base'],
                                r['child_base'] + f * k
                                ).reshape(f, k, heads) +
           jax.lax.slice_in_dim(alpha_dst_key, r['parent_base'],
                                r['parent_base'] + f)[:, None, :])
      attn = _masked_run_softmax(e, m, wch.dtype, self.negative_slope)
      msgs = wch.reshape(f, k, heads, hd)
      return (msgs * attn[..., None]).sum(axis=1).reshape(f, heads * hd)

    parts, key_t = self._walk(recs, edge_mask_dict, rows, per_record)
    outv = self._resolve(parts, heads * hd, w[key_t].dtype)
    if not self.concat:
      outv = outv.reshape(rows[key_t], heads, hd).mean(axis=1)
    return key_t, outv

class RGNN(nn.Module):
  """Hetero GNN: embeds each node type, stacks HeteroConv layers
  (reference examples/igbh/rgnn.py RGNN with sage/gat convs).

  ``hop_node_offsets`` ({ntype: (o_0..o_H)}) / ``hop_edge_offsets``
  ({etype: (e_1..e_H)}) — from ``sampler.hetero_tree_layout`` with the
  SAME seed caps/fanouts as the loader — enable the HIERARCHICAL forward
  over hetero tree-mode batches: layer l only processes the typed
  node/edge prefixes its depth needs, the typed counterpart of the
  reference's trim_to_layer hierarchical model
  (examples/hetero/hierarchical_sage.py:35-66) and of this framework's
  layered GraphSAGE. Requires dedup='tree' batches.
  """
  etypes: Sequence[EdgeType]
  hidden_dim: int
  out_dim: int
  num_layers: int = 2
  conv: str = 'sage'
  heads: int = 1     # conv='gat': attention heads (reference igbh: 4)
  out_ntype: NodeType = None
  dtype: Any = None
  hop_node_offsets: Any = None
  hop_edge_offsets: Any = None
  # tree_dense: typed dense k-run aggregation over the hetero tree
  # layout (TreeHeteroConv) — no per-edge gathers, segment scatters, or
  # src/dst buffer concatenations. Requires ``tree_records`` from
  # sampler.hetero_tree_blocks built with the SAME seed caps/fanouts as
  # the loader. NOTE: records name STORED etypes; ``etypes`` here stays
  # the message-direction (reversed) types for param parity.
  tree_dense: bool = False
  tree_records: Any = None
  # merge_dense: the dense k-run aggregation over CALIBRATED exact-dedup
  # hetero batches (TreeHeteroConv mode='merge') — records AND offsets
  # must come from hetero_tree_blocks(etype_caps=caps) with the SAME
  # caps as the loader's frontier_caps dict. Requires dedup='merge'.
  merge_dense: bool = False

  def __post_init__(self):
    # EdgeType-keyed dicts cannot live on Module fields (flax >= 0.10
    # asserts string dict keys); store as pair tuples, thaw at call time
    object.__setattr__(self, 'hop_edge_offsets',
                       freeze_etype_items(self.hop_edge_offsets))
    super().__post_init__()

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict,
               train: bool = False, layers=None, embed: bool = True,
               head=None):
    hier = self.hop_node_offsets is not None
    hop_edge_offsets = thaw_etype_items(self.hop_edge_offsets)
    assert not (self.tree_dense and self.merge_dense)
    if layers is not None:
      # layer slice (serving tier; see GraphSAGE): conv layers [lo, hi)
      # of the SAME forward definition. ``embed`` gates the per-type
      # input Dense (the materializer runs it as its own row-local
      # pass), ``head`` gates the final lin_out (None = the full
      # forward's out_ntype behavior). Skipped layers still CONSTRUCT
      # their conv modules: the per-etype convs are auto-named in
      # construction order (SAGEConv_0, ...), so skipping construction
      # would silently rebind a later layer onto an earlier layer's
      # params — flax assigns names at construction, not call
      # (tests/test_serving.py pins the slice-vs-full parity).
      assert not hier and not self.tree_dense and not self.merge_dense, (
          'layer slices run the plain segment forward — build the '
          'serving model without hop offsets / dense flags')
      assert 0 <= layers[0] <= layers[1] <= self.num_layers
    if self.tree_dense or self.merge_dense:
      assert hier and self.tree_records is not None, (
          'RGNN dense paths require hop offsets + tree_records '
          '(sampler.hetero_tree_blocks)')
    if hier:
      check_hetero_offsets(x_dict, edge_index_dict,
                           self.hop_node_offsets, hop_edge_offsets,
                           self.num_layers)
    if embed:
      x_dict = {t: nn.Dense(self.hidden_dim, dtype=self.dtype,
                            name=f'embed_{t}')(x)
                for t, x in x_dict.items()}
    # reference structure (examples/igbh/rgnn.py:37-56): with a predict
    # type, every conv layer keeps hidden_dim and a final Linear maps
    # to out_dim; GAT uses dim // heads per head with concat on EVERY
    # layer, so the width stays dim
    lin_out = self.out_ntype is not None
    for i in range(self.num_layers):
      last = i == self.num_layers - 1
      dim = self.hidden_dim if (lin_out or not last) else self.out_dim
      if self.conv == 'gat':
        assert dim % self.heads == 0, (
            f'GAT layer width {dim} must be divisible by '
            f'heads={self.heads} (reference parity: per-head dim = '
            'width // heads)')
        conv_dim = dim // self.heads
      else:
        conv_dim = dim
      if hier:
        hops_used = self.num_layers - i
        x_in, ei, em = hetero_trim(
            x_dict, edge_index_dict, edge_mask_dict,
            self.hop_node_offsets, hop_edge_offsets, hops_used)
      else:
        x_in, ei, em = x_dict, edge_index_dict, edge_mask_dict
      if self.tree_dense or self.merge_dense:
        # output widths: the next layer's typed prefixes (the deepest
        # typed blocks are pure child input — homo out_rows, per type)
        out_rows = {t: self.hop_node_offsets[t][hops_used - 1]
                    for t in x_in}
        mode = 'merge' if self.merge_dense else 'tree'
        x_dict = TreeHeteroConv(
            conv_dim, records=self.tree_records[:hops_used],
            conv=self.conv, heads=self.heads, concat=True,
            dtype=self.dtype, out_rows=out_rows, mode=mode,
            name=f'hetero{i}')(x_in, em,
                               ei if mode == 'merge' else None)
      else:
        # constructed even for layers a slice skips: construction order
        # assigns the per-etype convs' auto-names (see the layers note
        # above) — only the CALL is skipped
        convs = {tuple(et): SAGEConv(conv_dim, dtype=self.dtype)
                 if self.conv == 'sage'
                 else GATConv(conv_dim, heads=self.heads, concat=True,
                              dtype=self.dtype)
                 for et in self.etypes}
        if layers is not None and not (layers[0] <= i < layers[1]):
          continue
        x_dict = HeteroConv(convs, name=f'hetero{i}')(x_in, ei, em)
      if not last:
        x_dict = {t: nn.relu(v) for t, v in x_dict.items()}
    if head is None:
      head = lin_out
    if head:
      assert lin_out, 'head=True requires out_ntype'
      return nn.Dense(self.out_dim, dtype=self.dtype,
                      name='lin_out')(x_dict[self.out_ntype])
    return x_dict
