"""Model stacks: GraphSAGE / GCN / GAT and a hetero (RGNN-style) wrapper.

Counterparts of the reference's example models
(/root/reference/examples/train_sage_ogbn_products.py SAGE stack,
examples/igbh/rgnn.py RGNN) implemented natively in flax over the padded
batch format. `HeteroConv` aggregates per-edge-type messages into per-node-
type embeddings (sum across relations), mirroring rgnn.py's HeteroConv use.
"""
from typing import Any, Dict, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..typing import EdgeType, NodeType
from .conv import GATConv, GCNConv, SAGEConv

_CONVS = {'sage': SAGEConv, 'gcn': GCNConv, 'gat': GATConv}


class GraphSAGE(nn.Module):
  """Multi-layer GraphSAGE (reference example: 3 layers, hidden 256)."""
  hidden_dim: int
  out_dim: int
  num_layers: int = 3
  dropout: float = 0.0
  aggr: str = 'mean'

  @nn.compact
  def __call__(self, x, edge_index, edge_mask, train: bool = False):
    for i in range(self.num_layers):
      dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
      x = SAGEConv(dim, aggr=self.aggr, name=f'conv{i}')(
          x, edge_index, edge_mask)
      if i < self.num_layers - 1:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x


class GCN(nn.Module):
  hidden_dim: int
  out_dim: int
  num_layers: int = 2
  dropout: float = 0.0

  @nn.compact
  def __call__(self, x, edge_index, edge_mask, train: bool = False):
    for i in range(self.num_layers):
      dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
      x = GCNConv(dim, name=f'conv{i}')(x, edge_index, edge_mask)
      if i < self.num_layers - 1:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x


class GAT(nn.Module):
  hidden_dim: int
  out_dim: int
  num_layers: int = 2
  heads: int = 4
  dropout: float = 0.0

  @nn.compact
  def __call__(self, x, edge_index, edge_mask, train: bool = False):
    for i in range(self.num_layers):
      last = i == self.num_layers - 1
      x = GATConv(self.out_dim if last else self.hidden_dim,
                  heads=1 if last else self.heads, concat=not last,
                  name=f'conv{i}')(x, edge_index, edge_mask)
      if not last:
        x = nn.elu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x


class HeteroConv(nn.Module):
  """Per-edge-type convs summed into per-node-type outputs
  (RGNN layer; reference examples/igbh/rgnn.py)."""
  convs: Dict[EdgeType, Any]  # EdgeType -> nn.Module instance

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict):
    out: Dict[NodeType, Any] = {}
    for et, conv in self.convs.items():
      src_t, _, dst_t = et
      if et not in edge_index_dict or src_t not in x_dict:
        continue
      if dst_t not in x_dict:
        continue
      # bipartite message passing: messages flow src_t -> dst_t; convs
      # consume a single x so we splice src features into a combined view
      ei = edge_index_dict[et]
      em = edge_mask_dict[et]
      n_dst = x_dict[dst_t].shape[0]
      n_src = x_dict[src_t].shape[0]
      x_cat = jnp.concatenate([x_dict[dst_t], x_dict[src_t]], axis=0)
      row = jnp.where(ei[0] >= 0, ei[0] + n_dst, -1)
      ei2 = jnp.stack([row, ei[1]])
      h = conv(x_cat, ei2, em)[:n_dst]
      out[dst_t] = out.get(dst_t, 0) + h
    return out


class RGNN(nn.Module):
  """Hetero GNN: embeds each node type, stacks HeteroConv layers
  (reference examples/igbh/rgnn.py RGNN with sage/gat convs)."""
  etypes: Sequence[EdgeType]
  hidden_dim: int
  out_dim: int
  num_layers: int = 2
  conv: str = 'sage'
  out_ntype: NodeType = None

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict,
               train: bool = False):
    x_dict = {t: nn.Dense(self.hidden_dim, name=f'embed_{t}')(x)
              for t, x in x_dict.items()}
    for i in range(self.num_layers):
      last = i == self.num_layers - 1
      dim = self.out_dim if last else self.hidden_dim
      convs = {tuple(et): SAGEConv(dim) if self.conv == 'sage'
               else GATConv(dim)
               for et in self.etypes}
      x_dict = HeteroConv(convs, name=f'hetero{i}')(
          x_dict, edge_index_dict, edge_mask_dict)
      if not last:
        x_dict = {t: nn.relu(v) for t, v in x_dict.items()}
    return x_dict if self.out_ntype is None else x_dict[self.out_ntype]
