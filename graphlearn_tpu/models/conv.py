"""Masked message-passing convolutions over padded COO batches.

The reference framework stops at producing PyG batches and leaves models to
torch_geometric (SURVEY.md §1; /root/reference/README.md:102-111's SAGEConv
examples). A TPU framework needs native models: these flax convs consume the
fixed-shape `Data` batches (edge_index [2, E] with -1 padding, row=message
source, col=target) and aggregate via `jax.ops.segment_*` — XLA lowers the
segment ops to efficient scatter-adds, and the masked-padding design means
one compile for the whole epoch. Feature matmuls are [N, F] x [F, H] dense —
MXU-shaped; keep hidden dims multiples of 128 for best tiling.
"""
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def _masked_targets(col, edge_mask, num_nodes: int):
  """Padded/invalid edges scatter into segment `num_nodes` (dropped)."""
  return jnp.where(edge_mask & (col >= 0), col, num_nodes)


def segment_mean_agg(msgs, col, edge_mask, num_nodes: int):
  """Mean-aggregate edge messages at their target nodes."""
  tgt = _masked_targets(col, edge_mask, num_nodes)
  summed = jax.ops.segment_sum(msgs, tgt, num_segments=num_nodes + 1)
  # counts in f32 (exact for any degree), divide in the message dtype
  count = jax.ops.segment_sum(jnp.ones_like(tgt, jnp.float32), tgt,
                              num_segments=num_nodes + 1)
  inv = (1.0 / jnp.maximum(count[:num_nodes, None], 1.0)).astype(msgs.dtype)
  return summed[:num_nodes] * inv


def segment_sum_agg(msgs, col, edge_mask, num_nodes: int):
  tgt = _masked_targets(col, edge_mask, num_nodes)
  return jax.ops.segment_sum(msgs, tgt, num_segments=num_nodes + 1
                             )[:num_nodes]


def segment_max_agg(msgs, col, edge_mask, num_nodes: int):
  tgt = _masked_targets(col, edge_mask, num_nodes)
  out = jax.ops.segment_max(msgs, tgt, num_segments=num_nodes + 1)
  out = jnp.where(jnp.isfinite(out), out, 0.0)
  return out[:num_nodes]


_AGGS = {'mean': segment_mean_agg, 'sum': segment_sum_agg,
         'max': segment_max_agg}


class SAGEConv(nn.Module):
  """GraphSAGE conv: W_self x_v + W_nbr agg_{u->v} x_u.

  ``dtype`` selects the compute dtype (``jnp.bfloat16`` runs the matmuls
  and aggregation on the MXU at twice the f32 rate; params stay f32).
  """
  out_dim: int
  aggr: str = 'mean'
  use_bias: bool = True
  dtype: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    row, col = edge_index[0], edge_index[1]
    src = jnp.where((row >= 0)[:, None], x[jnp.maximum(row, 0)],
                    jnp.zeros((), x.dtype))
    agg = _AGGS[self.aggr](src, col, edge_mask, n)
    h = nn.Dense(self.out_dim, use_bias=self.use_bias, dtype=self.dtype,
                 name='lin_self')(x)
    h = h + nn.Dense(self.out_dim, use_bias=False, dtype=self.dtype,
                     name='lin_nbr')(agg)
    return h


class GCNConv(nn.Module):
  """GCN conv with symmetric degree normalization + implicit self loops."""
  out_dim: int
  use_bias: bool = True
  dtype: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    row, col = edge_index[0], edge_index[1]
    tgt = _masked_targets(col, edge_mask, n)
    srcseg = _masked_targets(row, edge_mask, n)
    # degree norms in f32 regardless of compute dtype (rsqrt of counts)
    ones = jnp.ones_like(tgt, jnp.float32)
    # degrees including the self loop
    deg_in = jax.ops.segment_sum(ones, tgt, num_segments=n + 1)[:n] + 1.0
    deg_out = jax.ops.segment_sum(ones, srcseg, num_segments=n + 1)[:n] + 1.0
    h = nn.Dense(self.out_dim, use_bias=self.use_bias, dtype=self.dtype)(x)
    inv_src = (1.0 / jnp.sqrt(deg_out))[jnp.maximum(row, 0)]
    inv_dst_e = (1.0 / jnp.sqrt(deg_in))[jnp.maximum(col, 0)]
    norm = (inv_src * inv_dst_e).astype(h.dtype)
    msgs = h[jnp.maximum(row, 0)] * norm[:, None]
    agg = jax.ops.segment_sum(
        jnp.where(edge_mask[:, None], msgs, jnp.zeros((), h.dtype)), tgt,
        num_segments=n + 1)[:n]
    # self loop term (1/sqrt(d)^2)
    return agg + h * (1.0 / deg_in[:, None]).astype(h.dtype)


class GATConv(nn.Module):
  """Graph attention conv (multi-head, masked segment softmax)."""
  out_dim: int
  heads: int = 1
  negative_slope: float = 0.2
  concat: bool = True
  dtype: Any = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask):
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    h_dim = self.out_dim
    row, col = edge_index[0], edge_index[1]
    safe_row, safe_col = jnp.maximum(row, 0), jnp.maximum(col, 0)
    w = nn.Dense(self.heads * h_dim, use_bias=False, dtype=self.dtype,
                 name='lin')(x)
    w = w.reshape(n, self.heads, h_dim)
    a_src = self.param('att_src', nn.initializers.glorot_uniform(),
                       (self.heads, h_dim))
    a_dst = self.param('att_dst', nn.initializers.glorot_uniform(),
                       (self.heads, h_dim))
    # attention logits/softmax in f32 for stability; messages in dtype
    wf = w.astype(jnp.float32)
    alpha_src = (wf * a_src[None]).sum(-1)  # [N, H]
    alpha_dst = (wf * a_dst[None]).sum(-1)
    e = alpha_src[safe_row] + alpha_dst[safe_col]  # [E, H]
    e = nn.leaky_relu(e, self.negative_slope)
    tgt = _masked_targets(col, edge_mask, n)
    # segment softmax: subtract per-target max for stability
    seg_max = jax.ops.segment_max(e, tgt, num_segments=n + 1)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    e = jnp.exp(e - seg_max[tgt])
    e = jnp.where(edge_mask[:, None], e, 0.0)
    denom = jax.ops.segment_sum(e, tgt, num_segments=n + 1)
    attn = (e / jnp.maximum(denom[tgt], 1e-9)).astype(w.dtype)
    msgs = w[safe_row] * attn[:, :, None]           # [E, H, D]
    out = jax.ops.segment_sum(
        jnp.where(edge_mask[:, None, None], msgs, jnp.zeros((), w.dtype)),
        tgt, num_segments=n + 1)[:n]
    if self.concat:
      return out.reshape(n, self.heads * h_dim)
    return out.mean(axis=1)
