"""Heterogeneous Graph Transformer (HGT): typed-attention conv + stack.

Native flax counterpart of the PyG ``HGTConv`` the reference uses in
/root/reference/examples/hetero/train_hgt_mag.py:28-50 (hidden/out dims,
``group='sum'`` relation aggregation). Semantics follow the HGT design:

- per NODE TYPE projections K/Q/V (+ the output projection A and a
  learnable gated residual);
- per EDGE TYPE relation matrices W_att/W_msg ([H, D, D]) and a prior
  scalar per head;
- attention = segment softmax over each destination node's incoming
  edges, computed per edge type (PyG's per-relation propagate), relation
  outputs summed at the destination (``group='sum'``).

Consumes the framework's padded hetero batches (x/edge_index/edge_mask
dicts keyed by message-flow edge types, -1 = padding) so one compile
serves every batch. Attention logits/softmax run in f32 even under
``dtype=bfloat16`` (stability); projections and messages use ``dtype``.
"""
import math
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..typing import EdgeType, NodeType


def _etype_name(et) -> str:
  return '__'.join(et)


class HGTConv(nn.Module):
  """One HGT layer over padded hetero batches.

  ``metadata`` = (node_types, edge_types) in message-flow orientation —
  the same keys the hetero loaders emit (PyG metadata() equivalent).
  """
  out_dim: int
  metadata: Tuple[Sequence[NodeType], Sequence[EdgeType]]
  heads: int = 4
  dtype: Any = None
  # per-type input widths for types ABSENT from a batch: lets the dummy
  # param materialization (below) match the real kernel shapes when
  # in-dims differ from out_dim. Inside the HGT stack every conv input
  # is hidden_dim == out_dim, so the default suffices there.
  in_dims: Any = None
  # tree_records (sampler.hetero_tree_blocks, restricted to this
  # layer's hops): dense k-run attention over typed tree batches — a
  # parent's in-edges per etype ARE its contiguous k-run, so the
  # segment softmax becomes a masked run softmax with dense slices
  # (same params either way; equivalence-tested). out_rows: per-type
  # output prefix widths (the consumer's typed prefixes).
  tree_records: Any = None
  out_rows: Any = None
  # merge=True: the records came from a CALIBRATED exact-dedup layout
  # (hetero_tree_blocks(etype_caps=...)): children are gathered through
  # the edge rows and run blocks land at dynamic bases, exactly like
  # models.TreeHeteroConv mode='merge' (clamped merge states pack by
  # dynamic valid counts — nothing is positional).
  merge: bool = False

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict):
    assert self.out_dim % self.heads == 0, \
        f'heads ({self.heads}) must divide out_dim ({self.out_dim})'
    heads, d = self.heads, self.out_dim // self.heads
    ntypes, etypes = self.metadata

    k = {}
    q = {}
    v = {}
    for t in ntypes:
      if t not in x_dict:
        # absent node type: still materialize its params (k/q/v here,
        # a/skip below) so the param STRUCTURE never depends on batch
        # content — flax requires an identical tree across calls, and a
        # type first seen at apply time would otherwise miss params.
        # Dummy width: in_dims[t] when provided, else out_dim — the HGT
        # stack invariant (conv inputs are the hidden dim). Standalone
        # users whose in-dims differ from out_dim must pass in_dims
        # (or provide every metadata type at init).
        w = (self.in_dims or {}).get(t, self.out_dim)
        dummy = jnp.zeros((1, w), self.dtype or jnp.float32)
        for proj in ('k', 'q', 'v'):
          nn.Dense(self.out_dim, dtype=self.dtype,
                   name=f'{proj}_{t}')(dummy)
        continue
      x = x_dict[t]
      if self.dtype is not None:
        x = x.astype(self.dtype)
      n = x.shape[0]
      k[t] = nn.Dense(self.out_dim, dtype=self.dtype,
                      name=f'k_{t}')(x).reshape(n, heads, d)
      q[t] = nn.Dense(self.out_dim, dtype=self.dtype,
                      name=f'q_{t}')(x).reshape(n, heads, d)
      v[t] = nn.Dense(self.out_dim, dtype=self.dtype,
                      name=f'v_{t}')(x).reshape(n, heads, d)

    cdtype = self.dtype or jnp.result_type(*[x.dtype
                                             for x in x_dict.values()])
    dense = self.tree_records is not None
    rows_out = {t: (k[t].shape[0] if self.out_rows is None
                    else min(int(self.out_rows[t]), k[t].shape[0]))
                for t in k}
    agg = {t: jnp.zeros((rows_out[t] if dense else k[t].shape[0],
                         heads, d), cdtype) for t in k}
    for et in etypes:
      et = tuple(et)
      src_t, _, dst_t = et
      name = _etype_name(et)
      # params exist for every metadata etype regardless of batch content
      # (flax requires identical param structure across calls)
      a_rel = self.param(f'att_{name}', nn.initializers.glorot_uniform(),
                         (heads, d, d))
      m_rel = self.param(f'msg_{name}', nn.initializers.glorot_uniform(),
                         (heads, d, d))
      p_rel = self.param(f'pri_{name}', nn.initializers.ones, (heads,))
      if et not in edge_index_dict or src_t not in k or dst_t not in k:
        continue
      k_rel = jnp.einsum('nhd,hde->nhe', k[src_t],
                         a_rel.astype(k[src_t].dtype))
      v_rel = jnp.einsum('nhd,hde->nhe', v[src_t],
                         m_rel.astype(v[src_t].dtype))
      if dense:
        fn = self._merge_et if self.merge else self._dense_et
        agg[dst_t] = agg[dst_t] + fn(
            et, k_rel, v_rel, q[dst_t], p_rel, edge_index_dict,
            edge_mask_dict, rows_out[dst_t], heads, d, cdtype)
        continue
      ei = edge_index_dict[et]
      em = edge_mask_dict[et]
      row = jnp.maximum(ei[0], 0)
      col = jnp.maximum(ei[1], 0)
      valid = em & (ei[0] >= 0) & (ei[1] >= 0)
      n_dst = k[dst_t].shape[0]
      # attention logits + softmax in f32
      logits = (q[dst_t][col].astype(jnp.float32) *
                k_rel[row].astype(jnp.float32)).sum(-1)
      logits = logits * p_rel[None, :] / math.sqrt(d)     # [E, H]
      tgt = jnp.where(valid, col, n_dst)
      seg_max = jax.ops.segment_max(logits, tgt, num_segments=n_dst + 1)
      seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
      ex = jnp.exp(logits - seg_max[tgt])
      ex = jnp.where(valid[:, None], ex, 0.0)
      denom = jax.ops.segment_sum(ex, tgt, num_segments=n_dst + 1)
      attn = (ex / jnp.maximum(denom[tgt], 1e-9)).astype(v_rel.dtype)
      msgs = v_rel[row] * attn[:, :, None]                # [E, H, D]
      agg[dst_t] = agg[dst_t] + jax.ops.segment_sum(
          jnp.where(valid[:, None, None], msgs, jnp.zeros((), msgs.dtype)),
          tgt, num_segments=n_dst + 1)[:n_dst]

    out = {}
    for t in ntypes:
      if t not in k:
        # absent type: params only (see the k/q/v note above)
        nn.Dense(self.out_dim, dtype=self.dtype, name=f'a_{t}')(
            jnp.zeros((1, self.out_dim), self.dtype or jnp.float32))
        self.param(f'skip_{t}', nn.initializers.ones, ())
        continue
      n = agg[t].shape[0]
      a = nn.Dense(self.out_dim, dtype=self.dtype, name=f'a_{t}')(
          nn.gelu(agg[t].reshape(n, self.out_dim)))
      skip = self.param(f'skip_{t}', nn.initializers.ones, ())
      if x_dict[t].shape[-1] == self.out_dim:
        gate = jax.nn.sigmoid(skip).astype(a.dtype)
        out[t] = gate * a + (1.0 - gate) * x_dict[t][:n].astype(a.dtype)
      else:
        out[t] = a
    return out

  @staticmethod
  def _run_attention(kc, vc, qp, m, p_rel, d, cdtype):
    """Masked k-run typed attention shared by the tree and merge dense
    paths: [f,k,H,D] keys/values vs [f,H,D] parent queries -> [f,H,D]
    (f32 logits, same stabilization as the segment softmax)."""
    logits = (qp[:, None].astype(jnp.float32) *
              kc.astype(jnp.float32)).sum(-1)
    logits = logits * p_rel[None, None, :] / math.sqrt(d)    # [f, k, H]
    logits = jnp.where(m[..., None], logits, -jnp.inf)
    mx = logits.max(axis=1, keepdims=True)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    ex = jnp.where(m[..., None], jnp.exp(logits - mx), 0.0)
    denom = jnp.maximum(ex.sum(axis=1, keepdims=True), 1e-9)
    attn = (ex / denom).astype(cdtype)
    return (vc * attn[..., None]).sum(axis=1)               # [f, H, D]

  def _et_records(self, et):
    return [r for hop in self.tree_records for r in hop
            if r['out_et'] == tuple(et)]

  def _dense_et(self, et, k_rel, v_rel, q_dst, p_rel, edge_index_dict,
                edge_mask_dict, r_out, heads, d, cdtype):
    """Dense k-run attention for one etype over tree records: a
    parent's in-edges per etype are its contiguous k-run, so the
    per-destination softmax is a masked run softmax."""
    del edge_index_dict   # positional layout: children via child_base
    from .models import resolve_hetero_parts, walk_hetero_records
    recs = self._et_records(et)

    def per_record(r, m):
      f, kk = r['fcap'], r['k']
      kc = jax.lax.slice_in_dim(k_rel, r['child_base'],
                                r['child_base'] + f * kk
                                ).reshape(f, kk, heads, d)
      vc = jax.lax.slice_in_dim(v_rel, r['child_base'],
                                r['child_base'] + f * kk
                                ).reshape(f, kk, heads, d)
      qp = jax.lax.slice_in_dim(q_dst, r['parent_base'],
                                r['parent_base'] + f)
      return self._run_attention(kc, vc, qp, m, p_rel, d, cdtype)

    parts = walk_hetero_records(recs, edge_mask_dict, r_out, per_record)
    return resolve_hetero_parts(parts, (heads, d), cdtype)

  def _merge_et(self, et, k_rel, v_rel, q_dst, p_rel, edge_index_dict,
                edge_mask_dict, r_out, heads, d, cdtype):
    """Dense k-run attention over CALIBRATED merge records: children
    gathered through the edge rows (FLAT 2D gathers — PERF.md layout
    rule — then reshaped), parent queries dynamic-sliced at the run
    base, run blocks accumulated read-modify-write (TreeHeteroConv
    mode='merge' machinery)."""
    from .models import TreeHeteroConv
    recs = self._et_records(et)
    acc = jnp.zeros((r_out, heads, d), cdtype)
    kf = k_rel.reshape(-1, heads * d)
    vf = v_rel.reshape(-1, heads * d)
    for r in recs:
      if r['parent_base'] >= r_out:
        break
      f, kk = r['fcap'], r['k']
      m, src, base, ok = TreeHeteroConv._run_layout(
          r, edge_mask_dict, edge_index_dict, r_out)
      kc = kf[src].reshape(f, kk, heads, d)
      vc = vf[src].reshape(f, kk, heads, d)
      qp = jax.lax.dynamic_slice_in_dim(q_dst, base, f)
      vals = self._run_attention(kc, vc, qp, m, p_rel, d, cdtype)
      vals = jnp.where(ok[:, None, None], vals,
                       jnp.zeros((), vals.dtype))
      cur = jax.lax.dynamic_slice_in_dim(acc, base, f)
      acc = jax.lax.dynamic_update_slice(acc, cur + vals, (base, 0, 0))
    return acc


class HGT(nn.Module):
  """HGT stack (reference examples/hetero/train_hgt_mag.py HGT class):
  per-type input Dense + relu, ``num_layers`` HGTConv layers, linear
  head on ``out_ntype`` (None = return the full dict).

  ``hop_node_offsets``/``hop_edge_offsets`` (from
  ``sampler.hetero_tree_layout`` with the loader's seed caps/fanouts)
  enable the HIERARCHICAL forward over hetero tree-mode batches: layer l
  only processes the typed node/edge prefixes its depth needs — the same
  trim-per-layer scheme as RGNN's, applied to typed attention.
  """
  ntypes: Sequence[NodeType]
  etypes: Sequence[EdgeType]
  hidden_dim: int
  out_dim: int
  heads: int = 4
  num_layers: int = 2
  out_ntype: NodeType = None
  dtype: Any = None
  hop_node_offsets: Any = None
  hop_edge_offsets: Any = None
  # tree_records (sampler.hetero_tree_blocks): dense k-run typed
  # attention per layer (see HGTConv.tree_records) with per-type
  # out_rows prefix outputs — requires the hierarchical offsets.
  tree_records: Any = None
  # merge_dense: tree_records/offsets came from a calibrated merge
  # layout (hetero_tree_blocks(etype_caps=...)) — dense attention on
  # clamped exact-dedup batches (HGTConv merge=True); dedup='merge'.
  merge_dense: bool = False
  # per-type RAW feature widths: when given, the input Dense lin_{t} is
  # materialized for every ntype even if absent from the init batch, so
  # the param tree never depends on batch content (see HGTConv.in_dims)
  in_dims: Any = None

  def __post_init__(self):
    # EdgeType-keyed dicts cannot live on Module fields (flax >= 0.10
    # asserts string dict keys); store as pair tuples, thaw at call time
    from .models import freeze_etype_items
    object.__setattr__(self, 'hop_edge_offsets',
                       freeze_etype_items(self.hop_edge_offsets))
    super().__post_init__()

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict,
               train: bool = False):
    from .models import (check_hetero_offsets, hetero_trim,
                         thaw_etype_items)
    hier = self.hop_node_offsets is not None
    hop_edge_offsets = thaw_etype_items(self.hop_edge_offsets)
    if hier:
      check_hetero_offsets(x_dict, edge_index_dict,
                           self.hop_node_offsets, hop_edge_offsets,
                           self.num_layers)
    x_dict = {t: nn.relu(nn.Dense(self.hidden_dim, dtype=self.dtype,
                                  name=f'lin_{t}')(
        x.astype(self.dtype) if self.dtype is not None else x))
        for t, x in x_dict.items()}
    if self.in_dims:
      # absent-type lin params (batch-independent param tree; the conv
      # layers handle their own absent-type params via HGTConv)
      for t in self.ntypes:
        if t not in x_dict and t in self.in_dims:
          nn.Dense(self.hidden_dim, dtype=self.dtype, name=f'lin_{t}')(
              jnp.zeros((1, self.in_dims[t]),
                        self.dtype or jnp.float32))
    if self.tree_records is not None:
      assert hier, ('HGT(tree_records=...) requires the hierarchical '
                    'hop offsets built from the same plan')
    meta = (tuple(self.ntypes), tuple(tuple(e) for e in self.etypes))
    for i in range(self.num_layers):
      hops_used = self.num_layers - i
      if hier:
        x_in, ei, em = hetero_trim(
            x_dict, edge_index_dict, edge_mask_dict,
            self.hop_node_offsets, hop_edge_offsets, hops_used)
      else:
        x_in, ei, em = x_dict, edge_index_dict, edge_mask_dict
      recs = out_rows = None
      if self.tree_records is not None:
        recs = self.tree_records[:hops_used]
        out_rows = {t: self.hop_node_offsets[t][hops_used - 1]
                    for t in x_in}
      x_dict = HGTConv(self.hidden_dim, meta, heads=self.heads,
                       dtype=self.dtype, tree_records=recs,
                       out_rows=out_rows, merge=self.merge_dense,
                       name=f'conv{i}')(x_in, ei, em)
    head = nn.Dense(self.out_dim, dtype=self.dtype, name='head')
    if self.out_ntype is None:
      return {t: head(x) for t, x in x_dict.items()}
    return head(x_dict[self.out_ntype])
