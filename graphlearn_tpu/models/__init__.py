from .conv import (GATConv, GCNConv, SAGEConv, segment_max_agg,
                   segment_mean_agg, segment_sum_agg)
from .hgt import HGT, HGTConv
from .models import (GAT, GCN, GraphSAGE, HeteroConv, MergeGATConv,
                     MergeSAGEConv, RGNN, TreeGATConv, TreeHeteroConv,
                     TreeSAGEConv)
from .train import (TrainState, batch_to_dict, create_train_state,
                    make_train_step, merge_hop_offsets, tree_hop_offsets)
