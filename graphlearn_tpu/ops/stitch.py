"""Stitch partial sampling results back into seed order.

TPU-native counterpart of the reference stitch kernels
(/root/reference/graphlearn_torch/csrc/cuda/stitch_sample_results.cu): in the
distributed sampler each partition returns neighbors for the subset of seeds
it owns plus the positions of those seeds in the original request; stitching
is a pure fixed-shape scatter.
"""
import jax.numpy as jnp


def stitch_rows(index_list, rows_list, mask_list, out_len: int):
  """Scatter per-partition row-blocks into the original seed order.

  Args:
    index_list: list of [Bp] positions into the output (padded entries may be
      arbitrary where the corresponding mask row is all-False).
    rows_list: list of [Bp, K] payloads.
    mask_list: list of [Bp, K] validity masks.
    out_len: number of output rows (static).

  Returns (out [out_len, K], out_mask [out_len, K]).
  """
  k = rows_list[0].shape[1]
  dtype = rows_list[0].dtype
  out = jnp.zeros((out_len, k), dtype=dtype)
  out_mask = jnp.zeros((out_len, k), dtype=bool)
  for idx, rows, mask in zip(index_list, rows_list, mask_list):
    row_valid = mask.any(axis=1)
    slot = jnp.where(row_valid, idx, out_len)
    out = out.at[slot].set(jnp.where(mask, rows, out.dtype.type(0)),
                           mode='drop')
    out_mask = out_mask.at[slot].set(mask, mode='drop')
  return out, out_mask
