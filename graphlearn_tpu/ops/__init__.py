from .collate import (collate_batch, gather_rows, stack2, stack2_batched,
                      valid_mask)
from .gather_pallas import (decode_gather_plan, gather_rows_hbm,
                            gather_rows_hbm2, plan_gather_runs)
from .induce import InducerState, induce_next, init_empty, init_node
from .induce_map import (MapInducerState, induce_next_map, init_node_map)
from .induce_merge import (MergeInducerState, induce_next_merge,
                           init_empty_merge, init_node_merge)
from .induce_tree import (TreeInducerState, induce_next_tree,
                          init_empty_tree, init_node_tree)
from .negative import (random_negative_sample, random_negative_sample_local,
                       sort_csr_segments)
from .neighbor import (BLOCK, build_padded_adjacency,
                       build_padded_adjacency_device, build_row_cumsum,
                       choose_padded_window, edge_in_csr,
                       padded_table_stats, uniform_sample,
                       uniform_sample_block, uniform_sample_local,
                       uniform_sample_padded, weighted_sample,
                       weighted_sample_local)
from .route import (exchange_capacity, gather_from_buckets, round8,
                    route_slots, scatter_to_buckets)
from .sample_fused import (LEVEL_MAX_CANDIDATES, build_indices128,
                           sample_hop_fused, sample_level_fused)
from .stitch import stitch_rows
from .subgraph import (node_subgraph, node_subgraph_bucketed,
                       node_subgraph_local)
from .unique import FILL, masked_unique, searchsorted_membership
