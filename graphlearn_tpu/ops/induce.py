"""Incremental subgraph induction (dedup + relabel) with fixed shapes.

TPU-native replacement for the reference Inducer
(/root/reference/graphlearn_torch/csrc/cuda/inducer.cu): the CUDA version
keeps a device hash table alive across hops so every node sampled within a
batch gets one globally-unique local index, and emits relabeled COO rows/cols
per hop. Here the persistent state is a fixed-capacity node buffer plus a
sorted view of it; per-hop dedup is sort-based (ops.unique) and membership
against earlier hops is a binary search on the sorted view. Everything is
jittable: capacities are static, counts are traced scalars.

State invariants:
  nodes[:num_nodes]   — global ids, position == local index (seeds first).
  sorted_vals         — ascending sort of nodes with INT_MAX padding.
  sorted_pos          — sorted_vals[i] == nodes[sorted_pos[i]].
"""
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .unique import FILL, masked_unique, searchsorted_membership


class InducerState(NamedTuple):
  nodes: jax.Array        # [cap] global ids, FILL-padded
  num_nodes: jax.Array    # scalar int32
  sorted_vals: jax.Array  # [cap] ascending, INT_MAX-padded
  sorted_pos: jax.Array   # [cap] position of sorted_vals in nodes


def _sort_view(nodes: jax.Array):
  big = jnp.iinfo(nodes.dtype).max
  keys = jnp.where(nodes == FILL, big, nodes)
  order = jnp.argsort(keys)
  return keys[order], order.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=('capacity',))
def init_node(seeds: jax.Array, seed_mask: jax.Array, capacity: int):
  """Start a batch: dedup seeds into local indices 0..n-1.

  Reference: CUDAInducer::InitNode (inducer.cu:75-93). Returns
  (state, uniq_seeds [B], uniq_mask [B], inverse [B]) — uniq_seeds[i] has
  local index i, and inverse[j] is the local index of input seed j (-1
  where masked), needed by link sampling to relocate each original seed.
  """
  b = seeds.shape[0]
  uniq, count, inverse = masked_unique(seeds, seed_mask, size=b)
  nodes = jnp.full((capacity,), FILL, dtype=seeds.dtype)
  nodes = nodes.at[:b].set(uniq)
  sorted_vals, sorted_pos = _sort_view(nodes)
  state = InducerState(nodes, count.astype(jnp.int32), sorted_vals,
                       sorted_pos)
  return state, uniq, jnp.arange(b) < count, inverse


@functools.partial(jax.jit, static_argnames=('capacity',))
def init_empty(capacity: int, dtype=jnp.int32):
  """An inducer state with no nodes yet (hetero: node types first reached
  mid-hop; reference lazily keys per-type hash tables, inducer.cu hetero)."""
  nodes = jnp.full((capacity,), FILL, dtype=dtype)
  sorted_vals, sorted_pos = _sort_view(nodes)
  return InducerState(nodes, jnp.asarray(0, jnp.int32), sorted_vals,
                      sorted_pos)


@jax.jit
def induce_next(state: InducerState, src_idx: jax.Array, nbrs: jax.Array,
                nbr_mask: jax.Array):
  """Absorb one hop of sampled neighbors.

  Reference: CUDAInducer::InduceNext (inducer.cu:95-165).

  Args:
    state: inducer state from init_node / previous induce_next.
    src_idx: [F] local indices of the frontier nodes the hop sampled from.
    nbrs: [F, K] sampled neighbor global ids (FILL-padded).
    nbr_mask: [F, K] validity.

  Returns (new_state, out) where out has:
    rows, cols: [F*K] relabeled COO (row = src local idx, col = nbr local
      idx), -1 where invalid; edge order matches ``nbrs.reshape(-1)`` so the
    caller can gather edge ids in the same order.
    edge_mask: [F*K]
    frontier, frontier_idx, frontier_mask: [F*K] newly-added unique nodes
      (global ids / local indices) — the next hop's seeds.
    num_new: scalar count of newly-added nodes.
  """
  f, k = nbrs.shape
  flat = nbrs.reshape(-1)
  flat_mask = nbr_mask.reshape(-1)
  size = f * k

  uniq, ucnt, inv = masked_unique(flat, flat_mask, size=size)
  uniq_valid = jnp.arange(size) < ucnt

  found, pos = searchsorted_membership(state.sorted_vals, uniq)
  found = found & uniq_valid
  existing_idx = state.sorted_pos[pos]

  new_mask = uniq_valid & (~found)
  new_rank = (jnp.cumsum(new_mask) - 1).astype(jnp.int32)
  new_idx = state.num_nodes + new_rank
  num_new = jnp.sum(new_mask).astype(jnp.int32)

  uniq_local = jnp.where(found, existing_idx, new_idx)
  uniq_local = jnp.where(uniq_valid, uniq_local, -1)

  nodes = state.nodes.at[jnp.where(new_mask, new_idx, state.nodes.shape[0])
                         ].set(uniq, mode='drop')
  sorted_vals, sorted_pos = _sort_view(nodes)
  new_state = InducerState(nodes, state.num_nodes + num_new, sorted_vals,
                           sorted_pos)

  rows = jnp.repeat(src_idx.astype(jnp.int32), k)
  cols = jnp.where(flat_mask, uniq_local[jnp.clip(inv, 0, size - 1)], -1)
  rows = jnp.where(flat_mask, rows, -1)

  slot = jnp.where(new_mask, new_rank, size)
  frontier = jnp.full((size,), FILL, dtype=flat.dtype
                      ).at[slot].set(uniq, mode='drop')
  frontier_idx = jnp.full((size,), -1, dtype=jnp.int32
                          ).at[slot].set(new_idx, mode='drop')
  frontier_mask = jnp.arange(size) < num_new

  out = dict(rows=rows, cols=cols, edge_mask=flat_mask, frontier=frontier,
             frontier_idx=frontier_idx, frontier_mask=frontier_mask,
             num_new=num_new)
  return new_state, out
