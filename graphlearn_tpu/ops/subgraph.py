"""Induced-subgraph extraction with fixed shapes.

TPU-native replacement for the reference SubGraphOp
(/root/reference/graphlearn_torch/csrc/cuda/subgraph_op.cu): given a node
set, keep every edge whose endpoints are both in the set, relabeled to local
indices. The CUDA version slices CSR rows exactly and masks columns with a
device hash table; here rows are scanned up to a static ``max_degree`` cap and
set-membership is a binary search over the deduped (sorted) node set.
"""
import functools

import jax
import jax.numpy as jnp

from .unique import FILL, masked_unique


@functools.partial(jax.jit, static_argnames=('max_degree',))
def node_subgraph(indptr, indices, srcs, src_mask, max_degree: int):
  """Extract the subgraph induced by ``srcs[src_mask]``.

  Returns dict with:
    nodes: [B] deduped node set (ascending, FILL-padded); local index == pos.
    num_nodes: scalar.
    rows, cols: [B * max_degree] relabeled COO, -1 where invalid.
    epos: [B * max_degree] CSR edge positions (for edge-id gather).
    edge_mask: [B * max_degree].
  """
  b = srcs.shape[0]
  nodes, num_nodes, _ = masked_unique(srcs, src_mask, size=b)
  node_valid = jnp.arange(b) < num_nodes

  safe_nodes = jnp.where(node_valid, nodes, 0)
  start = indptr[safe_nodes]
  deg = indptr[safe_nodes + 1] - start
  off = jnp.arange(max_degree, dtype=start.dtype)[None, :]
  in_row = node_valid[:, None] & (off < deg[:, None])
  epos = jnp.where(in_row, start[:, None] + off, 0)
  nbr = jnp.where(in_row, indices[epos], FILL)

  # Membership + relabel: ``nodes`` is ascending over [0, num_nodes) but
  # FILL(-1)-padded at the tail, which would break searchsorted's ordering
  # requirement — remap padding to int-max for the search keys.
  big = jnp.iinfo(nodes.dtype).max
  skeys = jnp.where(node_valid, nodes, big)
  pos = jnp.clip(jnp.searchsorted(skeys, nbr), 0, b - 1)
  member = in_row & (skeys[pos] == nbr)

  rows = jnp.where(member, jnp.broadcast_to(
      jnp.arange(b, dtype=jnp.int32)[:, None], (b, max_degree)), -1)
  cols = jnp.where(member, pos.astype(jnp.int32), -1)
  return dict(nodes=nodes, num_nodes=num_nodes,
              rows=rows.reshape(-1), cols=cols.reshape(-1),
              epos=jnp.where(member, epos, 0).reshape(-1),
              edge_mask=member.reshape(-1))


def node_subgraph_local(row_ids, indptr_loc, indices, node_keys,
                        max_degree: int):
  """Induced-subgraph extraction over a *partition-local* CSR.

  Distributed counterpart of :func:`node_subgraph` (reference: each
  partition answers a subgraph RPC from its local graph,
  dist_neighbor_sampler.py:499-559 / rpc_sample_callee). ``node_keys`` is
  the ascending node set with padding mapped to int-max (searchsorted
  keys); the shard finds which of those nodes it owns (binary search on
  ``row_ids``), scans each owned row to ``max_degree``, and keeps edges
  whose endpoint is also in the set — relabeled to positions in
  ``node_keys``.

  Traced inside shard_map. Returns dict rows/cols [B*max_degree] (-1
  invalid), epos [B*max_degree] local CSR edge positions, edge_mask.
  """
  b = node_keys.shape[0]
  big = jnp.iinfo(node_keys.dtype).max
  node_valid = node_keys != big
  # which set nodes does this shard own?
  rpos = jnp.clip(jnp.searchsorted(row_ids, node_keys), 0,
                  row_ids.shape[0] - 1)
  owned = node_valid & (row_ids[rpos] == node_keys)
  start = jnp.where(owned, indptr_loc[rpos], 0)
  deg = jnp.where(owned, indptr_loc[rpos + 1] - start, 0)
  off = jnp.arange(max_degree, dtype=start.dtype)[None, :]
  in_row = off < deg[:, None]
  epos = jnp.where(in_row, start[:, None] + off, 0)
  nbr = jnp.where(in_row, indices[epos], big)
  pos = jnp.clip(jnp.searchsorted(node_keys, nbr), 0, b - 1)
  member = in_row & (node_keys[pos] == nbr)
  rows = jnp.where(member, jnp.broadcast_to(
      jnp.arange(b, dtype=jnp.int32)[:, None], (b, max_degree)), -1)
  cols = jnp.where(member, pos.astype(jnp.int32), -1)
  return dict(rows=rows.reshape(-1), cols=cols.reshape(-1),
              epos=jnp.where(member, epos, 0).reshape(-1),
              edge_mask=member.reshape(-1))
