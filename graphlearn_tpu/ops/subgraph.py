"""Induced-subgraph extraction with fixed shapes.

TPU-native replacement for the reference SubGraphOp
(/root/reference/graphlearn_torch/csrc/cuda/subgraph_op.cu): given a node
set, keep every edge whose endpoints are both in the set, relabeled to local
indices. The CUDA version slices CSR rows exactly and masks columns with a
device hash table; here rows are scanned up to a static ``max_degree`` cap and
set-membership is a binary search over the deduped (sorted) node set.
"""
import functools

import jax
import jax.numpy as jnp

from .unique import FILL, masked_unique


@functools.partial(jax.jit, static_argnames=('max_degree',))
def node_subgraph(indptr, indices, srcs, src_mask, max_degree: int):
  """Extract the subgraph induced by ``srcs[src_mask]``.

  Returns dict with:
    nodes: [B] deduped node set (ascending, FILL-padded); local index == pos.
    num_nodes: scalar.
    rows, cols: [B * max_degree] relabeled COO, -1 where invalid.
    epos: [B * max_degree] CSR edge positions (for edge-id gather).
    edge_mask: [B * max_degree].
  """
  b = srcs.shape[0]
  nodes, num_nodes, _ = masked_unique(srcs, src_mask, size=b)
  node_valid = jnp.arange(b) < num_nodes

  safe_nodes = jnp.where(node_valid, nodes, 0)
  start = indptr[safe_nodes]
  deg = indptr[safe_nodes + 1] - start
  off = jnp.arange(max_degree, dtype=start.dtype)[None, :]
  in_row = node_valid[:, None] & (off < deg[:, None])
  epos = jnp.where(in_row, start[:, None] + off, 0)
  nbr = jnp.where(in_row, indices[epos], FILL)

  # Membership + relabel: ``nodes`` is ascending over [0, num_nodes) but
  # FILL(-1)-padded at the tail, which would break searchsorted's ordering
  # requirement — remap padding to int-max for the search keys.
  big = jnp.iinfo(nodes.dtype).max
  skeys = jnp.where(node_valid, nodes, big)
  pos = jnp.clip(jnp.searchsorted(skeys, nbr), 0, b - 1)
  member = in_row & (skeys[pos] == nbr)

  rows = jnp.where(member, jnp.broadcast_to(
      jnp.arange(b, dtype=jnp.int32)[:, None], (b, max_degree)), -1)
  cols = jnp.where(member, pos.astype(jnp.int32), -1)
  return dict(nodes=nodes, num_nodes=num_nodes,
              rows=rows.reshape(-1), cols=cols.reshape(-1),
              epos=jnp.where(member, epos, 0).reshape(-1),
              edge_mask=member.reshape(-1))


@functools.partial(jax.jit, static_argnames=('deg_small', 'cap_large',
                                             'max_degree'))
def node_subgraph_bucketed(indptr, indices, srcs, src_mask,
                           deg_small: int, cap_large: int,
                           max_degree: int):
  """Degree-bucketed induced-subgraph extraction.

  :func:`node_subgraph` scans EVERY row to the graph's max degree, so one
  celebrity vertex makes every batch ``[B, max_degree]``-sized. Here rows
  are split into two static buckets: low-degree rows (deg <= deg_small,
  the vast majority on power-law graphs) scan only ``deg_small`` columns,
  and up to ``cap_large`` high-degree rows scan ``max_degree``. The output
  buffer shrinks from ``B * max_degree`` to
  ``B * deg_small + cap_large * max_degree``. High-degree rows beyond
  ``cap_large`` are NOT silently lost: they are counted in
  ``num_dropped_rows`` so callers can grow the cap (reference slices
  exactly per row — subgraph_op.cu:133-242 — which a static-shape program
  cannot; this is the TPU-native trade).

  Returns the :func:`node_subgraph` dict plus ``num_dropped_rows``.
  """
  b = srcs.shape[0]
  nodes, num_nodes, _ = masked_unique(srcs, src_mask, size=b)
  node_valid = jnp.arange(b) < num_nodes
  safe_nodes = jnp.where(node_valid, nodes, 0)
  start = indptr[safe_nodes]
  deg = jnp.where(node_valid, indptr[safe_nodes + 1] - start, 0)
  big = jnp.iinfo(nodes.dtype).max
  skeys = jnp.where(node_valid, nodes, big)

  def extract(row_pos, row_mask, cap):
    """Scan rows ``nodes[row_pos]`` to ``cap`` columns, relabel."""
    n = row_pos.shape[0]
    st = jnp.where(row_mask, start[row_pos], 0)
    dg = jnp.where(row_mask, deg[row_pos], 0)
    off = jnp.arange(cap, dtype=st.dtype)[None, :]
    in_row = off < dg[:, None]
    epos = jnp.where(in_row, st[:, None] + off, 0)
    nbr = jnp.where(in_row, indices[epos], FILL)
    pos = jnp.clip(jnp.searchsorted(skeys, nbr), 0, b - 1)
    member = in_row & (skeys[pos] == nbr)
    rows = jnp.where(member, jnp.broadcast_to(
        row_pos.astype(jnp.int32)[:, None], (n, cap)), -1)
    cols = jnp.where(member, pos.astype(jnp.int32), -1)
    return (rows.reshape(-1), cols.reshape(-1),
            jnp.where(member, epos, 0).reshape(-1), member.reshape(-1))

  is_small = node_valid & (deg <= deg_small)
  is_large = node_valid & (deg > deg_small)
  # small pass covers all B positions; large rows masked out of it
  all_pos = jnp.arange(b, dtype=jnp.int32)
  r1, c1, e1, m1 = extract(all_pos, is_small, deg_small)
  # compact high-degree row positions into cap_large slots
  order = jnp.argsort(jnp.where(is_large, 0, 1), stable=True)
  lpos = order[:cap_large].astype(jnp.int32)
  lmask = is_large[lpos]
  r2, c2, e2, m2 = extract(lpos, lmask, max_degree)
  num_large = jnp.sum(is_large).astype(jnp.int32)
  dropped = jnp.maximum(num_large - cap_large, 0)
  return dict(nodes=nodes, num_nodes=num_nodes,
              rows=jnp.concatenate([r1, r2]),
              cols=jnp.concatenate([c1, c2]),
              epos=jnp.concatenate([e1, e2]),
              edge_mask=jnp.concatenate([m1, m2]),
              num_dropped_rows=dropped)


def node_subgraph_local(row_ids, indptr_loc, indices, node_keys,
                        max_degree: int):
  """Induced-subgraph extraction over a *partition-local* CSR.

  Distributed counterpart of :func:`node_subgraph` (reference: each
  partition answers a subgraph RPC from its local graph,
  dist_neighbor_sampler.py:499-559 / rpc_sample_callee). ``node_keys`` is
  the ascending node set with padding mapped to int-max (searchsorted
  keys); the shard finds which of those nodes it owns (binary search on
  ``row_ids``), scans each owned row to ``max_degree``, and keeps edges
  whose endpoint is also in the set — relabeled to positions in
  ``node_keys``.

  Traced inside shard_map. Returns dict rows/cols [B*max_degree] (-1
  invalid), epos [B*max_degree] local CSR edge positions, edge_mask.
  """
  b = node_keys.shape[0]
  big = jnp.iinfo(node_keys.dtype).max
  node_valid = node_keys != big
  # which set nodes does this shard own?
  rpos = jnp.clip(jnp.searchsorted(row_ids, node_keys), 0,
                  row_ids.shape[0] - 1)
  owned = node_valid & (row_ids[rpos] == node_keys)
  start = jnp.where(owned, indptr_loc[rpos], 0)
  deg = jnp.where(owned, indptr_loc[rpos + 1] - start, 0)
  off = jnp.arange(max_degree, dtype=start.dtype)[None, :]
  in_row = off < deg[:, None]
  epos = jnp.where(in_row, start[:, None] + off, 0)
  nbr = jnp.where(in_row, indices[epos], big)
  pos = jnp.clip(jnp.searchsorted(node_keys, nbr), 0, b - 1)
  member = in_row & (node_keys[pos] == nbr)
  rows = jnp.where(member, jnp.broadcast_to(
      jnp.arange(b, dtype=jnp.int32)[:, None], (b, max_degree)), -1)
  cols = jnp.where(member, pos.astype(jnp.int32), -1)
  return dict(rows=rows.reshape(-1), cols=cols.reshape(-1),
              epos=jnp.where(member, epos, 0).reshape(-1),
              edge_mask=member.reshape(-1))
