"""Fused batch collation: mask/edge_index/feature/label gathers in ONE
jitted dispatch.

The reference collates on the host driver (loader/node_loader.py:85-113
gathers features via UnifiedTensor then builds PyG Data). Here collation
must be a single device program for a different reason: an eager op whose
input is a still-pending sampler output serializes the dispatch pipeline
on remote-dispatch runtimes (PERF.md), so the loader may not touch the
sampler's outputs eagerly. All arrays enter as arguments (never closures),
and optional stores are trace-time ``None`` branches.
"""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=('label_cap',))
def collate_batch(node, num_nodes, row, col, feats, id2index, labels,
                  edge_feats, edge, label_cap=None):
  """Build the derived batch payloads on device.

  Args:
    node: [cap_n] global ids (FILL=-1 padded).
    num_nodes: scalar valid count.
    row / col: [cap_e] relabeled endpoints (or None).
    feats: [N, F] device feature table (or None).
    id2index: [N] hotness-reorder map applied before the gather (or None).
    labels: [N] device label table (or None).
    edge_feats: [E, F_e] device edge-feature table (or None).
    edge: [cap_e] global edge ids (needed when edge_feats given).
    label_cap: static; gather labels only for the first ``label_cap``
      node slots (the seed block leads the buffer, and supervision uses
      seed slots only — a full-buffer label gather is a per-element
      random access over the whole node capacity, ~5 ms/batch at
      products scale). None = full buffer (reference-parity y shape).

  Returns dict with node_mask, edge_index (or None), x, y, edge_attr —
  padded slots gather row/label 0 (masked downstream by node_mask).
  """
  out = {}
  out['node_mask'] = jnp.arange(node.shape[0]) < num_nodes
  out['edge_index'] = (jnp.stack([row, col]) if row is not None else None)
  safe = jnp.maximum(node, 0)
  if feats is not None:
    fidx = id2index[safe] if id2index is not None else safe
    out['x'] = feats[fidx]
  else:
    out['x'] = None
  lsafe = safe if label_cap is None else safe[:label_cap]
  out['y'] = labels[lsafe] if labels is not None else None
  if edge_feats is not None and edge is not None:
    out['edge_attr'] = edge_feats[jnp.maximum(edge, 0)]
  else:
    out['edge_attr'] = None
  return out


@jax.jit
def valid_mask(node, num_nodes):
  """arange(len(node)) < num_nodes, as a jitted dispatch."""
  return jnp.arange(node.shape[0]) < num_nodes


@jax.jit
def stack2(a, b):
  """Jitted 2-row stack (edge_index assembly without an eager op)."""
  return jnp.stack([a, b])


@jax.jit
def stack2_batched(a, b):
  """[P, E] x 2 -> [P, 2, E] (sharded edge_index assembly)."""
  return jnp.stack([a, b], axis=1)


@jax.jit
def gather_rows(table, id2index, ids):
  """Single fused gather with padding clamp (hetero per-type collate)."""
  safe = jnp.maximum(ids, 0)
  if id2index is not None:
    safe = id2index[safe]
  return table[safe]
