"""Fused sample+gather CSR hop: the neighbor-slot draw and the adjacency
gather in ONE Pallas pass.

The hardware-matched-sampler argument (GNNSampler, arxiv 2108.11571;
sampler accelerators, arxiv 2209.02916) instantiated for TPU: XLA lowers
``ops.uniform_sample``'s hop as (a) the [B, K] offset draw, (b) an
HBM-materialized [B, K] ``epos`` intermediate, and (c) a LATENCY-BOUND
element gather over the [E] CSR indices array — one DMA transaction per
sampled edge (~140M elem/s, PERF.md). But a seed's neighbor segment
``indices[start : start+deg]`` is CONTIGUOUS in HBM, so this kernel
stages it with ONE aligned multi-row DMA per seed and resolves all k
draws against the staged window with dense VPU one-hot selection —
k transactions collapse to ~1 for every seed whose segment fits the
window, and the sampled edges never round-trip through an
HBM-materialized intermediate.

Bit-matching contract: the draw itself (offsets, validity mask, epos)
is computed OUTSIDE the kernel with byte-for-byte the same jnp ops as
``ops.uniform_sample`` fed by the same counter-addressed fold_in key —
so the kernel's only job is ``indices[epos]``, and the XLA fallback
(off-TPU, or routing flag off) IS ``ops.uniform_sample``'s stream:
identical edges, identical epos, identical mask, on every path.

Layout: the CSR indices ship as a FILL-padded aligned ``[ceil(E/128),
128]`` block view (``build_indices128`` — the 128-lane cousin of block
sampling's [E/16, 16] view). Per seed the kernel branches:

  deg fits the window  -> one [NR, 128]-row DMA staging the aligned
                          superset of [start, start+deg) (NR =
                          window//128 + 1 covers any start alignment);
  deg > window (hubs)  -> k single-[128]-row DMAs, one per sampled
                          position — no worse than XLA's k element
                          transactions, and hop-local (no fallback
                          cliff: a single hub in the frontier does not
                          de-optimize the rest of the batch).

Routing is evidence-gated like every kernel in this repo:
``NeighborSampler(use_fused_hop=...)`` defaults to False, the XLA path
stays bit-identical, and interpret-mode parity tests pin the kernel
against ``ops.uniform_sample`` on CPU (tests/test_ops.py).
"""
import functools

import jax
import jax.numpy as jnp

from .unique import FILL

LANES = 128


def build_indices128(indices, min_rows: int = 0):
  """[E] CSR indices -> FILL-padded aligned [max(ceil(E/128), min_rows),
  128] view (device-side; a free reshape plus tail pad)."""
  e = int(indices.shape[0])
  rows = max(-(-e // LANES), min_rows, 1)
  pad = rows * LANES - e
  ind = jnp.asarray(indices).astype(jnp.int32)
  if pad:
    ind = jnp.concatenate([ind, jnp.full((pad,), FILL, jnp.int32)])
  return ind.reshape(rows, LANES)


def _draw(start, deg, seed_mask, k: int, key):
  """ops.uniform_sample's offset draw, byte for byte (the bit-matching
  contract lives or dies on this staying IDENTICAL to neighbor.py)."""
  b = seed_mask.shape[0]
  u = jax.random.uniform(key, (b, k))
  rand_off = jnp.floor(u * deg[:, None].astype(u.dtype)).astype(jnp.int32)
  rand_off = jnp.minimum(rand_off, jnp.maximum(deg[:, None] - 1, 0))
  seq_off = jnp.arange(k, dtype=jnp.int32)[None, :]
  offsets = jnp.where(deg[:, None] > k, rand_off, seq_off)
  mask = seed_mask[:, None] & (offsets < deg[:, None])
  epos = start[:, None] + offsets
  return epos, mask


def _hop_kernel_factory(k, nr, nbk):
  def kernel(plan_ref, blocks_ref, epos_ref, meta_ref, out_ref, win, big,
             sem_w, sem_b):
    from jax.experimental import pallas as pl
    i = pl.program_id(0)
    bs = out_ref.shape[0]

    def dmas(s):
      from jax.experimental.pallas import tpu as pltpu
      row0 = plan_ref[i * bs + s, 0]
      small = plan_ref[i * bs + s, 1]
      window = pltpu.make_async_copy(blocks_ref.at[pl.ds(row0, nr)],
                                     win.at[s], sem_w.at[s])
      return small, window

    def row_dma(s, j):
      from jax.experimental.pallas import tpu as pltpu
      r = jnp.clip(epos_ref[s, j] // LANES, 0, nbk - 1)
      return pltpu.make_async_copy(blocks_ref.at[r], big.at[s, j],
                                   sem_b.at[s, j])

    def issue(s, carry):
      small, window = dmas(s)

      @pl.when(small == 1)
      def _():
        window.start()

      @pl.when(small == 0)
      def _():
        def issue_j(j, c):
          row_dma(s, j).start()
          return c
        jax.lax.fori_loop(0, k, issue_j, None, unroll=True)
      return carry

    jax.lax.fori_loop(0, bs, issue, None)

    def drain(s, carry):
      small, window = dmas(s)

      @pl.when(small == 1)
      def _():
        window.wait()

      @pl.when(small == 0)
      def _():
        def drain_j(j, c):
          row_dma(s, j).wait()
          return c
        jax.lax.fori_loop(0, k, drain_j, None, unroll=True)
      return carry

    jax.lax.fori_loop(0, bs, drain, None)

    # dense VPU extraction over the staged windows (one-hot contraction,
    # NOT take_along_axis — the same rule as ops.uniform_sample_padded)
    epos = epos_ref[:]                               # [bs, k]
    row0 = meta_ref[:, 0]                            # [bs]
    small = meta_ref[:, 1]
    wflat = win[:].reshape(bs, nr * LANES)
    pos_l = jnp.clip(epos - row0[:, None] * LANES, 0, nr * LANES - 1)
    lanes_w = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nr * LANES), 2)
    small_nbrs = jnp.sum(wflat[:, None, :] * (pos_l[:, :, None] == lanes_w),
                         axis=-1)
    lanes_b = jax.lax.broadcasted_iota(jnp.int32, (1, 1, LANES), 2)
    big_nbrs = jnp.sum(big[:] * ((epos % LANES)[:, :, None] == lanes_b),
                       axis=-1)
    sel = jnp.where(small[:, None] == 1, small_nbrs, big_nbrs)  # [bs, k]
    out_ref[:] = jnp.concatenate(
        [sel, jnp.zeros((bs, LANES - k), jnp.int32)], axis=1)
  return kernel


def _gather_epos_pallas(blocks128, start, deg, safe_epos, k: int,
                        window: int, block_seeds: int, interpret: bool):
  """``indices[safe_epos]`` via per-seed staged windows (see module
  docstring); values at masked slots are whatever row 0 holds — callers
  mask them, exactly like the XLA path's ``indices[safe_epos]``."""
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  b = start.shape[0]
  assert window % LANES == 0 and window > 0
  nr = window // LANES + 1      # covers any start%128 alignment
  nbk = blocks128.shape[0]
  assert nbk >= nr, 'build_indices128(min_rows=nr) guarantees this'
  assert 0 < k <= LANES
  bs = min(block_seeds, b)
  pad = (-b) % bs
  row0 = jnp.clip(start // LANES, 0, nbk - nr).astype(jnp.int32)
  # every sampled position of a 'small' seed lies inside its window:
  # epos < start + deg <= row0*128 + nr*128 (clamped row0 only lowers
  # the base, and the window top then reaches the padded array end)
  small = ((start - row0 * LANES + deg) <= nr * LANES).astype(jnp.int32)
  plan = jnp.stack([row0, small], axis=1)            # [b, 2]
  epos32 = safe_epos.astype(jnp.int32)
  if pad:
    plan = jnp.concatenate(
        [plan, jnp.tile(jnp.array([[0, 1]], jnp.int32), (pad, 1))])
    epos32 = jnp.concatenate([epos32, jnp.zeros((pad, k), jnp.int32)])
  grid = (b + pad) // bs

  out = pl.pallas_call(
      _hop_kernel_factory(k, nr, nbk),
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=1,
          grid=(grid,),
          in_specs=[
              pl.BlockSpec(memory_space=pl.ANY),               # blocks128
              pl.BlockSpec((bs, k), lambda i, plan_ref: (i, 0)),   # epos
              pl.BlockSpec((bs, 2), lambda i, plan_ref: (i, 0)),   # meta
          ],
          out_specs=pl.BlockSpec((bs, LANES), lambda i, plan_ref: (i, 0)),
          scratch_shapes=[
              pltpu.VMEM((bs, nr, LANES), jnp.int32),
              pltpu.VMEM((bs, k, LANES), jnp.int32),
              pltpu.SemaphoreType.DMA((bs,)),
              pltpu.SemaphoreType.DMA((bs, k)),
          ],
      ),
      out_shape=jax.ShapeDtypeStruct((b + pad, LANES), jnp.int32),
      interpret=interpret,
  )(plan, blocks128, epos32, plan)
  return out[:b, :k]


@functools.partial(jax.jit,
                   static_argnames=('k', 'window', 'block_seeds',
                                    'interpret', 'force'))
def sample_hop_fused(indptr, indices, blocks128, seeds, seed_mask, k: int,
                     key, meta=None, window: int = 512,
                     block_seeds: int = 128, interpret: bool = False,
                     force: bool = False):
  """One fused uniform CSR hop; same output contract — and the same
  PRNG stream, bit for bit — as :func:`ops.uniform_sample`.

  Args:
    indptr/indices: the CSR (used by the fallback path and for
      ``meta=None`` row lookup).
    blocks128: :func:`build_indices128` aligned view (may be None —
      forces the XLA fallback).
    seeds/seed_mask/k/key/meta: exactly :func:`ops.uniform_sample`.
    window: staged segment span per seed (multiple of 128; autotune axis
      probed by benchmarks/prof_gather2.py). Seeds with deg > window
      take the per-sample row-DMA path — never a whole-batch fallback.
    block_seeds: seeds per grid step.
    interpret: run the Pallas interpreter (CPU parity tests).
    force: run the kernel off-TPU (tests); default falls back to the
      XLA hop off-TPU.

  Returns (nbrs [B, K], epos [B, K], mask [B, K]) — FILL/0-padded like
  ``uniform_sample``.
  """
  safe_seeds = jnp.where(seed_mask, seeds, 0)
  if meta is not None:
    row = meta[safe_seeds]
    start, deg = row[:, 0], row[:, 1]
  else:
    start = indptr[safe_seeds]
    deg = indptr[safe_seeds + 1] - start
  epos, mask = _draw(start, deg, seed_mask, k, key)
  safe_epos = jnp.where(mask, epos, 0)
  use_kernel = blocks128 is not None and (
      interpret or force or jax.default_backend() == 'tpu')
  if use_kernel:
    picked = _gather_epos_pallas(blocks128, start, deg, safe_epos, k,
                                 window, block_seeds, interpret)
  else:
    picked = indices[safe_epos]
  nbrs = jnp.where(mask, picked, FILL)
  return nbrs, safe_epos, mask
