"""Fused sample+gather CSR hop: the neighbor-slot draw and the adjacency
gather in ONE Pallas pass.

The hardware-matched-sampler argument (GNNSampler, arxiv 2108.11571;
sampler accelerators, arxiv 2209.02916) instantiated for TPU: XLA lowers
``ops.uniform_sample``'s hop as (a) the [B, K] offset draw, (b) an
HBM-materialized [B, K] ``epos`` intermediate, and (c) a LATENCY-BOUND
element gather over the [E] CSR indices array — one DMA transaction per
sampled edge (~140M elem/s, PERF.md). But a seed's neighbor segment
``indices[start : start+deg]`` is CONTIGUOUS in HBM, so this kernel
stages it with ONE aligned multi-row DMA per seed and resolves all k
draws against the staged window with dense VPU one-hot selection —
k transactions collapse to ~1 for every seed whose segment fits the
window, and the sampled edges never round-trip through an
HBM-materialized intermediate.

Bit-matching contract: the draw itself (offsets, validity mask, epos)
is computed OUTSIDE the kernel with byte-for-byte the same jnp ops as
``ops.uniform_sample`` fed by the same counter-addressed fold_in key —
so the kernel's only job is ``indices[epos]``, and the XLA fallback
(off-TPU, or routing flag off) IS ``ops.uniform_sample``'s stream:
identical edges, identical epos, identical mask, on every path.

Layout: the CSR indices ship as a FILL-padded aligned ``[ceil(E/128),
128]`` block view (``build_indices128`` — the 128-lane cousin of block
sampling's [E/16, 16] view). Per seed the kernel branches:

  deg fits the window  -> one [NR, 128]-row DMA staging the aligned
                          superset of [start, start+deg) (NR =
                          window//128 + 1 covers any start alignment);
  deg > window (hubs)  -> k single-[128]-row DMAs, one per sampled
                          position — no worse than XLA's k element
                          transactions, and hop-local (no fallback
                          cliff: a single hub in the frontier does not
                          de-optimize the rest of the batch).

Routing is evidence-gated like every kernel in this repo:
``NeighborSampler(use_fused_hop=...)`` defaults to False, the XLA path
stays bit-identical, and interpret-mode parity tests pin the kernel
against ``ops.uniform_sample`` on CPU (tests/test_ops.py).
"""
import functools

import jax
import jax.numpy as jnp

from .induce_merge import MergeInducerState, induce_next_merge
from .unique import FILL

LANES = 128

# fused-LEVEL kernel bound: the in-kernel dedup is O(S^2) value-compares
# (S = frontier * k candidates) — dense VPU work that beats the merge
# engine's sort cascade only while S^2 stays small. Past this bound the
# wrapper refuses at trace time; the tuner then scores the candidate as
# broken evidence instead of shipping a regression.
LEVEL_MAX_CANDIDATES = 1 << 15


def build_indices128(indices, min_rows: int = 0):
  """[E] CSR indices -> FILL-padded aligned [max(ceil(E/128), min_rows),
  128] view (device-side; a free reshape plus tail pad)."""
  e = int(indices.shape[0])
  rows = max(-(-e // LANES), min_rows, 1)
  pad = rows * LANES - e
  ind = jnp.asarray(indices).astype(jnp.int32)
  if pad:
    ind = jnp.concatenate([ind, jnp.full((pad,), FILL, jnp.int32)])
  return ind.reshape(rows, LANES)


def _draw(start, deg, seed_mask, k: int, key):
  """ops.uniform_sample's offset draw, byte for byte (the bit-matching
  contract lives or dies on this staying IDENTICAL to neighbor.py)."""
  b = seed_mask.shape[0]
  u = jax.random.uniform(key, (b, k))
  rand_off = jnp.floor(u * deg[:, None].astype(u.dtype)).astype(jnp.int32)
  rand_off = jnp.minimum(rand_off, jnp.maximum(deg[:, None] - 1, 0))
  seq_off = jnp.arange(k, dtype=jnp.int32)[None, :]
  offsets = jnp.where(deg[:, None] > k, rand_off, seq_off)
  mask = seed_mask[:, None] & (offsets < deg[:, None])
  epos = start[:, None] + offsets
  return epos, mask


def _hop_kernel_factory(k, nr, nbk):
  def kernel(plan_ref, blocks_ref, epos_ref, meta_ref, out_ref, win, big,
             sem_w, sem_b):
    from jax.experimental import pallas as pl
    i = pl.program_id(0)
    bs = out_ref.shape[0]

    def dmas(s):
      from jax.experimental.pallas import tpu as pltpu
      row0 = plan_ref[i * bs + s, 0]
      small = plan_ref[i * bs + s, 1]
      window = pltpu.make_async_copy(blocks_ref.at[pl.ds(row0, nr)],
                                     win.at[s], sem_w.at[s])
      return small, window

    def row_dma(s, j):
      from jax.experimental.pallas import tpu as pltpu
      r = jnp.clip(epos_ref[s, j] // LANES, 0, nbk - 1)
      return pltpu.make_async_copy(blocks_ref.at[r], big.at[s, j],
                                   sem_b.at[s, j])

    def issue(s, carry):
      small, window = dmas(s)

      @pl.when(small == 1)
      def _():
        window.start()

      @pl.when(small == 0)
      def _():
        def issue_j(j, c):
          row_dma(s, j).start()
          return c
        jax.lax.fori_loop(0, k, issue_j, None, unroll=True)
      return carry

    jax.lax.fori_loop(0, bs, issue, None)

    def drain(s, carry):
      small, window = dmas(s)

      @pl.when(small == 1)
      def _():
        window.wait()

      @pl.when(small == 0)
      def _():
        def drain_j(j, c):
          row_dma(s, j).wait()
          return c
        jax.lax.fori_loop(0, k, drain_j, None, unroll=True)
      return carry

    jax.lax.fori_loop(0, bs, drain, None)

    # dense VPU extraction over the staged windows (one-hot contraction,
    # NOT take_along_axis — the same rule as ops.uniform_sample_padded)
    epos = epos_ref[:]                               # [bs, k]
    row0 = meta_ref[:, 0]                            # [bs]
    small = meta_ref[:, 1]
    wflat = win[:].reshape(bs, nr * LANES)
    pos_l = jnp.clip(epos - row0[:, None] * LANES, 0, nr * LANES - 1)
    lanes_w = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nr * LANES), 2)
    small_nbrs = jnp.sum(wflat[:, None, :] * (pos_l[:, :, None] == lanes_w),
                         axis=-1)
    lanes_b = jax.lax.broadcasted_iota(jnp.int32, (1, 1, LANES), 2)
    big_nbrs = jnp.sum(big[:] * ((epos % LANES)[:, :, None] == lanes_b),
                       axis=-1)
    sel = jnp.where(small[:, None] == 1, small_nbrs, big_nbrs)  # [bs, k]
    out_ref[:] = jnp.concatenate(
        [sel, jnp.zeros((bs, LANES - k), jnp.int32)], axis=1)
  return kernel


def _gather_epos_pallas(blocks128, start, deg, safe_epos, k: int,
                        window: int, block_seeds: int, interpret: bool):
  """``indices[safe_epos]`` via per-seed staged windows (see module
  docstring); values at masked slots are whatever row 0 holds — callers
  mask them, exactly like the XLA path's ``indices[safe_epos]``."""
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  b = start.shape[0]
  assert window % LANES == 0 and window > 0
  nr = window // LANES + 1      # covers any start%128 alignment
  nbk = blocks128.shape[0]
  assert nbk >= nr, 'build_indices128(min_rows=nr) guarantees this'
  assert 0 < k <= LANES
  bs = min(block_seeds, b)
  pad = (-b) % bs
  row0 = jnp.clip(start // LANES, 0, nbk - nr).astype(jnp.int32)
  # every sampled position of a 'small' seed lies inside its window:
  # epos < start + deg <= row0*128 + nr*128 (clamped row0 only lowers
  # the base, and the window top then reaches the padded array end)
  small = ((start - row0 * LANES + deg) <= nr * LANES).astype(jnp.int32)
  plan = jnp.stack([row0, small], axis=1)            # [b, 2]
  epos32 = safe_epos.astype(jnp.int32)
  if pad:
    plan = jnp.concatenate(
        [plan, jnp.tile(jnp.array([[0, 1]], jnp.int32), (pad, 1))])
    epos32 = jnp.concatenate([epos32, jnp.zeros((pad, k), jnp.int32)])
  grid = (b + pad) // bs

  out = pl.pallas_call(
      _hop_kernel_factory(k, nr, nbk),
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=1,
          grid=(grid,),
          in_specs=[
              pl.BlockSpec(memory_space=pl.ANY),               # blocks128
              pl.BlockSpec((bs, k), lambda i, plan_ref: (i, 0)),   # epos
              pl.BlockSpec((bs, 2), lambda i, plan_ref: (i, 0)),   # meta
          ],
          out_specs=pl.BlockSpec((bs, LANES), lambda i, plan_ref: (i, 0)),
          scratch_shapes=[
              pltpu.VMEM((bs, nr, LANES), jnp.int32),
              pltpu.VMEM((bs, k, LANES), jnp.int32),
              pltpu.SemaphoreType.DMA((bs,)),
              pltpu.SemaphoreType.DMA((bs, k)),
          ],
      ),
      out_shape=jax.ShapeDtypeStruct((b + pad, LANES), jnp.int32),
      interpret=interpret,
  )(plan, blocks128, epos32, plan)
  return out[:b, :k]


@functools.partial(jax.jit,
                   static_argnames=('k', 'window', 'block_seeds',
                                    'interpret', 'force'))
def sample_hop_fused(indptr, indices, blocks128, seeds, seed_mask, k: int,
                     key, meta=None, window: int = 512,
                     block_seeds: int = 128, interpret: bool = False,
                     force: bool = False):
  """One fused uniform CSR hop; same output contract — and the same
  PRNG stream, bit for bit — as :func:`ops.uniform_sample`.

  Args:
    indptr/indices: the CSR (used by the fallback path and for
      ``meta=None`` row lookup).
    blocks128: :func:`build_indices128` aligned view (may be None —
      forces the XLA fallback).
    seeds/seed_mask/k/key/meta: exactly :func:`ops.uniform_sample`.
    window: staged segment span per seed (multiple of 128; autotune axis
      probed by benchmarks/prof_gather2.py). Seeds with deg > window
      take the per-sample row-DMA path — never a whole-batch fallback.
    block_seeds: seeds per grid step.
    interpret: run the Pallas interpreter (CPU parity tests).
    force: run the kernel off-TPU (tests); default falls back to the
      XLA hop off-TPU.

  Returns (nbrs [B, K], epos [B, K], mask [B, K]) — FILL/0-padded like
  ``uniform_sample``.
  """
  safe_seeds = jnp.where(seed_mask, seeds, 0)
  if meta is not None:
    row = meta[safe_seeds]
    start, deg = row[:, 0], row[:, 1]
  else:
    start = indptr[safe_seeds]
    deg = indptr[safe_seeds + 1] - start
  epos, mask = _draw(start, deg, seed_mask, k, key)
  safe_epos = jnp.where(mask, epos, 0)
  use_kernel = blocks128 is not None and (
      interpret or force or jax.default_backend() == 'tpu')
  if use_kernel:
    picked = _gather_epos_pallas(blocks128, start, deg, safe_epos, k,
                                 window, block_seeds, interpret)
  else:
    picked = indices[safe_epos]
  nbrs = jnp.where(mask, picked, FILL)
  return nbrs, safe_epos, mask


def _chunk_of(n: int) -> int:
  """Largest inner-reduction tile (multiple of LANES, <= 1024) dividing
  ``n`` — bounds every [128, tile] compare transient to <=512KB VMEM."""
  for c in (1024, 512, 256, 128):
    if n % c == 0:
      return c
  raise AssertionError(f'{n} is not a multiple of {LANES}')


def _level_kernel_factory(k, nr, nbk, bs, n_gather, s_fill, s_buf, c_pad,
                          limit, limit_pad):
  """Whole-fanout-level kernel: grid steps [0, n_gather) stage per-seed
  CSR windows and resolve the k draws (the sample+gather phases, shared
  with the hop kernel); the FINAL grid step resolves the dedup map
  in-kernel — membership against the node-buffer prefix (a node's
  position in the buffer IS its local index), within-level first
  occurrence, and value-determined ranks that assign new locals in
  ascending-id order, reproducing ops.induce_next_merge's assignment
  exactly without a single sort."""
  cjs = _chunk_of(s_buf)
  cjc = _chunk_of(c_pad)

  def kernel(plan_ref, misc_ref, blocks_ref, epos_ref, mask_ref, meta_ref,
             nodes_ref, cols_ref, block_ref, counts_ref, win, big, flat,
             val, winr, rank, fnd_b, pos_b, sem_w, sem_b):
    from jax.experimental import pallas as pl
    i = pl.program_id(0)

    # ---- gather phase: one seed block per step (hop-kernel core) --------
    @pl.when(i < n_gather)
    def _gather():
      def dmas(s):
        from jax.experimental.pallas import tpu as pltpu
        row0 = plan_ref[i * bs + s, 0]
        small = plan_ref[i * bs + s, 1]
        window = pltpu.make_async_copy(blocks_ref.at[pl.ds(row0, nr)],
                                       win.at[s], sem_w.at[s])
        return small, window

      def row_dma(s, j):
        from jax.experimental.pallas import tpu as pltpu
        r = jnp.clip(epos_ref[s, j] // LANES, 0, nbk - 1)
        return pltpu.make_async_copy(blocks_ref.at[r], big.at[s, j],
                                     sem_b.at[s, j])

      def issue(s, carry):
        small, window = dmas(s)

        @pl.when(small == 1)
        def _():
          window.start()

        @pl.when(small == 0)
        def _():
          def issue_j(j, c):
            row_dma(s, j).start()
            return c
          jax.lax.fori_loop(0, k, issue_j, None, unroll=True)
        return carry

      jax.lax.fori_loop(0, bs, issue, None)

      def drain(s, carry):
        small, window = dmas(s)

        @pl.when(small == 1)
        def _():
          window.wait()

        @pl.when(small == 0)
        def _():
          def drain_j(j, c):
            row_dma(s, j).wait()
            return c
          jax.lax.fori_loop(0, k, drain_j, None, unroll=True)
        return carry

      jax.lax.fori_loop(0, bs, drain, None)

      # dense one-hot extraction over the staged windows — byte for byte
      # the hop kernel's epilogue
      epos = epos_ref[:]                               # [bs, k]
      row0 = meta_ref[:, 0]                            # [bs]
      small = meta_ref[:, 1]
      wflat = win[:].reshape(bs, nr * LANES)
      pos_l = jnp.clip(epos - row0[:, None] * LANES, 0, nr * LANES - 1)
      lanes_w = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nr * LANES), 2)
      small_nbrs = jnp.sum(
          wflat[:, None, :] * (pos_l[:, :, None] == lanes_w), axis=-1)
      lanes_b = jax.lax.broadcasted_iota(jnp.int32, (1, 1, LANES), 2)
      big_nbrs = jnp.sum(big[:] * ((epos % LANES)[:, :, None] == lanes_b),
                         axis=-1)
      sel = jnp.where(small[:, None] == 1, small_nbrs, big_nbrs)  # [bs, k]
      base = i * (bs * k)
      flat[0, pl.ds(base, bs * k)] = sel.reshape(-1)
      val[0, pl.ds(base, bs * k)] = mask_ref[:].reshape(-1)

    # ---- dedup phase: the level's relabel map, in-register --------------
    @pl.when(i == n_gather)
    def _dedup():
      nn = misc_ref[0]                       # num_nodes before this level
      n_i = s_buf // LANES
      if s_buf > s_fill:
        # lane-alignment tail past the last written candidate: scratch is
        # uninitialized, so the validity flags there must be cleared
        # before any compare reads them
        val[0, pl.ds(s_fill, s_buf - s_fill)] = jnp.zeros(
            (s_buf - s_fill,), jnp.int32)

      def pass1(ci, carry):
        ds = pl.ds(ci * LANES, LANES)
        a = flat[0, ds].reshape(LANES, 1)
        av = val[0, ds].reshape(LANES, 1)
        apos = ci * LANES + jax.lax.broadcasted_iota(
            jnp.int32, (LANES, 1), 0)

        def memb(cj, acc):
          f2, p2 = acc
          ndc = nodes_ref[0, pl.ds(cj * cjc, cjc)].reshape(1, cjc)
          idc = cj * cjc + jax.lax.broadcasted_iota(
              jnp.int32, (1, cjc), 1)
          eq = ((a == ndc) & (idc < nn)).astype(jnp.int32)
          f2 = jnp.maximum(f2, jnp.max(eq, axis=1, keepdims=True))
          p2 = jnp.maximum(
              p2, jnp.max(jnp.where(eq > 0, idc, -1), axis=1,
                          keepdims=True))
          return f2, p2

        fnd, pos = jax.lax.fori_loop(
            0, c_pad // cjc, memb,
            (jnp.zeros((LANES, 1), jnp.int32),
             jnp.full((LANES, 1), -1, jnp.int32)))

        def dupl(cj, d):
          fc = flat[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          vc = val[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          pc = cj * cjs + jax.lax.broadcasted_iota(
              jnp.int32, (1, cjs), 1)
          hit = ((a == fc) & (vc > 0) & (pc < apos)).astype(jnp.int32)
          return jnp.maximum(d, jnp.max(hit, axis=1, keepdims=True))

        dup = jax.lax.fori_loop(0, s_buf // cjs, dupl,
                                jnp.zeros((LANES, 1), jnp.int32))
        winr[0, ds] = (av * (1 - fnd) * (1 - dup)).reshape(-1)
        fnd_b[0, ds] = fnd.reshape(-1)
        pos_b[0, ds] = pos.reshape(-1)
        return carry

      jax.lax.fori_loop(0, n_i, pass1, None)

      def pass2(ci, carry):
        ds = pl.ds(ci * LANES, LANES)
        a = flat[0, ds].reshape(LANES, 1)
        av = val[0, ds].reshape(LANES, 1)
        fnd = fnd_b[0, ds].reshape(LANES, 1)
        pos = pos_b[0, ds].reshape(LANES, 1)

        def rnk(cj, r):
          fc = flat[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          wc = winr[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          return r + jnp.sum(wc * (fc < a).astype(jnp.int32), axis=1,
                             keepdims=True)

        rk = jax.lax.fori_loop(0, s_buf // cjs, rnk,
                               jnp.zeros((LANES, 1), jnp.int32))
        rank[0, ds] = rk.reshape(-1)
        cols = jnp.where(fnd > 0, pos,
                         jnp.where(av > 0, nn + rk, -1))
        cols_ref[0, ds] = cols.reshape(-1)
        return carry

      jax.lax.fori_loop(0, n_i, pass2, None)

      num_new = jnp.sum(winr[0, :])
      num_kept = jnp.minimum(num_new, limit)
      counts_ref[0:1, :] = jnp.zeros((1, LANES), jnp.int32) + num_new

      def pass3(ri, carry):
        r = ri * LANES + jax.lax.broadcasted_iota(jnp.int32, (LANES, 1), 0)

        def bsel(cj, v):
          fc = flat[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          wc = winr[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          rc = rank[0, pl.ds(cj * cjs, cjs)].reshape(1, cjs)
          hit = wc * (rc == r).astype(jnp.int32)
          return v + jnp.sum(hit * fc, axis=1, keepdims=True)

        v = jax.lax.fori_loop(0, s_buf // cjs, bsel,
                              jnp.zeros((LANES, 1), jnp.int32))
        blk = jnp.where(r < num_kept, v, FILL)
        block_ref[0, pl.ds(ri * LANES, LANES)] = blk.reshape(-1)
        return carry

      jax.lax.fori_loop(0, limit_pad // LANES, pass3, None)

  return kernel


def _level_pallas(blocks128, start, deg, safe_epos, mask, nodes_prefix,
                  num_nodes, k: int, limit: int, window: int,
                  block_seeds: int, interpret: bool):
  """Run the fused level kernel. Returns (cols_raw [S], block
  [limit], num_new) — the relabel map (pre-truncation-mask), the
  ascending-id winner append block (FILL past num_kept), and the RAW
  new-unique count."""
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  b = start.shape[0]
  assert window % LANES == 0 and window > 0
  nr = window // LANES + 1
  nbk = blocks128.shape[0]
  assert nbk >= nr, 'build_indices128(min_rows=nr) guarantees this'
  assert 0 < k <= LANES
  bs = min(block_seeds, b)
  pad = (-b) % bs
  s_fill = (b + pad) * k
  s_buf = -(-s_fill // LANES) * LANES
  assert s_buf <= LEVEL_MAX_CANDIDATES, (
      f'fused level: {b} seeds x fanout {k} = {s_buf} padded candidates '
      f'exceeds LEVEL_MAX_CANDIDATES={LEVEL_MAX_CANDIDATES} (the '
      'in-kernel dedup is O(S^2) compares — route this plan through the '
      'hop kernel or the XLA merge engine instead)')
  c = nodes_prefix.shape[0]
  c_pad = -(-c // LANES) * LANES
  limit_pad = max(-(-limit // LANES) * LANES, LANES)

  row0 = jnp.clip(start // LANES, 0, nbk - nr).astype(jnp.int32)
  small = ((start - row0 * LANES + deg) <= nr * LANES).astype(jnp.int32)
  plan = jnp.stack([row0, small], axis=1)            # [b, 2]
  epos32 = safe_epos.astype(jnp.int32)
  mask32 = mask.astype(jnp.int32)
  if pad:
    plan = jnp.concatenate(
        [plan, jnp.tile(jnp.array([[0, 1]], jnp.int32), (pad, 1))])
    epos32 = jnp.concatenate([epos32, jnp.zeros((pad, k), jnp.int32)])
    mask32 = jnp.concatenate([mask32, jnp.zeros((pad, k), jnp.int32)])
  nodes_row = nodes_prefix.astype(jnp.int32).reshape(1, c)
  if c_pad > c:
    nodes_row = jnp.concatenate(
        [nodes_row, jnp.full((1, c_pad - c), FILL, jnp.int32)], axis=1)
  misc = jnp.asarray(num_nodes, jnp.int32).reshape(1)
  n_gather = (b + pad) // bs

  def gather_blk(i, plan_ref, misc_ref):
    return (jnp.minimum(i, n_gather - 1), 0)

  cols, block, counts = pl.pallas_call(
      _level_kernel_factory(k, nr, nbk, bs, n_gather, s_fill, s_buf,
                            c_pad, limit, limit_pad),
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=2,
          grid=(n_gather + 1,),
          in_specs=[
              pl.BlockSpec(memory_space=pl.ANY),           # blocks128
              pl.BlockSpec((bs, k), gather_blk),           # epos
              pl.BlockSpec((bs, k), gather_blk),           # mask
              pl.BlockSpec((bs, 2), gather_blk),           # meta (= plan)
              pl.BlockSpec((1, c_pad), lambda *_: (0, 0)),  # node prefix
          ],
          out_specs=[
              pl.BlockSpec((1, s_buf), lambda *_: (0, 0)),
              pl.BlockSpec((1, limit_pad), lambda *_: (0, 0)),
              pl.BlockSpec((1, LANES), lambda *_: (0, 0)),
          ],
          scratch_shapes=[
              pltpu.VMEM((bs, nr, LANES), jnp.int32),      # win
              pltpu.VMEM((bs, k, LANES), jnp.int32),       # big
              pltpu.VMEM((1, s_buf), jnp.int32),          # flat
              pltpu.VMEM((1, s_buf), jnp.int32),          # val
              pltpu.VMEM((1, s_buf), jnp.int32),          # winner
              pltpu.VMEM((1, s_buf), jnp.int32),          # rank
              pltpu.VMEM((1, s_buf), jnp.int32),          # found
              pltpu.VMEM((1, s_buf), jnp.int32),          # pos
              pltpu.SemaphoreType.DMA((bs,)),
              pltpu.SemaphoreType.DMA((bs, k)),
          ],
      ),
      out_shape=[
          jax.ShapeDtypeStruct((1, s_buf), jnp.int32),
          jax.ShapeDtypeStruct((1, limit_pad), jnp.int32),
          jax.ShapeDtypeStruct((1, LANES), jnp.int32),
      ],
      interpret=interpret,
  )(plan, misc, blocks128, epos32, mask32, plan, nodes_row)
  return cols[0, :b * k], block[0, :limit], counts[0, 0]


@functools.partial(jax.jit,
                   static_argnames=('k', 'prefix_cap', 'max_new', 'final',
                                    'window', 'block_seeds', 'interpret',
                                    'force'))
def sample_level_fused(indptr, indices, blocks128, seeds, seed_mask,
                       k: int, key, state, src_idx, meta=None, *,
                       prefix_cap: int, max_new=None, final: bool = False,
                       window: int = 512, block_seeds: int = 128,
                       interpret: bool = False, force: bool = False):
  """One whole fanout LEVEL — sample + gather + exact cross-hop dedup —
  in a single fused kernel pass, bit-identical to ``ops.uniform_sample``
  followed by :func:`ops.induce_next_merge`.

  The draw (offsets, mask, epos) stays OUTSIDE the kernel, byte for byte
  ``ops.uniform_sample``'s stream off the same counter-addressed key —
  the kernel resolves ``indices[epos]`` via staged windows (the hop
  kernel's phases) and then the dedup map in the same pass: membership
  against the node-buffer prefix (a node's buffer position IS its local
  index), within-level first occurrence, and value-determined ranks
  (``rank(v) = #{winner values < v}``) that assign new locals in
  ascending-id order — exactly the merge engine's sorted-rank
  assignment, duplicates sharing their winner's local by construction,
  with no sort anywhere in the kernel.

  Args:
    indptr/indices/blocks128/seeds/seed_mask/k/key/meta/window/
    block_seeds/interpret/force: as :func:`sample_hop_fused` (``seeds``
    is this level's frontier).
    state: the :class:`ops.MergeInducerState` before this level. The
      kernel path leaves the sorted view STALE (it never reads it);
      the XLA fallback maintains it (``update_view=not final``) so
      off-TPU programs remain bit-identical to the unfused engine.
    src_idx: frontier local indices (edge source relabel).
    prefix_cap: static occupancy bound before this level (the merge
      layout offset — bounds the in-kernel membership scan).
    max_new: static clamp on nodes kept (the plan's next-hop cap).
    final: last level induced on this state (fallback skips its view
      rebuild, exactly like the unfused engine's ``final`` hop).

  Returns ``(state', out, epos, mask)`` with ``out`` the
  ``induce_next_merge`` output dict.
  """
  f = seeds.shape[0]
  size = f * k
  cap = state.nodes.shape[0]
  c = min(prefix_cap, cap)
  limit = min(size, cap - c, size if max_new is None else max_new)

  safe_seeds = jnp.where(seed_mask, seeds, 0)
  if meta is not None:
    row = meta[safe_seeds]
    start, deg = row[:, 0], row[:, 1]
  else:
    start = indptr[safe_seeds]
    deg = indptr[safe_seeds + 1] - start
  epos, mask = _draw(start, deg, seed_mask, k, key)
  safe_epos = jnp.where(mask, epos, 0)

  use_kernel = blocks128 is not None and (
      interpret or force or jax.default_backend() == 'tpu')
  if not use_kernel:
    picked = indices[safe_epos]
    nbrs = jnp.where(mask, picked, FILL)
    state2, out = induce_next_merge(state, src_idx, nbrs, mask,
                                    prefix_cap=prefix_cap, max_new=max_new,
                                    update_view=not final)
    return state2, out, safe_epos, mask

  nodes_prefix = jax.lax.slice(state.nodes, (0,), (c,))
  cols_raw, block, num_new = _level_pallas(
      blocks128, start, deg, safe_epos, mask, nodes_prefix,
      state.num_nodes, k, limit, window, block_seeds, interpret)
  num_new = num_new.astype(jnp.int32)
  num_kept = jnp.minimum(num_new, limit)

  flat_mask = mask.reshape(-1)
  emask = flat_mask & (cols_raw >= 0) & \
      (cols_raw < state.num_nodes + num_kept)
  cols = jnp.where(emask, cols_raw, -1)
  rows = jnp.where(emask, jnp.repeat(src_idx.astype(jnp.int32), k), -1)

  block = block.astype(state.nodes.dtype)
  nodes = jax.lax.dynamic_update_slice(state.nodes, block,
                                       (state.num_nodes,))
  frontier = jnp.concatenate(
      [block, jnp.full((size - limit,), FILL, block.dtype)]) \
      if limit < size else block
  fin = jnp.arange(size) < num_kept
  frontier_idx = jnp.where(
      fin, state.num_nodes + jnp.arange(size, dtype=jnp.int32), -1)

  out = dict(rows=rows, cols=cols, edge_mask=emask, frontier=frontier,
             frontier_idx=frontier_idx, frontier_mask=fin,
             num_new=num_new)
  state2 = MergeInducerState(nodes, state.num_nodes + num_kept,
                             state.sorted_ids, state.sorted_loc)
  return state2, out, safe_epos, mask
