"""Fixed-shape random negative edge sampling.

TPU-native replacement for the reference negative samplers
(/root/reference/graphlearn_torch/csrc/cuda/random_negative_sampler.cu and
csrc/cpu/random_negative_sampler.cc): draw candidate (row, col) pairs, reject
pairs present in the CSR via binary search, and keep the first ``num_samples``
survivors. The CUDA version loops trials with thrust compaction and a D2H
count; here all ``trials * num_samples`` candidates are drawn and tested in
one fixed-shape pass, and compaction is an argsort — no host sync.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from .neighbor import edge_in_csr


def sort_csr_segments(indptr: np.ndarray, indices: np.ndarray):
  """Host-side: sort ``indices`` within each row segment (binary-search
  membership requires sorted rows). Returns (sorted_indices, perm) where
  ``perm`` maps sorted edge positions back to original CSR positions."""
  indptr = np.asarray(indptr)
  indices = np.asarray(indices)
  rows = np.repeat(np.arange(indptr.shape[0] - 1),
                   np.diff(indptr))
  perm = np.lexsort((indices, rows))
  return indices[perm], perm


@functools.partial(jax.jit,
                   static_argnames=('num_samples', 'trials', 'padding'))
def random_negative_sample(indptr, sorted_indices, num_src, num_dst,
                           num_samples: int, key, trials: int = 5,
                           padding: bool = False):
  """Sample (row, col) pairs absent from the CSR.

  Args:
    indptr/sorted_indices: CSR with row-sorted indices
      (:func:`sort_csr_segments`).
    num_src/num_dst: id ranges for rows/cols.
    num_samples: number of pairs wanted (static).
    trials: candidate multiplier; ``trials * num_samples`` candidates are
      tested (reference semantics: retry up to ``trials_num`` rounds,
      random_negative_sampler.cu).
    padding: non-strict mode — pad any shortfall with random (possibly
      positive) pairs so the output is always full (reference ``padding``
      flag).

  Returns (rows [num_samples], cols [num_samples], mask [num_samples]).
  """
  total = num_samples * trials
  kr, kc = jax.random.split(key)
  rows = jax.random.randint(kr, (total,), 0, num_src, dtype=jnp.int32)
  cols = jax.random.randint(kc, (total,), 0, num_dst, dtype=jnp.int32)
  is_edge = edge_in_csr(indptr, sorted_indices, rows, cols)
  valid = ~is_edge
  # Stable partition: valid candidates first, in draw order.
  order = jnp.argsort(jnp.where(valid, 0, 1), stable=True)
  take = order[:num_samples]
  out_rows = rows[take]
  out_cols = cols[take]
  out_mask = valid[take]
  if padding:
    out_mask = jnp.ones_like(out_mask)
  return out_rows, out_cols, out_mask


def random_negative_sample_local(row_ids, indptr_loc, sorted_indices,
                                 num_dst: int, num_samples: int, key,
                                 trials: int = 5, strict: bool = False):
  """Shard-local negative sampling for the distributed engine.

  Each shard draws source rows from ITS OWN partition's local CSR.
  Candidate (local_row, dst) pairs are rejected when present in the
  local CSR segment; survivors map to global ids via ``row_ids``.

  STRICTNESS: the engine's partition invariant is that a row's COMPLETE
  out-edge set lives on its owner's shard (the exchange samples node v
  only on owner(v) — splitting a row across shards would undersample),
  so the local membership check is globally complete for locally-drawn
  sources. ``strict=False`` (reference parity: its distributed path
  cannot check remote edges at all, dist_neighbor_sampler.py:380-383)
  always emits ``num_samples`` pairs, letting a candidate that stayed
  an edge through every trial slip through. ``strict=True`` marks such
  slots invalid instead — every VALID pair is guaranteed a non-edge,
  beyond the reference's distributed contract.

  Traced inside shard_map (no jit wrapper; the caller's program compiles
  it). Returns (src_global [num_samples], dst [num_samples],
  valid [num_samples]) — ``valid`` is all-False on a shard that owns zero
  rows of this CSR (skewed partitioning of a rare edge type), so callers
  must mask those slots out of the seed union instead of treating the
  INT_MAX row padding as node ids.
  """
  num_actual = jnp.sum(row_ids != jnp.iinfo(row_ids.dtype).max
                       ).astype(jnp.int32)
  num_rows = jnp.maximum(num_actual, 1)
  total = num_samples * trials
  kr, kc = jax.random.split(key)
  u = jax.random.randint(kr, (total,), 0, jnp.int32(2 ** 30),
                         dtype=jnp.int32) % num_rows
  cols = jax.random.randint(kc, (total,), 0, num_dst, dtype=jnp.int32)
  is_edge = edge_in_csr(indptr_loc, sorted_indices, u, cols)
  order = jnp.argsort(jnp.where(is_edge, 1, 0), stable=True)
  take = order[:num_samples]
  valid = jnp.broadcast_to(num_actual > 0, (num_samples,))
  if strict:
    valid = valid & ~is_edge[take]
  src = jnp.where(valid, row_ids[u[take]].astype(jnp.int32), -1)
  return src, jnp.where(valid, cols[take], -1), valid
