"""Tree-mode (no-dedup) inducer: positional relabeling, zero random access.

The map/sort inducers give reference-parity EXACT dedup (every global id
appears once in the batch), but on TPU their random scatters/gathers over
[num_nodes] tables dominate the whole sample — profiler-measured 35 of
53.7 ms per products-scale batch (PERF.md). This inducer is the TPU-first
alternative: every sampled slot IS its own node (GraphSAGE's computation-
TREE semantics — the same unrolling as the reference's pyg-v1
NeighborSampler path), so local index = hop offset + slot position and the
node buffer is written with ONE contiguous dynamic-update-slice per hop.
No table, no scatter, no gather.

Trade: duplicate global ids occupy multiple slots (features gather per
slot — buffers are capacity-sized in all modes, so padded compute and
feature bytes are UNCHANGED), and a node re-sampled at a deeper hop gets a
fresh leaf copy instead of merging into its earlier occurrence — the
standard sampled-computation-tree GNN semantics. num_nodes counts VALID
slots (not unique ids).
"""
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .unique import FILL


class TreeInducerState(NamedTuple):
  nodes: jax.Array      # [cap] global ids, FILL at invalid slots
  num_nodes: jax.Array  # scalar int32: count of VALID slots


@functools.partial(jax.jit, static_argnames=('capacity',))
def init_node_tree(seeds: jax.Array, seed_mask: jax.Array, capacity: int):
  """Start a batch: seed slot i == local index i (no dedup).

  Same return contract as init_node_map; ``inverse`` is the identity
  (masked -1) since every seed position owns its slot.
  """
  b = seeds.shape[0]
  nodes = jnp.full((capacity,), FILL, seeds.dtype)
  nodes = jax.lax.dynamic_update_slice(
      nodes, jnp.where(seed_mask, seeds, FILL), (0,))
  count = jnp.sum(seed_mask).astype(jnp.int32)
  inverse = jnp.where(seed_mask, jnp.arange(b, dtype=jnp.int32), -1)
  return (TreeInducerState(nodes, count), jnp.where(seed_mask, seeds, FILL),
          seed_mask, inverse)


@functools.partial(jax.jit, static_argnames=('capacity',))
def init_empty_tree(capacity: int, dtype=jnp.int32):
  """A tree state with no nodes yet (hetero: node types first reached
  mid-hop)."""
  return TreeInducerState(jnp.full((capacity,), FILL, dtype),
                          jnp.asarray(0, jnp.int32))


@functools.partial(jax.jit, static_argnames=('offset',))
def induce_next_tree(state: TreeInducerState, src_idx: jax.Array,
                     nbrs: jax.Array, nbr_mask: jax.Array, offset: int):
  """Absorb one hop: the hop block occupies slots
  [offset, offset + F*K) — ``offset`` is the STATIC prefix sum of hop
  capacities (the caller's positional layout plan).
  """
  f, k = nbrs.shape
  size = f * k
  flat = nbrs.reshape(-1)
  flat_mask = nbr_mask.reshape(-1)
  local = offset + jnp.arange(size, dtype=jnp.int32)
  nodes = jax.lax.dynamic_update_slice(
      state.nodes, jnp.where(flat_mask, flat, FILL), (offset,))
  num_new = jnp.sum(flat_mask).astype(jnp.int32)
  out = dict(
      rows=jnp.where(flat_mask, jnp.repeat(src_idx.astype(jnp.int32), k),
                     -1),
      cols=jnp.where(flat_mask, local, -1),
      edge_mask=flat_mask,
      frontier=jnp.where(flat_mask, flat, FILL),
      frontier_idx=local,
      frontier_mask=flat_mask,
      num_new=num_new)
  return TreeInducerState(nodes, state.num_nodes + num_new), out
