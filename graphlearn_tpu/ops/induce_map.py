"""Direct-address (map-based) inducer: dedup/relabel without sorts.

The sort-based inducer (ops/induce.py) pays O(cap log cap) XLA sorts per
hop — the dominant cost of a multi-hop sample at products scale. This
variant is the TPU answer to the reference's GPU open-addressing hash table
(/root/reference/graphlearn_torch/include/hash_table.cuh): a dense [N]
table mapping global node id -> local index + 1 (0 = absent). All steps are
gathers, scatters and one cumsum over the hop block — no sorts:

  1. winner pick: scatter position ids into the table slot; the stored
     winner dedups duplicates within the hop (any winner is correct, like
     the reference's atomicCAS first-writer-wins, hash_table.cuh:43-64).
  2. membership: one gather against the table.
  3. new-node ranks: cumsum over the hop block.
  4. state update: scatter new local indices into the table and new ids
     into the node list.

Cost scales with num_nodes only through the one-time table allocation
(int32[N] = 4 bytes/node; 1M nodes = 4MB HBM). For billion-node graphs use
the sort-based inducer or shard the table (the distributed sampler's
partitions each hold a shard-sized table).
"""
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .unique import FILL


class MapInducerState(NamedTuple):
  table: jax.Array      # [N] global id -> local index + 1 (0 = absent)
  nodes: jax.Array      # [cap] global ids, FILL-padded; pos == local idx
  num_nodes: jax.Array  # scalar int32


@functools.partial(jax.jit, static_argnames=('capacity', 'num_graph_nodes'))
def init_node_map(seeds: jax.Array, seed_mask: jax.Array, capacity: int,
                  num_graph_nodes: int):
  """Start a batch: dedup seeds into local indices (seeds first).

  Returns (state, uniq_seeds [B], uniq_mask [B], inverse [B]); unlike the
  sort-based init_node, uniq_seeds keeps FIRST-OCCURRENCE order rather
  than ascending order (both satisfy the contract: position == local idx).
  """
  b = seeds.shape[0]
  table = jnp.zeros((num_graph_nodes,), jnp.int32)
  safe = jnp.where(seed_mask, seeds, 0)
  pos = jnp.arange(b, dtype=jnp.int32)
  # winner: plain set-scatter; among duplicates exactly one position's
  # write survives and `probe[id] == pos` selects it (any winner is
  # correct — same contract as the reference's atomicCAS first-writer.
  # set-scatter measures ~4x faster than min-scatter on TPU).
  probe = jnp.full((num_graph_nodes,), b, jnp.int32)
  probe = probe.at[jnp.where(seed_mask, safe, num_graph_nodes)].set(
      pos, mode='drop')
  winner = seed_mask & (probe[safe] == pos)
  rank = (jnp.cumsum(winner) - 1).astype(jnp.int32)
  count = jnp.sum(winner).astype(jnp.int32)
  nodes = jnp.full((capacity,), FILL, seeds.dtype)
  nodes = nodes.at[jnp.where(winner, rank, capacity)].set(seeds,
                                                          mode='drop')
  table = table.at[jnp.where(winner, safe, num_graph_nodes)].set(
      rank + 1, mode='drop')
  uniq = nodes[:b]
  uniq_mask = jnp.arange(b) < count
  inverse = jnp.where(seed_mask, table[safe] - 1, -1)
  return MapInducerState(table, nodes, count), uniq, uniq_mask, inverse


@functools.partial(jax.jit, static_argnames=('compact_frontier',))
def induce_next_map(state: MapInducerState, src_idx: jax.Array,
                    nbrs: jax.Array, nbr_mask: jax.Array,
                    compact_frontier: bool = True):
  """Absorb one hop (same contract as ops.induce.induce_next).

  ``compact_frontier=False`` emits the next-hop frontier POSITIONALLY
  (mask = winner) instead of scatter-compacting it — saves two
  S-element scatters per hop (~7 ms/batch at products scale, measured).
  Only valid when the consumer keeps the frontier's full width (no
  node_budget truncation): a truncating consumer must take the compact
  form so the first `budget` entries are real winners.
  """
  f, k = nbrs.shape
  size = f * k
  n_table = state.table.shape[0]
  flat = nbrs.reshape(-1)
  flat_mask = nbr_mask.reshape(-1)
  safe = jnp.where(flat_mask, flat, 0)

  existing = state.table[safe]                     # local idx + 1, 0 absent
  is_new_id = flat_mask & (existing == 0)
  # one winner among duplicates of each new id via set-scatter (see
  # init_node_map note)
  pos = jnp.arange(size, dtype=jnp.int32)
  probe = jnp.full((n_table,), size, jnp.int32)
  probe = probe.at[jnp.where(is_new_id, safe, n_table)].set(pos,
                                                            mode='drop')
  winner = is_new_id & (probe[safe] == pos)
  rank = (jnp.cumsum(winner) - 1).astype(jnp.int32)
  num_new = jnp.sum(winner).astype(jnp.int32)
  new_idx = state.num_nodes + rank

  nodes = state.nodes.at[jnp.where(winner, new_idx,
                                   state.nodes.shape[0])].set(flat,
                                                              mode='drop')
  table = state.table.at[jnp.where(winner, safe, n_table)].set(
      new_idx + 1, mode='drop')

  local = jnp.where(flat_mask, table[safe] - 1, -1)
  rows = jnp.where(flat_mask, jnp.repeat(src_idx.astype(jnp.int32), k), -1)

  if compact_frontier:
    slot = jnp.where(winner, rank, size)
    frontier = jnp.full((size,), FILL, flat.dtype).at[slot].set(
        flat, mode='drop')
    frontier_idx = jnp.full((size,), -1, jnp.int32).at[slot].set(
        new_idx, mode='drop')
    frontier_mask = jnp.arange(size) < num_new
  else:
    frontier = jnp.where(winner, flat, FILL)
    frontier_idx = jnp.where(winner, new_idx, -1)
    frontier_mask = winner

  out = dict(rows=rows, cols=local, edge_mask=flat_mask,
             frontier=frontier, frontier_idx=frontier_idx,
             frontier_mask=frontier_mask, num_new=num_new)
  return MapInducerState(table, nodes, state.num_nodes + num_new), out
