"""Masked, fixed-shape unique/dedup primitives.

TPU-native replacement for the reference's GPU open-addressing hash table
(/root/reference/graphlearn_torch/include/hash_table.cuh): XLA has no atomics
for a device hash table, and dynamic output sizes break jit, so dedup is
sort-based over fixed-size buffers with validity masks. All functions are
jittable with static ``size``.
"""
import functools

import jax
import jax.numpy as jnp

FILL = -1  # sentinel for invalid/padded ids (all real ids are >= 0)


@functools.partial(jax.jit, static_argnames=('size',))
def masked_unique(ids: jax.Array, mask: jax.Array, size: int):
  """Deduplicate ``ids[mask]`` into a fixed-size buffer.

  Returns:
    uniq:    [size] unique values in ascending order, FILL-padded.
    count:   scalar number of valid uniques.
    inverse: [N] index into ``uniq`` for each input position (-1 where masked).
  """
  n = ids.shape[0]
  assert size >= 1
  big = jnp.iinfo(ids.dtype).max
  x = jnp.where(mask, ids, big)
  order = jnp.argsort(x)
  xs = x[order]
  is_first = jnp.concatenate(
      [jnp.ones((1,), dtype=bool), xs[1:] != xs[:-1]])
  valid = xs != big
  is_new = is_first & valid
  uidx = jnp.cumsum(is_new) - 1          # unique slot of each sorted element
  count = jnp.sum(is_new)
  uniq = jnp.full((size,), FILL, dtype=ids.dtype)
  uniq = uniq.at[jnp.where(is_new, uidx, size)].set(xs, mode='drop')
  inverse = jnp.zeros((n,), dtype=jnp.int32)
  inverse = inverse.at[order].set(uidx.astype(jnp.int32))
  inverse = jnp.where(mask, inverse, -1)
  return uniq, count, inverse


def searchsorted_membership(sorted_vals: jax.Array, queries: jax.Array):
  """Membership of ``queries`` in ascending ``sorted_vals`` (may contain
  int-max padding at the tail). Returns (found, pos) where ``pos`` indexes
  ``sorted_vals`` (clamped)."""
  pos = jnp.searchsorted(sorted_vals, queries)
  pos = jnp.clip(pos, 0, sorted_vals.shape[0] - 1)
  found = sorted_vals[pos] == queries
  return found, pos
