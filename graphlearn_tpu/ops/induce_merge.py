"""Merge-sort exact inducer: cross-hop dedup/relabel built on sorts only.

The third (and fastest) exact-dedup engine, alongside the direct-address
table (ops/induce_map.py) and the legacy searchsorted engine
(ops/induce.py). Same semantic contract as the reference's GPU hash-table
inducer (/root/reference/graphlearn_torch/include/hash_table.cuh:43-84,
csrc/cuda/inducer.cu:95-165): every node sampled within a batch gets one
globally-unique local index; which duplicate "wins" is unspecified (the
reference takes atomicCAS first-writer; this engine takes the
first-in-flat-order occurrence).

Why sorts: on TPU (v5e device-trace, benchmarks/prof_dedup.py) random
element scatters/gathers run at ~140-200 M transactions/s regardless of
table size — HBM-transaction-bound, so the [N]-table engine's 6 random
ops/hop cost ~30 ms/batch at products scale. A key+payload `lax.sort` of
the same volume runs 3-5x faster than ONE such gather (768k pairs =
1.2 ms: lane-parallel bitonic networks are dense VPU work). This engine
therefore does per-hop dedup + cross-hop membership with one merged sort
and two compaction sorts, zero random access:

  sorted-view invariant: state carries (sorted_ids, sorted_loc) — the
  current node set ascending, with each id's local index. Only the first
  ``prefix_cap`` slots (the static max node count before this hop, i.e.
  the same per-hop offset the tree layout uses) can be occupied, so each
  hop touches a prefix that grows with the hop, not the full capacity.

  per hop (C = prefix_cap, S = frontier*k candidates):
    1. ONE sort of [C+S]: keys = (state sorted ids ++ candidate ids),
       second key orders state entries before candidates of the same id
       and candidate duplicates by flat position. First-occurrence
       candidates are the new nodes; their rank (cumsum) assigns local
       indices num_nodes+0.., and a segmented fill-forward (associative
       scan — dense, log-depth) broadcasts each group's local index to
       every duplicate.
    2. compaction sort #2 restores candidate results to flat order (the
       edge-output contract matches nbrs.reshape(-1), like the other
       engines) — a sort is ~3x cheaper than the equivalent unsort
       scatter on TPU.
    3. compaction sort #3 packs the winners into the append block: one
       contiguous dynamic-update-slice extends ``nodes``, and the same
       block IS the (compact) next-hop frontier.
    4. compaction sort #4 rebuilds the sorted view for the next hop
       (skipped on the final hop via ``update_view=False``).

Memory scales with the batch only (no [N] table), so this engine also
replaces the legacy engine for billion-node graphs.
"""
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .unique import FILL, masked_unique

# payload encoding: state entries carry their local index (< _MARK);
# candidates carry _MARK + flat position. Static capacities above 4M
# nodes/edges per batch would alias — asserted at trace time.
_MARK = 1 << 22


class MergeInducerState(NamedTuple):
  nodes: jax.Array       # [cap] global ids, FILL-padded; pos == local idx
  num_nodes: jax.Array   # scalar int32
  sorted_ids: jax.Array  # [cap] ascending ids, INT-MAX-padded
  sorted_loc: jax.Array  # [cap] local index of sorted_ids (-1 padded)


def _seg_fill(vals: jax.Array, flags: jax.Array) -> jax.Array:
  """Broadcast ``vals`` at flagged positions forward until the next flag
  (segmented fill).

  Implemented as THREE packed cummaxes instead of an associative scan:
  the scan's log-depth slice/concat cascade lowers to ~40 small XLA ops
  per call (~1 ms/batch of pure op overhead at products scale, measured
  in the bench trace), while a cummax is one fused op. Packing rides the
  group rank in the high bits — cummax then always selects the CURRENT
  group's value — with the payload split into 3 bytes so everything
  fits int32: group rank < 2^23, values in [0, 2^24). Positions before
  the first flag return garbage (callers mask them; in sorted-key order
  the first valid element is always a flag).
  """
  n = vals.shape[0]
  assert n < (1 << 23), 'seg_fill capacity exceeds packed-cummax bound'
  grp = jnp.cumsum(flags.astype(jnp.int32))          # <= n < 2^23
  v = jnp.where(flags, vals, 0)
  b0 = jax.lax.cummax((grp << 8) | (v & 0xFF))
  b1 = jax.lax.cummax((grp << 8) | ((v >> 8) & 0xFF))
  b2 = jax.lax.cummax((grp << 8) | ((v >> 16) & 0xFF))
  return ((b0 & 0xFF) | ((b1 & 0xFF) << 8) | ((b2 & 0xFF) << 16))


@functools.partial(jax.jit, static_argnames=('capacity',))
def init_node_merge(seeds: jax.Array, seed_mask: jax.Array, capacity: int):
  """Start a batch: dedup seeds into local indices (ascending order, like
  the legacy sort engine). Returns (state, uniq [B], uniq_mask [B],
  inverse [B])."""
  b = seeds.shape[0]
  uniq, count, inverse = masked_unique(seeds, seed_mask, size=b)
  big = jnp.iinfo(seeds.dtype).max
  nodes = jnp.full((capacity,), FILL, seeds.dtype).at[:b].set(uniq)
  sorted_ids = jnp.full((capacity,), big, seeds.dtype)
  sorted_ids = sorted_ids.at[:b].set(jnp.where(uniq == FILL, big, uniq))
  sorted_loc = jnp.full((capacity,), -1, jnp.int32)
  sorted_loc = sorted_loc.at[:b].set(
      jnp.where(uniq == FILL, -1, jnp.arange(b, dtype=jnp.int32)))
  state = MergeInducerState(nodes, count.astype(jnp.int32), sorted_ids,
                            sorted_loc)
  return state, uniq, jnp.arange(b) < count, inverse


@functools.partial(jax.jit, static_argnames=('capacity', 'dtype'))
def init_empty_merge(capacity: int, dtype=jnp.int32):
  """A merge-inducer state with no nodes yet (hetero lazy per-type
  states)."""
  big = jnp.iinfo(dtype).max
  return MergeInducerState(
      jnp.full((capacity,), FILL, dtype),
      jnp.asarray(0, jnp.int32),
      jnp.full((capacity,), big, dtype),
      jnp.full((capacity,), -1, jnp.int32))


@functools.partial(jax.jit,
                   static_argnames=('prefix_cap', 'max_new',
                                    'update_view'))
def induce_next_merge(state: MergeInducerState, src_idx: jax.Array,
                      nbrs: jax.Array, nbr_mask: jax.Array,
                      prefix_cap: int, max_new=None,
                      update_view: bool = True):
  """Absorb one hop (same output contract as ops.induce.induce_next:
  edge arrays in ``nbrs.reshape(-1)`` order, compact frontier).

  Args:
    prefix_cap: static max node count BEFORE this hop — under clamped
      plans, the sum of clamped per-hop frontier caps; bounds the
      sorted-view prefix this hop must merge against, and (with
      ``max_new``) keeps the contiguous node append statically in
      bounds.
    max_new: static clamp on nodes KEPT this hop (the plan's
      ``caps[i+1]``). None = the hop's full candidate width (valid for
      unclamped plans, where capacity = sum of full widths).
    update_view: skip the sorted-view rebuild (one compaction sort) when
      no further hop will be induced on this state (the final hop).
  """
  f, k = nbrs.shape
  size = f * k
  cap = state.nodes.shape[0]
  c = min(prefix_cap, cap)
  # encoding bounds: state payloads (local idx < cap) must stay below
  # _MARK, and candidate payloads (_MARK + pos, pos < size) must fit int32
  assert cap <= _MARK and _MARK + size < 2 ** 31, \
      'batch capacity exceeds payload encoding'
  # _seg_fill packs its payload into 3 bytes: every value it carries here
  # (tentative local idx new_idx < num_nodes + num_new <= cap + size) must
  # fit 2^24. Asserted directly so a future bump of _MARK or the seg-fill
  # capacity bound fails at trace time instead of corrupting local indices.
  assert cap + size < (1 << 24), \
      'cap + hop size exceeds the seg_fill 3-byte payload bound'
  big = jnp.iinfo(state.nodes.dtype).max

  flat = nbrs.reshape(-1).astype(state.nodes.dtype)
  flat_mask = nbr_mask.reshape(-1)

  # -- sort #1: merged (state-prefix ++ candidates) ------------------------
  keys = jnp.concatenate([
      jax.lax.slice(state.sorted_ids, (0,), (c,)),
      jnp.where(flat_mask, flat, big)])
  payload = jnp.concatenate([
      jax.lax.slice(state.sorted_loc, (0,), (c,)),
      _MARK + jnp.arange(size, dtype=jnp.int32)])
  keys_s, pay_s = jax.lax.sort((keys, payload), num_keys=2)

  valid = keys_s != big
  is_state = pay_s < _MARK
  first = valid & jnp.concatenate([
      jnp.ones((1,), bool), keys_s[1:] != keys_s[:-1]])
  winner = first & ~is_state                     # first occurrence, no
  rank = (jnp.cumsum(winner) - 1).astype(jnp.int32)   # state entry before
  num_new = jnp.sum(winner).astype(jnp.int32)
  limit = min(size, cap - c, size if max_new is None else max_new)
  num_kept = jnp.minimum(num_new, limit)
  new_idx = state.num_nodes + rank
  base = jnp.where(is_state, pay_s, new_idx)     # local idx at each first
  local_all = _seg_fill(jnp.where(first, base, -1), first)

  # -- sort #2: candidate locals back to flat order ------------------------
  pos_key = jnp.where(is_state, size, pay_s - _MARK)
  cols_sorted = jnp.where(valid & ~is_state, local_all, -1)
  _, cols_full = jax.lax.sort((pos_key, cols_sorted), num_keys=1)
  cols = jax.lax.slice(cols_full, (0,), (size,))
  # edges whose target winner was overflow-truncated (local idx past the
  # stored region) must NOT stay valid — models would silently aggregate
  # clamped-garbage rows. No-op on unclamped plans (cols < new_total
  # always holds there).
  emask = flat_mask & (cols >= 0) & (cols < state.num_nodes + num_kept)
  cols = jnp.where(emask, cols, -1)
  rows = jnp.where(emask, jnp.repeat(src_idx.astype(jnp.int32), k), -1)

  # -- sort #3: winners -> contiguous append block (also the frontier) -----
  # Clamped-growth invariant: callers pass prefix_cap = the CLAMPED
  # occupancy bound before this hop (sum of clamped frontier caps), so
  # num_nodes <= c by induction and a block of limit = min(size, cap-c)
  # always fits — the append is one contiguous dynamic-update-slice on
  # every plan, including node_budget / frontier_caps-clamped ones.
  # Under overflow (num_new > limit, detectable as
  # num_sampled_nodes[i+1] > caps[i+1]) the extra winners are TRUNCATED:
  # not stored, not in the frontier — num_nodes stays <= capacity.
  wkey = jnp.where(winner, rank, size + c)
  _, block_full = jax.lax.sort((wkey, keys_s), num_keys=1)
  in_new = jnp.arange(limit) < num_kept
  block = jnp.where(in_new, jax.lax.slice(block_full, (0,), (limit,)),
                    FILL)
  nodes = jax.lax.dynamic_update_slice(state.nodes, block,
                                       (state.num_nodes,))
  frontier = jnp.concatenate(
      [block, jnp.full((size - limit,), FILL, block.dtype)]) \
      if limit < size else block
  fin = jnp.arange(size) < num_kept
  frontier_idx = jnp.where(
      fin, state.num_nodes + jnp.arange(size, dtype=jnp.int32), -1)

  # -- sort #4: new sorted view prefix [c+size] ----------------------------
  if update_view:
    # overflow-truncated winners (rank >= limit) must not enter the view
    # either — their ids were never stored
    keep = valid & (is_state | (winner & (rank < limit)))
    sid, sloc = jax.lax.sort((jnp.where(keep, keys_s, big),
                              jnp.where(keep, local_all, -1)), num_keys=1)
    if c + size < cap:
      sorted_ids = jnp.concatenate(
          [sid, jax.lax.slice(state.sorted_ids, (c + size,), (cap,))])
      sorted_loc = jnp.concatenate(
          [sloc, jax.lax.slice(state.sorted_loc, (c + size,), (cap,))])
    else:
      sorted_ids, sorted_loc = sid[:cap], sloc[:cap]
  else:
    sorted_ids, sorted_loc = state.sorted_ids, state.sorted_loc

  # num_new reports the RAW new-unique count (overflow detection:
  # num_sampled_nodes[i+1] > caps[i+1]); state growth is clamped so the
  # occupancy invariant holds on every plan
  out = dict(rows=rows, cols=cols, edge_mask=emask, frontier=frontier,
             frontier_idx=frontier_idx, frontier_mask=fin,
             num_new=num_new)
  return MergeInducerState(nodes, state.num_nodes + num_kept, sorted_ids,
                           sorted_loc), out
