"""Fixed-shape neighbor sampling over an HBM-resident CSR.

TPU-native replacement for the reference CUDA sampler
(/root/reference/graphlearn_torch/csrc/cuda/random_sampler.cu). The CUDA path
computes exact per-seed neighbor counts, a prefix sum, a D2H sync, and a
variable-size output (random_sampler.cu:267-307); on TPU that sync and dynamic
shape would break jit, so sampling emits a dense ``[B, K]`` buffer with a
validity mask:

  deg <= K: take all neighbors in order (mask pads the tail) — matches the
            reference's "keep all" branch.
  deg >  K: K uniform draws with replacement (matches the reference CPU
            sampler semantics, csrc/cpu/random_sampler.cc:24-47; the CUDA
            reservoir's without-replacement guarantee is relaxed — tests, like
            the reference's, assert membership/caps, not exact multisets).

Weighted sampling follows the reference CPU weighted sampler's CDF + binary
search (csrc/cpu/weighted_sampler.cc:147-193) but over a precomputed per-row
cumulative-weight array so the per-draw work is a fixed 32-step bisection.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .unique import FILL


@functools.partial(jax.jit, static_argnames=('k',))
def uniform_sample(indptr, indices, seeds, seed_mask, k: int, key,
                   meta=None):
  """Sample up to ``k`` neighbors per seed.

  Args:
    indptr:  [N+1] CSR row pointer (int32/int64, device-resident).
    indices: [E] neighbor ids.
    seeds:   [B] seed ids (padded entries arbitrary where ``seed_mask`` False).
    seed_mask: [B] bool validity.
    k: fanout (static).
    key: jax PRNG key.
    meta: optional [N, 2] (start, degree) row table
      (``build_csr_meta``). Folds the two indptr ELEMENT gathers into
      one ROW gather — on TPU both cost ~one HBM transaction per seed,
      so this halves the row-pointer lookup time (the same trick block
      mode uses for its metadata).

  Returns:
    nbrs:  [B, K] neighbor ids, FILL where invalid.
    epos:  [B, K] position into the CSR ``indices`` array of each sampled
           edge (valid where mask; use to gather edge ids/weights).
    mask:  [B, K] bool validity.
  """
  b = seeds.shape[0]
  safe_seeds = jnp.where(seed_mask, seeds, 0)
  if meta is not None:
    row = meta[safe_seeds]
    start, deg = row[:, 0], row[:, 1]
  else:
    start = indptr[safe_seeds]
    deg = indptr[safe_seeds + 1] - start
  u = jax.random.uniform(key, (b, k))
  rand_off = jnp.floor(u * deg[:, None].astype(u.dtype)).astype(jnp.int32)
  rand_off = jnp.minimum(rand_off, jnp.maximum(deg[:, None] - 1, 0))
  seq_off = jnp.arange(k, dtype=jnp.int32)[None, :]
  offsets = jnp.where(deg[:, None] > k, rand_off, seq_off)
  mask = seed_mask[:, None] & (offsets < deg[:, None])
  epos = start[:, None] + offsets
  safe_epos = jnp.where(mask, epos, 0)
  nbrs = jnp.where(mask, indices[safe_epos], FILL)
  return nbrs, jnp.where(mask, epos, 0), mask


def build_row_cumsum(indptr, weights):
  """Host/device precompute for weighted sampling: per-edge cumulative weight
  restarting at each row (so ``cum[indptr[r]:indptr[r+1]]`` is the row CDF)."""
  cum = jnp.cumsum(weights)
  row_base = jnp.concatenate([jnp.zeros((1,), cum.dtype), cum])[indptr[:-1]]
  n = indptr.shape[0] - 1
  counts = indptr[1:] - indptr[:-1]
  base_per_edge = jnp.repeat(row_base, counts,
                             total_repeat_length=weights.shape[0])
  return cum - base_per_edge


@functools.partial(jax.jit, static_argnames=('k',))
def weighted_sample(indptr, indices, row_cumsum, seeds, seed_mask, k: int,
                    key):
  """Edge-weight-biased sampling with replacement via inverse-CDF bisection.

  ``row_cumsum`` comes from :func:`build_row_cumsum`. Same output contract as
  :func:`uniform_sample`. Rows with degree <= k keep all neighbors (parity
  with the uniform path and the reference's keep-all branch).
  """
  b = seeds.shape[0]
  safe_seeds = jnp.where(seed_mask, seeds, 0)
  start = indptr[safe_seeds]
  end = indptr[safe_seeds + 1]
  deg = end - start
  total = row_cumsum[jnp.maximum(end - 1, 0)]
  total = jnp.where(deg > 0, total, 1.0)
  u = jax.random.uniform(key, (b, k)) * total[:, None]

  # Vectorized bisection for the first edge position with cum >= u within
  # [start, end). 32 steps cover any degree < 2^32.
  lo = jnp.broadcast_to(start[:, None], (b, k))
  hi = jnp.broadcast_to(end[:, None], (b, k))

  def body(_, carry):
    lo, hi = carry
    mid = (lo + hi) // 2
    go_right = row_cumsum[jnp.clip(mid, 0, row_cumsum.shape[0] - 1)] < u
    lo = jnp.where(go_right, mid + 1, lo)
    hi = jnp.where(go_right, hi, mid)
    return lo, hi

  lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
  wpos = jnp.minimum(lo, jnp.maximum(end[:, None] - 1, 0))

  seq_off = jnp.arange(k, dtype=start.dtype)[None, :]
  epos = jnp.where(deg[:, None] > k, wpos, start[:, None] + seq_off)
  mask = seed_mask[:, None] & (
      jnp.where(deg[:, None] > k, 0, seq_off) < deg[:, None])
  safe_epos = jnp.where(mask, epos, 0)
  nbrs = jnp.where(mask, indices[safe_epos], FILL)
  return nbrs, jnp.where(mask, epos, 0), mask


def choose_padded_window(fanouts, candidates=(16, 64, 128)) -> int:
  """Pick the padded-adjacency window for a fanout list.

  The window must cover max(fanout) (smaller would systematically
  under-sample). Among sufficient widths the measured order on v5e is
  16 > 64 > 128 >> 32 (PERF.md: W=32 hits a reproducible XLA
  tiling/codegen cliff — 10.0 ms vs 4.97 at W=16 and 6.52 at W=64 — so
  it is deliberately absent from ``candidates``).
  """
  need = max(fanouts)
  for w in candidates:
    if w >= need:
      return w
  return _round_up_pow2(need)


def _round_up_pow2(n: int) -> int:
  w = 1
  while w < n:
    w *= 2
  return w


def padded_table_stats(indptr, window: int):
  """Degree-conditional neighbor-recall of a [N, window] padded table.

  Quantifies the padded mode's disclosed truncation: rows with
  deg > window expose only a random ``window``-subset per epoch.
  Returns:
    node_recall: mean over nodes of min(deg, W)/deg (deg > 0).
    edge_recall: sum(min(deg, W)) / sum(deg) — the probability that a
      uniformly chosen EDGE's slot survives truncation; hub-sensitive,
      so it is the number that matters on power-law graphs.
    frac_truncated_nodes / frac_truncated_edges: how much of the graph
      the trade touches.
    recall_by_degree: {decile upper bound -> mean node recall} over
      degree deciles (only nodes with deg > 0).
  """
  indptr = np.asarray(indptr)
  deg = np.diff(indptr).astype(np.int64)
  pos = deg[deg > 0]
  kept = np.minimum(pos, window)
  stats = {
      'window': int(window),
      'node_recall': float((kept / pos).mean()) if pos.size else 1.0,
      'edge_recall': float(kept.sum() / max(pos.sum(), 1)),
      'frac_truncated_nodes': float((pos > window).mean()) if pos.size
      else 0.0,
      'frac_truncated_edges': float(pos[pos > window].sum()
                                    / max(pos.sum(), 1)),
  }
  if pos.size:
    qs = np.quantile(pos, np.linspace(0.1, 1.0, 10))
    by_dec = {}
    lo = 0
    for q in qs:
      sel = (pos > lo) & (pos <= q)
      if sel.any():
        by_dec[int(q)] = float((kept[sel] / pos[sel]).mean())
      lo = q
    stats['recall_by_degree'] = by_dec
  return stats


def build_padded_adjacency(indptr, indices, window: int, seed: int = 0,
                           edge_pos: bool = False):
  """Host-side: dense [N, window] neighbor table with per-row shuffling.

  The TPU answer to CSR pointer-chasing: XLA's ELEMENT gather over a
  [25M] CSR indices array is DMA-latency-bound (~120M elem/s,
  device-trace evidence in PERF.md), while ROW gathers move ~5x more
  bytes/s. This table makes a sampling hop one row gather + cheap
  in-row VPU selection. Rows with deg > window keep a uniformly random
  ``window``-subset (the shuffle makes the truncation unbiased; rebuild
  with a new seed to refresh the subset across epochs).

  Returns (nbr_table [N, window] int32, FILL-padded; deg [N] int32 =
  min(true degree, window); epos_table [N, window] or None — CSR edge
  positions for with_edge/weighted lookups).
  """
  indptr = np.asarray(indptr)
  indices = np.asarray(indices)
  n = indptr.shape[0] - 1
  e = indices.shape[0]
  rng = np.random.default_rng(seed)
  rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
  order = np.lexsort((rng.random(e), rows))     # shuffle within each row
  # `order` keeps row blocks contiguous, so the within-row rank after the
  # shuffle is the same arithmetic as before it
  shuf_rows = rows[order]
  shuf_within = np.arange(e, dtype=np.int64) - np.repeat(
      indptr[:-1], np.diff(indptr))
  sel = shuf_within < window
  tab = np.full((n, window), FILL, np.int32)
  tab[shuf_rows[sel], shuf_within[sel]] = indices[order][sel]
  deg = np.minimum(np.diff(indptr), window).astype(np.int32)
  epos = None
  if edge_pos:
    epos = np.zeros((n, window), np.int32)
    epos[shuf_rows[sel], shuf_within[sel]] = order[sel]
  return tab, deg, epos


@functools.partial(jax.jit, static_argnames=('window', 'edge_pos'))
def build_padded_adjacency_device(indptr, indices, window: int, key,
                                  edge_pos: bool = False):
  """Device-side :func:`build_padded_adjacency`: the same per-row
  shuffle + truncate construction as ONE two-key sort over the edge
  list plus a fixed-shape scatter — no host work, no [N, W] upload.

  Why it exists: the per-epoch padded reseed (de-biasing the deg > W
  truncation) cost ~90 s/epoch of HOST numpy + transfer at products
  scale (round-4 matrix finding); on device the rebuild is a ~E-entry
  sort + scatter (~0.5 s at 61M edges). Returns the same
  (tab, deg, epos) contract; subsets are exact uniform
  without-replacement per row, drawn from ``key``.
  """
  e = indices.shape[0]
  n = indptr.shape[0] - 1
  rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32),
                    jnp.diff(indptr).astype(jnp.int32),
                    total_repeat_length=e)
  rand = jax.random.uniform(key, (e,))
  # two-key sort keeps row blocks contiguous and shuffles within rows;
  # payload = original edge position
  _, _, order = jax.lax.sort(
      (rows, rand, jnp.arange(e, dtype=jnp.int32)), num_keys=2)
  within = jnp.arange(e, dtype=jnp.int32) - jnp.repeat(
      indptr[:-1].astype(jnp.int32), jnp.diff(indptr).astype(jnp.int32),
      total_repeat_length=e)
  # positions beyond the window scatter out of bounds -> dropped
  tab = jnp.full((n, window), FILL, jnp.int32)
  tab = tab.at[rows, within].set(indices[order].astype(jnp.int32),
                                 mode='drop')
  deg = jnp.minimum(jnp.diff(indptr), window).astype(jnp.int32)
  epos = None
  if edge_pos:
    epos = jnp.zeros((n, window), jnp.int32).at[rows, within].set(
        order, mode='drop')
  return tab, deg, epos


@functools.partial(jax.jit, static_argnames=('k',))
def uniform_sample_padded(nbr_table, deg, seeds, seed_mask, k: int, key,
                          epos_table=None):
  """Uniform fanout sampling over a padded adjacency table
  (:func:`build_padded_adjacency`). Same output contract as
  :func:`uniform_sample`; ``epos`` is only meaningful when
  ``epos_table`` is given (else zeros)."""
  b = seeds.shape[0]
  safe = jnp.where(seed_mask, seeds, 0)
  rows = nbr_table[safe]                          # [B, W] row gather
  d = jnp.where(seed_mask, deg[safe], 0)
  u = jax.random.uniform(key, (b, k))
  rand_off = jnp.floor(u * d[:, None].astype(u.dtype)).astype(jnp.int32)
  rand_off = jnp.minimum(rand_off, jnp.maximum(d[:, None] - 1, 0))
  seq_off = jnp.arange(k, dtype=jnp.int32)[None, :]
  offsets = jnp.where(d[:, None] > k, rand_off, seq_off)
  mask = seed_mask[:, None] & (offsets < d[:, None])
  safe_off = jnp.where(mask, offsets, 0)
  # in-row selection via one-hot contraction, NOT take_along_axis: a
  # dynamic axis-1 gather lowers to the same latency-bound element
  # gather this op exists to avoid; the one-hot multiply-sum is pure
  # VPU work over the already-gathered [B, W] rows
  onehot = (safe_off[:, :, None] ==
            jnp.arange(rows.shape[1], dtype=jnp.int32)[None, None, :])
  picked = jnp.sum(rows[:, None, :] * onehot, axis=-1)
  nbrs = jnp.where(mask, picked, FILL)
  if epos_table is not None:
    ep = jnp.sum(epos_table[safe][:, None, :] * onehot, axis=-1)
    epos = jnp.where(mask, ep, 0)
  else:
    epos = jnp.zeros_like(nbrs)
  return nbrs, epos, mask


BLOCK = 16  # aligned CSR block width for block sampling


@functools.partial(jax.jit, static_argnames=('k',))
def uniform_sample_block(csr_meta, indices_blocks, num_edges: int, seeds,
                         seed_mask, k: int, key):
  """Block (cluster) fanout sampling over the raw CSR — row-gather speed
  without a prebuilt table.

  Element gathers over the CSR indices array are DMA-latency-bound on
  TPU, but 2-D ROW gathers run ~5x faster (PERF.md). This op reshapes
  the indices array into aligned [E/16, 16] blocks (``indices_blocks``,
  a free reshape of the padded array), draws ONE uniform position
  p = start + U[0, deg) per seed, gathers the single block containing p,
  and then draws the k samples uniformly from the block's elements that
  belong to the seed's segment. Marginals are EXACTLY uniform
  (P(block) * P(elem | block) = valid/deg * 1/valid = 1/deg); draws
  within one row of one hop are correlated through the shared block —
  cluster sampling, fresh per batch via the PRNG (unlike the padded
  table's fixed W-subset).

  ``csr_meta`` is the [N, 2] packed (row start, degree) table;
  ``indices_blocks`` is ``padded_indices.reshape(-1, 16)`` where the
  indices array is FILL-padded to a multiple of 16 (`num_edges` = true
  edge count). Same output contract as :func:`uniform_sample`.
  """
  assert k <= BLOCK, 'block sampling supports fanouts up to BLOCK=16'
  b = seeds.shape[0]
  nblocks = indices_blocks.shape[0]
  safe = jnp.where(seed_mask, seeds, 0)
  # (start, deg) packed per node: ONE 2-wide row gather instead of two
  # element gathers over indptr (element gathers are the latency-bound
  # op this mode exists to avoid)
  meta = csr_meta[safe]
  start = meta[:, 0]
  deg = jnp.where(seed_mask, meta[:, 1], 0)
  small = deg <= k                                 # keep-all branch
  ku, kk = jax.random.split(key)
  u = jax.random.uniform(ku, (b,))
  p = start + jnp.minimum((u * deg.astype(u.dtype)).astype(jnp.int32),
                          jnp.maximum(deg - 1, 0))
  # block anchor: the drawn position's block for sampled rows, the
  # segment's first block for keep-all rows (whose k slots may straddle
  # into the NEXT block — covered by a second row gather below)
  blk = jnp.clip(jnp.where(small, start // BLOCK, p // BLOCK), 0,
                 nblocks - 1)
  blk_base = blk * BLOCK
  rows = indices_blocks[blk]                       # [B, 16] row gather
  rows2 = indices_blocks[jnp.clip(blk + 1, 0, nblocks - 1)]
  lo = jnp.maximum(start, blk_base) - blk_base     # valid in-block range
  hi = jnp.minimum(start + deg, blk_base + BLOCK) - blk_base
  width = jnp.maximum(hi - lo, 0)
  u2 = jax.random.uniform(kk, (b, k))
  off_rand = lo[:, None] + jnp.minimum(
      (u2 * width[:, None].astype(u2.dtype)).astype(jnp.int32),
      jnp.maximum(width[:, None] - 1, 0))
  seq = jnp.arange(k, dtype=jnp.int32)[None, :]
  off = jnp.where(small[:, None],
                  (start - blk_base)[:, None] + seq, off_rand)
  mask = seed_mask[:, None] & jnp.where(
      small[:, None], seq < deg[:, None], width[:, None] > 0)
  # off in [0, 2*BLOCK): pick from the anchor block or its successor
  lanes = jnp.arange(BLOCK, dtype=jnp.int32)[None, None, :]
  pick_cur = jnp.sum(rows[:, None, :] * (off[:, :, None] == lanes),
                     axis=-1)
  pick_next = jnp.sum(
      rows2[:, None, :] * ((off[:, :, None] - BLOCK) == lanes), axis=-1)
  picked = jnp.where(off < BLOCK, pick_cur, pick_next)
  epos = jnp.where(mask, blk_base[:, None] + off, 0)
  epos = jnp.minimum(epos, num_edges - 1)
  nbrs = jnp.where(mask, picked, FILL)
  return nbrs, epos, mask


@functools.partial(jax.jit, static_argnames=('k',))
def uniform_sample_local(row_ids, indptr_loc, indices, seeds, seed_mask,
                         k: int, key):
  """Uniform fanout sampling over a *partition-local* CSR.

  The distributed graph stores only owned rows per shard: ``row_ids`` is the
  ascending (INT_MAX-padded) list of owned global ids and ``indptr_loc``
  their local CSR offsets. Row lookup is a binary search instead of direct
  indexing — the TPU replacement for the reference's partition-local Graph
  rows (csrc/cpu/graph.cc + dist_neighbor_sampler.py:624). Seeds not owned
  by this shard come back masked out.

  Same output contract as :func:`uniform_sample`.
  """
  b = seeds.shape[0]
  pos = jnp.searchsorted(row_ids, seeds)
  pos = jnp.clip(pos, 0, row_ids.shape[0] - 1)
  found = (row_ids[pos] == seeds) & seed_mask
  start = indptr_loc[pos]
  deg = jnp.where(found, indptr_loc[pos + 1] - start, 0)
  u = jax.random.uniform(key, (b, k))
  rand_off = jnp.floor(u * deg[:, None].astype(u.dtype)).astype(jnp.int32)
  rand_off = jnp.minimum(rand_off, jnp.maximum(deg[:, None] - 1, 0))
  seq_off = jnp.arange(k, dtype=jnp.int32)[None, :]
  offsets = jnp.where(deg[:, None] > k, rand_off, seq_off)
  mask = found[:, None] & (offsets < deg[:, None])
  epos = start[:, None] + offsets
  safe_epos = jnp.where(mask, epos, 0)
  nbrs = jnp.where(mask, indices[safe_epos], FILL)
  return nbrs, jnp.where(mask, epos, 0), mask


@functools.partial(jax.jit, static_argnames=('k',))
def weighted_sample_local(row_ids, indptr_loc, indices, row_cumsum, seeds,
                          seed_mask, k: int, key):
  """Edge-weight-biased fanout sampling over a *partition-local* CSR.

  Distributed counterpart of :func:`weighted_sample` (the reference's GPU
  path falls back to uniform for distributed weighted sampling,
  sampler/neighbor_sampler.py:86-91 — here the weighted path works in the
  sharded engine too). ``row_cumsum`` is the per-shard row-restarting
  cumulative weight array (:func:`build_row_cumsum` over the local CSR).
  Same output contract as :func:`uniform_sample_local`.
  """
  b = seeds.shape[0]
  pos = jnp.searchsorted(row_ids, seeds)
  pos = jnp.clip(pos, 0, row_ids.shape[0] - 1)
  found = (row_ids[pos] == seeds) & seed_mask
  start = indptr_loc[pos]
  end = indptr_loc[pos + 1]
  deg = jnp.where(found, end - start, 0)
  end = start + deg
  total = row_cumsum[jnp.maximum(end - 1, 0)]
  total = jnp.where(deg > 0, total, 1.0)
  u = jax.random.uniform(key, (b, k)) * total[:, None]

  lo = jnp.broadcast_to(start[:, None], (b, k))
  hi = jnp.broadcast_to(end[:, None], (b, k))

  def body(_, carry):
    lo, hi = carry
    mid = (lo + hi) // 2
    go_right = row_cumsum[jnp.clip(mid, 0, row_cumsum.shape[0] - 1)] < u
    lo = jnp.where(go_right, mid + 1, lo)
    hi = jnp.where(go_right, hi, mid)
    return lo, hi

  lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
  wpos = jnp.minimum(lo, jnp.maximum(end[:, None] - 1, 0))

  seq_off = jnp.arange(k, dtype=start.dtype)[None, :]
  epos = jnp.where(deg[:, None] > k, wpos, start[:, None] + seq_off)
  mask = found[:, None] & (
      jnp.where(deg[:, None] > k, 0, seq_off) < deg[:, None])
  safe_epos = jnp.where(mask, epos, 0)
  nbrs = jnp.where(mask, indices[safe_epos], FILL)
  return nbrs, jnp.where(mask, epos, 0), mask


def edge_in_csr(indptr, indices, rows, cols):
  """Vectorized membership test: is (rows[i], cols[i]) an edge?

  Replacement for the reference's per-trial device binary search
  (csrc/cuda/random_negative_sampler.cu EdgeInCSR). Requires ``indices``
  sorted within each row segment (see ops.negative.sort_csr_segments).
  """
  start = indptr[rows]
  end = indptr[rows + 1]
  lo, hi = start, end

  def body(_, carry):
    lo, hi = carry
    mid = (lo + hi) // 2
    v = indices[jnp.clip(mid, 0, indices.shape[0] - 1)]
    go_right = v < cols
    lo = jnp.where(go_right, mid + 1, lo)
    hi = jnp.where(go_right, hi, mid)
    return lo, hi

  lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
  pos = jnp.clip(lo, 0, indices.shape[0] - 1)
  return (lo < end) & (indices[pos] == cols)
