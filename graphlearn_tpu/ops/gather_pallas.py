"""Pallas TPU kernels: random row gather from an HBM-resident table.

TPU-native replacement for the reference's UnifiedTensor gather kernel
(/root/reference/graphlearn_torch/csrc/cuda/unified_tensor.cu:48-81, a
warp-per-row UVA gather). The feature lookup is the biggest per-batch byte
mover in GNN training (PERF.md: ~40x the sampler's budget), and XLA lowers
`jnp.take` over a large HBM table through generic dynamic-gather machinery.

Two generations live here:

v1 (``gather_rows_hbm``): one async row DMA per output row, many in
flight at once — grid step i owns output rows [i*G, (i+1)*G); the row
ids arrive via scalar prefetch (known before the body runs), the body
starts G concurrent HBM->VMEM row copies straight into the output block,
then waits. Measured on v5e-1: LOSES to XLA's take (1.41 vs 1.20 ms on
the 131k x [1M, 128] probe) — every row is its own DMA transaction, the
exact bound XLA's gather already sits at.

v2 (``gather_rows_hbm2``): multi-row DMA over contiguous id-RUNS. The
repo's design rule (ops/induce_merge.py, PERF.md): sorts beat random
access on TPU, so v2 sorts the ids on device (one key+payload lax.sort),
segments the sorted ids into maximal runs of STRICTLY CONSECUTIVE table
rows (split at ``run_span`` and at grid-block boundaries), and issues
ONE async copy per full run instead of per row — contiguous source AND
destination, so a sorted or locality-heavy id vector collapses from B
transactions to ~B/run_span. Slots not covered by a full-span run keep
the v1 single-row copy (random ids degrade to exactly v1 + the sort).
The unsort back to caller order is one more payload sort + a [B, F]
row permutation; callers whose ids are ALREADY sorted-unique (the
tiered-storage staging planner, searchsorted slab gathers) pass
``presorted=True`` and skip both. Autotune grid (block_rows, run_span)
probed by benchmarks/prof_gather2.py; routing stays evidence-gated
behind ``UnifiedTensor.use_pallas_v2`` exactly like v1's ``use_pallas``.

Falls back to `jnp.take` off-TPU (interpret mode exists but is orders of
magnitude slower; tests exercise the kernels via interpret=True on small
shapes). The fallback is bit-identical: same clamped-id contract.
"""
import functools
import time
import warnings

import jax
import jax.numpy as jnp


def _gather_kernel(ids_ref, table_ref, out_ref, sems):
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu
  i = pl.program_id(0)
  g = out_ref.shape[0]

  def dma(slot):
    rid = ids_ref[i * g + slot]
    return pltpu.make_async_copy(table_ref.at[rid], out_ref.at[slot],
                                 sems.at[slot])

  def issue(slot, _):
    dma(slot).start()
    return _

  jax.lax.fori_loop(0, g, issue, None, unroll=True)

  def drain(slot, _):
    dma(slot).wait()
    return _

  jax.lax.fori_loop(0, g, drain, None, unroll=True)


@functools.partial(jax.jit,
                   static_argnames=('block_rows', 'interpret', 'force'))
def gather_rows_hbm(table, ids, block_rows: int = 128,
                    interpret: bool = False, force: bool = False):
  """Gather ``table[ids]`` via per-row async DMAs.

  Args:
    table: [N, F] device array (HBM-resident; never copied wholesale).
    ids: [B] int32 row indices (clamped to [0, N)).
    block_rows: rows per grid step == concurrent DMAs in flight.
      Device-trace truth on v5e-1 (1M x 128 f32 table, 131k random ids):
      best config 1.41 ms/call at 128/256 vs XLA take's 1.20 ms — XLA's
      gather wins on this chip, so callers opt in explicitly
      (UnifiedTensor.use_pallas) — see benchmarks/prof_gather.py.
    interpret: run the Pallas interpreter (CPU tests).
    force: run the kernel even off-TPU (tests); default falls back to
      jnp.take when the backend isn't TPU.

  Returns [B, F] gathered rows.
  """
  if force and not interpret and table.shape[1] % 128 != 0:
    # Mosaic HBM row slices must be 128-lane aligned: a forced kernel on
    # a misaligned table would reach Mosaic and fail to LOWER, not fall
    # back — so ``force`` yields to the alignment guard (with a warning;
    # interpret mode has no lane constraint and keeps honoring force)
    warnings.warn(
        f'gather_rows_hbm(force=True): table width {table.shape[1]} is '
        'not 128-lane aligned — Mosaic cannot lower the row DMA; '
        'falling back to jnp.take', stacklevel=2)
    force = False
  if ids.shape[0] == 0 or (
      not (interpret or force) and (jax.default_backend() != 'tpu' or
                                    table.shape[1] % 128 != 0)):
    # Mosaic HBM row slices must be 128-lane aligned — misaligned tables
    # fall back to XLA's take (UnifiedTensor._pallas_ok routes accordingly)
    return jnp.take(table, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  b = ids.shape[0]
  g = min(block_rows, b)
  pad = (-b) % g
  ids = jnp.clip(ids, 0, table.shape[0] - 1).astype(jnp.int32)
  if pad:
    ids = jnp.concatenate([ids, jnp.zeros((pad,), jnp.int32)])
  grid = (b + pad) // g

  out = pl.pallas_call(
      _gather_kernel,
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=1,
          grid=(grid,),
          in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
          out_specs=pl.BlockSpec((g, table.shape[1]),
                                 lambda i, ids_ref: (i, 0)),
          scratch_shapes=[pltpu.SemaphoreType.DMA((g,))],
      ),
      out_shape=jax.ShapeDtypeStruct((b + pad, table.shape[1]),
                                     table.dtype),
      interpret=interpret,
  )(ids, table)
  return out[:b] if pad else out


# ------------------------------------------------------------------ v2

# plan encoding: bits 30-31 carry the per-slot DMA kind, low 30 bits the
# clamped table row. Tables beyond 2^30 rows must shard (same bound as
# the int32 CSR contract elsewhere in the stack). NOTE: kind 2 occupies
# the int32 SIGN bit, so decoding must mask after the shift —
# ``(plan >> 30) & 3`` — or an arithmetic right shift turns it into -2.
_KIND_SINGLE = 0   # one row DMA for this slot (v1 behaviour)
_KIND_RUN = 1      # this slot starts a full ``run_span``-row DMA
_KIND_COVERED = 2  # covered by a preceding run start: no DMA
_ROW_MASK = (1 << 30) - 1


def decode_gather_plan(plan):
  """(kind, row) arrays from a packed :func:`plan_gather_runs` plan —
  the sign-bit-safe decode every consumer should use."""
  return (plan >> 30) & 3, plan & _ROW_MASK


def plan_gather_runs(sid, n_rows: int, block_rows: int, run_span: int):
  """Per-slot DMA plan over a SORTED id vector (host-free, pure XLA).

  A slot either copies its own row (kind 0), starts one contiguous
  ``run_span``-row copy covering itself and the next ``run_span - 1``
  slots (kind 1 — only when those slots hold strictly consecutive ids,
  the run does not cross a grid-block boundary, and the span stays
  inside the table), or is covered by such a start (kind 2). Only
  FULL-length runs use the multi-row copy: a shorter run's copy would
  overwrite the slots of whatever run follows it (DMA sizes are static),
  so partial runs decompose into singles. Returns the packed int32 plan;
  decode with :func:`decode_gather_plan` (kind 2 rides the sign bit, so
  a bare ``plan >> 30`` mis-decodes it as -2).
  """
  b = sid.shape[0]
  j = jnp.arange(b, dtype=jnp.int32)
  prev = jnp.concatenate([jnp.full((1,), -2, jnp.int32), sid[:-1]])
  # maximal +1-step runs, broken at grid-block boundaries (a run must
  # stay inside the output block its DMA writes)
  start0 = (sid != prev + 1) | (j % block_rows == 0)
  origin = jax.lax.cummax(jnp.where(start0, j, -1))
  # split every run_span slots from the run origin: every resulting run
  # is <= run_span long, and a FULL run is exactly run_span
  is_start = start0 | ((j - origin) % run_span == 0)
  start_pos = jax.lax.cummax(jnp.where(is_start, j, -1))
  # run length = next start (strictly after me) - my start
  nxt = jnp.flip(jax.lax.cummin(jnp.flip(
      jnp.where(is_start, j, b).astype(jnp.int32))))
  nxt_after = jnp.concatenate([nxt[1:], jnp.full((1,), b, jnp.int32)])
  run_len = nxt_after - start_pos
  full = is_start & (run_len == run_span) & (sid + run_span <= n_rows)
  # propagate the start's ``full`` verdict across its run (packed cummax
  # rides the run rank in the high bits — ops/induce_merge.py's trick)
  grp = jnp.cumsum(is_start.astype(jnp.int32))
  fullv = jax.lax.cummax(
      (grp << 1) | (full & is_start).astype(jnp.int32)) & 1
  kind = jnp.where(fullv == 1,
                   jnp.where(is_start, _KIND_RUN, _KIND_COVERED),
                   _KIND_SINGLE).astype(jnp.int32)
  return sid | (kind << 30)


def _gather2_kernel_factory(span):
  def kernel(plan_ref, table_ref, out_ref, sems):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    i = pl.program_id(0)
    g = out_ref.shape[0]

    def dmas(slot):
      v = plan_ref[i * g + slot]
      rid = v & _ROW_MASK
      kind = (v >> 30) & 3   # mask: kind 2 rides the sign bit
      single = pltpu.make_async_copy(table_ref.at[rid], out_ref.at[slot],
                                     sems.at[slot])
      run = pltpu.make_async_copy(table_ref.at[pl.ds(rid, span)],
                                  out_ref.at[pl.ds(slot, span)],
                                  sems.at[slot])
      return kind, single, run

    def issue(slot, carry):
      kind, single, run = dmas(slot)

      @pl.when(kind == _KIND_SINGLE)
      def _():
        single.start()

      @pl.when(kind == _KIND_RUN)
      def _():
        run.start()
      return carry

    jax.lax.fori_loop(0, g, issue, None, unroll=True)

    def drain(slot, carry):
      kind, single, run = dmas(slot)

      @pl.when(kind == _KIND_SINGLE)
      def _():
        single.wait()

      @pl.when(kind == _KIND_RUN)
      def _():
        run.wait()
      return carry

    jax.lax.fori_loop(0, g, drain, None, unroll=True)
  return kernel


@functools.partial(jax.jit,
                   static_argnames=('block_rows', 'run_span', 'presorted',
                                    'interpret'))
def _gather_rows_hbm2_impl(table, ids, block_rows: int, run_span: int,
                           presorted: bool, interpret: bool):
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n, f = table.shape
  assert n <= _ROW_MASK, 'gather v2 plan packs rows into 30 bits'
  b = ids.shape[0]
  ids = jnp.clip(ids, 0, n - 1).astype(jnp.int32)
  if presorted:
    sid, inv = ids, None
  else:
    iota = jnp.arange(b, dtype=jnp.int32)
    sid, perm = jax.lax.sort((ids, iota), num_keys=1)
    _, inv = jax.lax.sort((perm, iota), num_keys=1)
  g = min(block_rows, b)
  span = min(run_span, g)
  pad = (-b) % g
  if pad:
    # pad slots hold row 0 as their own singles; sliced off below
    sid = jnp.concatenate([sid, jnp.zeros((pad,), jnp.int32)])
  plan = plan_gather_runs(sid, n, g, span)
  grid = (b + pad) // g

  out = pl.pallas_call(
      _gather2_kernel_factory(span),
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=1,
          grid=(grid,),
          in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
          out_specs=pl.BlockSpec((g, f), lambda i, plan_ref: (i, 0)),
          scratch_shapes=[pltpu.SemaphoreType.DMA((g,))],
      ),
      out_shape=jax.ShapeDtypeStruct((b + pad, f), table.dtype),
      interpret=interpret,
  )(plan, table)
  out = out[:b] if pad else out
  return out if presorted else jnp.take(out, inv, axis=0)


def gather_rows_hbm2(table, ids, block_rows: int = 256, run_span: int = 8,
                     presorted: bool = False, interpret: bool = False,
                     force: bool = False):
  """Gather ``table[ids]`` via run-segmented multi-row async DMAs (v2).

  Sorts the ids on device (skipped with ``presorted=True`` — the caller
  asserts ids are ascending; duplicates are fine, they break runs), then
  copies each full ``run_span``-long stretch of consecutive rows with
  ONE DMA and everything else row-by-row. Bit-identical to
  ``jnp.take(table, clip(ids), axis=0)`` on every path, including the
  off-TPU / misaligned-width fallback.

  Args:
    table: [N, F] device array (HBM-resident; F must be 128-lane aligned
      for the kernel path — misaligned widths fall back like v1).
    ids: [B] int32 row indices (clamped to [0, N)).
    block_rows: output rows per grid step (autotune axis 1).
    run_span: rows per multi-row DMA (autotune axis 2; 1 degenerates to
      the v1 per-row kernel plus the sort).
    presorted: ids are already ascending — skips the sort AND the unsort
      row permutation (the tiered staging planner's slab gathers and
      any searchsorted-driven caller qualify).
    interpret: run the Pallas interpreter (CPU tests).
    force: run the kernel even off-TPU; still falls back (with a
      warning) on misaligned widths, like v1.

  Returns [B, F] gathered rows.
  """
  from .. import metrics
  if force and not interpret and table.shape[1] % 128 != 0:
    warnings.warn(
        f'gather_rows_hbm2(force=True): table width {table.shape[1]} is '
        'not 128-lane aligned — Mosaic cannot lower the run DMA; '
        'falling back to jnp.take', stacklevel=2)
    force = False
  if ids.shape[0] == 0 or (
      not (interpret or force) and (jax.default_backend() != 'tpu' or
                                    table.shape[1] % 128 != 0)):
    metrics.inc('ops.gather_fallbacks')
    return jnp.take(table, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)
  metrics.inc('ops.gather_runs')
  from ..utils.trace import record_dispatch
  t0 = time.perf_counter()
  record_dispatch('gather2')
  out = _gather_rows_hbm2_impl(table, ids, block_rows, run_span,
                               presorted, interpret)
  # dispatch clock, NOT device time (PERF.md 'wall clocks LIE'): useful
  # as a liveness/regression signal, never as a throughput claim
  metrics.observe('ops.gather_ms', (time.perf_counter() - t0) * 1e3)
  return out
