"""Pallas TPU kernel: random row gather from an HBM-resident table.

TPU-native replacement for the reference's UnifiedTensor gather kernel
(/root/reference/graphlearn_torch/csrc/cuda/unified_tensor.cu:48-81, a
warp-per-row UVA gather). The feature lookup is the biggest per-batch byte
mover in GNN training (PERF.md: ~40x the sampler's budget), and XLA lowers
`jnp.take` over a large HBM table through generic dynamic-gather machinery.
This kernel instead keeps the table in HBM untouched and issues one async
row DMA per output row, many in flight at once:

  grid step i owns output rows [i*G, (i+1)*G); the row ids arrive via
  scalar prefetch (known before the body runs), the body starts G
  concurrent HBM->VMEM row copies straight into the output block, then
  waits. Pallas' pipeline machinery double-buffers the output blocks, so
  step i+1's DMAs issue while step i's block flushes.

Falls back to `jnp.take` off-TPU (interpret mode exists but is orders of
magnitude slower; tests exercise the kernel via interpret=True on small
shapes).
"""
import functools

import jax
import jax.numpy as jnp


def _gather_kernel(ids_ref, table_ref, out_ref, sems):
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu
  i = pl.program_id(0)
  g = out_ref.shape[0]

  def dma(slot):
    rid = ids_ref[i * g + slot]
    return pltpu.make_async_copy(table_ref.at[rid], out_ref.at[slot],
                                 sems.at[slot])

  def issue(slot, _):
    dma(slot).start()
    return _

  jax.lax.fori_loop(0, g, issue, None, unroll=True)

  def drain(slot, _):
    dma(slot).wait()
    return _

  jax.lax.fori_loop(0, g, drain, None, unroll=True)


@functools.partial(jax.jit,
                   static_argnames=('block_rows', 'interpret', 'force'))
def gather_rows_hbm(table, ids, block_rows: int = 128,
                    interpret: bool = False, force: bool = False):
  """Gather ``table[ids]`` via per-row async DMAs.

  Args:
    table: [N, F] device array (HBM-resident; never copied wholesale).
    ids: [B] int32 row indices (clamped to [0, N)).
    block_rows: rows per grid step == concurrent DMAs in flight.
      Device-trace truth on v5e-1 (1M x 128 f32 table, 131k random ids):
      best config 1.41 ms/call at 128/256 vs XLA take's 1.20 ms — XLA's
      gather wins on this chip, so callers opt in explicitly
      (UnifiedTensor.use_pallas) — see benchmarks/prof_gather.py.
    interpret: run the Pallas interpreter (CPU tests).
    force: run the kernel even off-TPU (tests); default falls back to
      jnp.take when the backend isn't TPU.

  Returns [B, F] gathered rows.
  """
  if ids.shape[0] == 0 or (
      not (interpret or force) and (jax.default_backend() != 'tpu' or
                                    table.shape[1] % 128 != 0)):
    # Mosaic HBM row slices must be 128-lane aligned — misaligned tables
    # fall back to XLA's take (UnifiedTensor._pallas_ok routes accordingly)
    return jnp.take(table, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  b = ids.shape[0]
  g = min(block_rows, b)
  pad = (-b) % g
  ids = jnp.clip(ids, 0, table.shape[0] - 1).astype(jnp.int32)
  if pad:
    ids = jnp.concatenate([ids, jnp.zeros((pad,), jnp.int32)])
  grid = (b + pad) // g

  out = pl.pallas_call(
      _gather_kernel,
      grid_spec=pltpu.PrefetchScalarGridSpec(
          num_scalar_prefetch=1,
          grid=(grid,),
          in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
          out_specs=pl.BlockSpec((g, table.shape[1]),
                                 lambda i, ids_ref: (i, 0)),
          scratch_shapes=[pltpu.SemaphoreType.DMA((g,))],
      ),
      out_shape=jax.ShapeDtypeStruct((b + pad, table.shape[1]),
                                     table.dtype),
      interpret=interpret,
  )(ids, table)
  return out[:b] if pad else out
