"""Fixed-capacity routing primitives for cross-shard exchange.

The reference routes data-dependent id sets between workers over RPC
(/root/reference/graphlearn_torch/python/distributed/dist_neighbor_sampler.py:585-648).
On TPU the exchange is a fixed-shape `all_to_all` over the mesh: each shard
packs its outgoing ids into a dense [num_parts, capacity] bucket buffer
(FILL-padded), the collective transposes shard<->bucket, and responses are
un-permuted with the remembered (dest, slot) coordinates.

Overflow contract (SURVEY §7 "per-partition capacity padding + overflow
handling"; reference splits exactly and never drops,
dist_neighbor_sampler.py:585-648): a bucket only overflows when more than
``capacity`` elements target one destination, so callers that size
``capacity`` to the frontier width — as every engine in
distributed/dist_neighbor_sampler.py does — are loss-free BY CONSTRUCTION
even under pathologically skewed partition books (every id on one
partition). :func:`route_slots` also returns the overflow count so callers
that trade capacity for all_to_all volume can detect (and assert on) any
drop instead of losing samples silently.
"""
import functools
import math

import jax
import jax.numpy as jnp

from .unique import FILL


def round8(n: int) -> int:
  """Round up to the lane-friendly multiple of 8 (min 8)."""
  return max(8, ((n + 7) // 8) * 8)


def exchange_capacity(request_width: int, nparts: int,
                      bucket_frac, hit_rate: float = 0.0) -> int:
  """Resolved per-destination bucket capacity for one fixed-shape
  exchange: ``round8(bucket_frac * expected_load / nparts)`` clamped to
  the loss-free full width, where the expected per-exchange load is
  ``request_width`` discounted by ``hit_rate`` (the feature store's
  cache-hit floor; the sampler's frontier exchange uses 0). ONE home
  for the capacity policy — the sampler's `_exchange_hop` and the
  feature store's `miss_capacity` both resolve through here, and the
  dryrun reports per-hop all_to_all bytes from it."""
  if bucket_frac is None or nparts <= 1:
    return request_width
  load = request_width
  if hit_rate > 0:
    load = max(0, math.ceil(request_width * (1.0 - float(hit_rate))))
  return min(request_width,
             round8(int(bucket_frac * load / nparts)))


@functools.partial(jax.jit, static_argnames=('capacity', 'with_overflow'))
def route_slots(dest, mask, capacity: int, with_overflow: bool = False):
  """Assign each element a slot within its destination bucket.

  Args:
    dest: [B] destination partition per element.
    mask: [B] validity.
    capacity: bucket capacity (static). ``capacity >= B`` can never
      overflow (see module docstring).
    with_overflow: also return the number of valid elements that did NOT
      get a slot (overflow beyond ``capacity`` in their bucket).

  Returns (slot [B], ok [B]) — ``ok`` = valid and not overflowed — plus
  ``num_overflow`` (scalar int32) when ``with_overflow``.
  """
  b = dest.shape[0]
  big = jnp.int32(2 ** 30)
  key = jnp.where(mask, dest.astype(jnp.int32), big)
  order = jnp.argsort(key, stable=True)
  sorted_key = key[order]
  idx = jnp.arange(b, dtype=jnp.int32)
  is_first = jnp.concatenate(
      [jnp.ones((1,), bool), sorted_key[1:] != sorted_key[:-1]])
  group_start = jax.lax.cummax(jnp.where(is_first, idx, 0))
  rank_sorted = idx - group_start
  slot = jnp.zeros((b,), jnp.int32).at[order].set(rank_sorted)
  ok = mask & (slot < capacity)
  if with_overflow:
    return slot, ok, jnp.sum(mask & ~ok).astype(jnp.int32)
  return slot, ok


def scatter_to_buckets(vals, dest, slot, ok, num_parts: int, capacity: int,
                       fill=FILL):
  """Pack [B] (or [B, ...]) values into [num_parts, capacity, ...]."""
  shape = (num_parts, capacity) + vals.shape[1:]
  out = jnp.full(shape, fill, dtype=vals.dtype)
  d = jnp.where(ok, dest, num_parts)
  return out.at[d, slot].set(vals, mode='drop')


def gather_from_buckets(recv, dest, slot, ok, fill=FILL):
  """Inverse of scatter: pull each element's response from
  recv[dest, slot]."""
  safe_d = jnp.where(ok, dest, 0)
  safe_s = jnp.where(ok, slot, 0)
  out = recv[safe_d, safe_s]
  if out.ndim == 1:
    return jnp.where(ok, out, fill)
  return jnp.where(ok.reshape((-1,) + (1,) * (out.ndim - 1)), out, fill)
