"""Client-side receiving channel pulling batches from sampling servers.

TPU-native port of
/root/reference/graphlearn_torch/python/channel/remote_channel.py: keeps
`prefetch_size` outstanding fetch requests per server, buffers responses in
a local queue, and tracks the per-server end-of-epoch protocol
(message None + end flag, remote_channel.py:58-131).
"""
import queue
import threading
from typing import List

from .base import ChannelBase, QueueTimeoutError, SampleMessage


class RemoteReceivingChannel(ChannelBase):
  """Reference: remote_channel.py:24-131."""

  def __init__(self, server_ranks: List[int], producer_ids: List[int],
               prefetch_size: int = 4, request_fn=None):
    """`request_fn(server_rank, producer_id)` -> (msg|None, end_flag);
    defaults to dist_client.request_server(fetch_one_sampled_message)."""
    self.server_ranks = list(server_ranks)
    self.producer_ids = list(producer_ids)
    self.prefetch_size = prefetch_size
    if request_fn is None:
      from ..distributed import dist_client

      def request_fn(rank, pid):
        return dist_client.request_server(
            rank, 'fetch_one_sampled_message', pid)
    self._request_fn = request_fn
    self._queue: queue.Queue = queue.Queue()
    self._threads: List[threading.Thread] = []
    self._stopped = threading.Event()
    self._pending_end = 0
    self._lock = threading.Lock()
    self._started = False

  def _puller(self, rank: int, pid: int):
    """One puller thread per (server, prefetch slot)."""
    while not self._stopped.is_set():
      try:
        msg, end = self._request_fn(rank, pid)
      except Exception as e:  # noqa: BLE001 - surfaced to the consumer
        self._queue.put(('error', repr(e)))
        return
      if msg is not None:
        self._queue.put(('msg', msg))
      if end:
        self._queue.put(('end', rank))
        return

  def start(self):
    """Begin one epoch of pulling (idempotent per epoch)."""
    self._stopped.clear()
    with self._lock:
      self._pending_end = 0
      self._threads = []
      for rank, pid in zip(self.server_ranks, self.producer_ids):
        self._pending_end += 1
        for _ in range(self.prefetch_size):
          t = threading.Thread(target=self._puller, args=(rank, pid),
                               daemon=True)
          self._threads.append(t)
      # only one end-marker per server must count: track per server below
      self._ends_seen = set()
      for t in self._threads:
        t.start()
    self._started = True

  def recv(self, timeout_ms: int = -1) -> SampleMessage:
    if not self._started:
      self.start()
    timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
    while True:
      try:
        kind, payload = self._queue.get(timeout=timeout)
      except queue.Empty as e:
        raise QueueTimeoutError('remote channel recv timeout') from e
      if kind == 'msg':
        return payload
      if kind == 'error':
        raise RuntimeError(f'remote fetch failed: {payload}')
      # end marker for one server
      with self._lock:
        self._ends_seen.add(payload)
        if len(self._ends_seen) >= len(set(self.server_ranks)):
          self._started = False
          raise StopIteration('epoch complete')

  def empty(self) -> bool:
    return self._queue.empty()

  def stop(self):
    self._stopped.set()
