"""Client-side receiving channel pulling batches from sampling servers.

TPU-native port of
/root/reference/graphlearn_torch/python/channel/remote_channel.py: keeps
`prefetch_size` outstanding fetch requests per server, buffers responses in
a local queue, and tracks the per-server end-of-epoch protocol
(message None + end flag, remote_channel.py:58-131).
"""
import queue
import threading
from typing import List

from .base import ChannelBase, QueueTimeoutError, SampleMessage


class RemoteReceivingChannel(ChannelBase):
  """Reference: remote_channel.py:24-131."""

  def __init__(self, server_ranks: List[int], producer_ids: List[int],
               prefetch_size: int = 4, request_fn=None):
    """`request_fn(server_rank, producer_id)` -> (msg|None, end_flag);
    defaults to dist_client.request_server(fetch_one_sampled_message)."""
    self.server_ranks = list(server_ranks)
    self.producer_ids = list(producer_ids)
    self.prefetch_size = prefetch_size
    if request_fn is None:
      from ..distributed import dist_client

      def request_fn(rank, pid):
        return dist_client.request_server(
            rank, 'fetch_one_sampled_message', pid)
    self._request_fn = request_fn
    self._queue: queue.Queue = queue.Queue()
    self._threads: List[threading.Thread] = []
    self._stopped = threading.Event()
    self._lock = threading.Lock()
    self._started = False

  def _puller(self, rank: int, pid: int, q: queue.Queue, active: dict,
              stopped: threading.Event):
    """One puller thread per (producer, prefetch slot).

    End-of-epoch ordering: with prefetch_size > 1 several pullers fetch the
    same producer concurrently, so the thread that receives the (None, end)
    response may finish while a sibling still has an earlier message in
    flight. The producer's 'end' marker is therefore only enqueued by the
    LAST puller of that producer to exit — every sibling has enqueued its
    final message before then, so no batch can be dropped behind the
    marker.

    ``q``/``active``/``stopped`` are THIS epoch's objects, passed in rather
    than read from self: a puller that outlives its epoch (consumer
    abandoned it mid-stream, then start() began a new one) keeps writing to
    its own epoch's dead queue and can never poison a later epoch's state.
    """
    try:
      while not stopped.is_set():
        try:
          msg, end = self._request_fn(rank, pid)
        except Exception as e:  # noqa: BLE001 - surfaced to the consumer
          q.put(('error', repr(e)))
          return
        if msg is not None:
          q.put(('msg', msg))
        if end:
          return
    finally:
      with self._lock:
        active[(rank, pid)] -= 1
        last = active[(rank, pid)] == 0
      if last:
        q.put(('end', (rank, pid)))

  def start(self):
    """Begin one epoch of pulling.

    Any previous epoch's pullers are stopped AND joined first: a stale
    puller that survived into the new epoch would consume new-epoch
    messages into its retired queue (the server counts them toward
    expected, so the new epoch would silently come up short). Callers
    restarting server producers must do so AFTER the old pullers are dead
    — see RemoteDistNeighborLoader.__iter__ ordering.
    """
    self.stop(join=True)
    self._stopped = threading.Event()
    self._queue = queue.Queue()
    with self._lock:
      self._threads = []
      active = {}
      for rank, pid in zip(self.server_ranks, self.producer_ids):
        active[(rank, pid)] = self.prefetch_size
        for _ in range(self.prefetch_size):
          t = threading.Thread(
              target=self._puller,
              args=(rank, pid, self._queue, active, self._stopped),
              daemon=True)
          self._threads.append(t)
      # one end-marker per (server, producer) pair ends the epoch
      self._ends_seen = set()
      for t in self._threads:
        t.start()
    self._started = True

  def recv(self, timeout_ms: int = -1) -> SampleMessage:
    if not self._started:
      self.start()
    timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
    while True:
      try:
        kind, payload = self._queue.get(timeout=timeout)
      except queue.Empty as e:
        raise QueueTimeoutError('remote channel recv timeout') from e
      if kind == 'msg':
        return payload
      if kind == 'error':
        raise RuntimeError(f'remote fetch failed: {payload}')
      # end marker for one (server, producer) pair
      with self._lock:
        self._ends_seen.add(payload)
        n_pairs = len(set(zip(self.server_ranks, self.producer_ids)))
        if len(self._ends_seen) >= n_pairs:
          self._started = False
          raise StopIteration('epoch complete')

  def empty(self) -> bool:
    return self._queue.empty()

  def stop(self, join: bool = False, timeout: float = 30.0):
    """Signal pullers to wind down; with ``join`` wait for them to exit
    (each finishes at most one in-flight request)."""
    self._stopped.set()
    if join:
      for t in self._threads:
        t.join(timeout=timeout)
      self._threads = []
    self._started = False
