"""Client-side receiving channel pulling batches from sampling servers.

TPU-native port of
/root/reference/graphlearn_torch/python/channel/remote_channel.py: keeps
`prefetch_size` outstanding fetch requests per server, buffers responses in
a local queue, and tracks the per-server end-of-epoch protocol
(message None + end flag, remote_channel.py:58-131).

Resilience extensions (distributed/resilience.py is the companion):

* every message carries provenance — ``recv_with_meta`` returns
  ``(rank, producer_id, msg)`` so the loader can ack which server
  delivered which seeds;
* a fetch failure marks the (server, producer) pair FAILED: one
  :class:`PeerDeadError` surfaces through ``recv`` (sibling pullers of
  the pair exit quietly) and the pair stops counting toward epoch
  completion, leaving the caller free to fail over;
* ``add_producer`` attaches a replacement producer mid-epoch (failover
  target) and ``abandon`` drops a pair so a hung-then-recovered server
  cannot leak late duplicates into the stream.

Fetches are NOT blindly retried here: ``fetch_one_sampled_message``
dequeues server-side, so a re-sent fetch after a lost response would
lose a batch silently. Lost-in-flight batches are instead recovered by
the loader's seed-level failover (unacked seeds are re-requested).
"""
import queue
import threading
from typing import List, Tuple

from .base import ChannelBase, QueueTimeoutError, SampleMessage


class PeerDeadError(RuntimeError):
  """A (server, producer) pair failed mid-epoch; carries provenance."""

  def __init__(self, rank: int, producer_id: int, cause: str):
    super().__init__(f'fetch from server rank {rank} '
                     f'(producer {producer_id}) failed: {cause}')
    self.rank = rank
    self.producer_id = producer_id
    self.cause = cause


class RemoteReceivingChannel(ChannelBase):
  """Reference: remote_channel.py:24-131."""

  def __init__(self, server_ranks: List[int], producer_ids: List[int],
               prefetch_size: int = 4, request_fn=None):
    """`request_fn(server_rank, producer_id)` -> (msg|None, end_flag);
    defaults to dist_client.request_server(fetch_one_sampled_message)
    with a bounded per-request timeout (the server's fetch poll returns
    within ~its timeout_ms, so a fetch blocked for longer means a hung
    peer, not a slow epoch)."""
    self.server_ranks = list(server_ranks)
    self.producer_ids = list(producer_ids)
    self.prefetch_size = prefetch_size
    if request_fn is None:
      from ..distributed import dist_client

      def request_fn(rank, pid):
        return dist_client.request_server(
            rank, 'fetch_one_sampled_message', pid, timeout=30.0)
    self._request_fn = request_fn
    self._queue: queue.Queue = queue.Queue()
    self._threads: List[threading.Thread] = []
    self._stopped = threading.Event()
    self._lock = threading.Lock()
    self._started = False
    self._pairs = set()        # pairs participating in THIS epoch
    self._ends_seen = set()
    self._failed = set()       # pairs that died or were abandoned
    self._received = 0

  def _puller(self, rank: int, pid: int, q: queue.Queue, active: dict,
              stopped: threading.Event, failed: set):
    """One puller thread per (producer, prefetch slot).

    End-of-epoch ordering: with prefetch_size > 1 several pullers fetch the
    same producer concurrently, so the thread that receives the (None, end)
    response may finish while a sibling still has an earlier message in
    flight. The producer's 'end' marker is therefore only enqueued by the
    LAST puller of that producer to exit — every sibling has enqueued its
    final message before then, so no batch can be dropped behind the
    marker.

    Failure: the FIRST puller whose fetch raises marks the pair failed
    and enqueues one 'dead' marker; siblings (whose own fetches will
    fail, or who see the failed flag) exit without enqueuing anything
    more for the pair. A pair in ``failed`` (also set by abandon())
    never enqueues another message — a hung server that recovers after
    failover cannot leak duplicate batches into the epoch.

    ``q``/``active``/``stopped``/``failed`` are THIS epoch's objects,
    passed in rather than read from self: a puller that outlives its
    epoch (consumer abandoned it mid-stream, then start() began a new
    one) keeps writing to its own epoch's dead queue and can never
    poison a later epoch's state.
    """
    from ..utils.faults import fault_point
    try:
      while not stopped.is_set():
        with self._lock:
          if (rank, pid) in failed:
            return
        try:
          fault_point('channel.remote.fetch')
          msg, end = self._request_fn(rank, pid)
        except Exception as e:  # noqa: BLE001 - surfaced to the consumer
          # failed.add and the 'dead' enqueue must be atomic: the
          # consumer's completion check reads (failed, queue-empty)
          # under this lock, and a gap between the two would let it
          # declare the epoch complete without ever surfacing the
          # PeerDeadError that triggers failover
          with self._lock:
            if (rank, pid) not in failed:
              failed.add((rank, pid))
              q.put(('dead', (rank, pid, repr(e))))
          return
        # the failed-check and the enqueue must be atomic: abandon()
        # takes the same lock before the caller drains the queue, so a
        # message is either visible to that drain or discarded — never
        # enqueued after the drain computed its unacked set (which
        # would deliver the batch twice once failover replays it)
        with self._lock:
          if (rank, pid) in failed:
            return   # late response after abandon/failover: discard
          if msg is not None:
            q.put(('msg', (rank, pid, msg)))
        if end:
          return
    finally:
      with self._lock:
        active[(rank, pid)] -= 1
        last = active[(rank, pid)] == 0
      if last:
        q.put(('end', (rank, pid)))

  def start(self):
    """Begin one epoch of pulling.

    Any previous epoch's pullers are stopped AND joined first: a stale
    puller that survived into the new epoch would consume new-epoch
    messages into its retired queue (the server counts them toward
    expected, so the new epoch would silently come up short). Callers
    restarting server producers must do so AFTER the old pullers are dead
    — see RemoteDistNeighborLoader.__iter__ ordering.
    """
    self.start_pairs(list(zip(self.server_ranks, self.producer_ids)))

  def start_pairs(self, pairs: List[Tuple[int, int]]):
    """start() restricted to a subset of the configured (rank, producer)
    pairs — loaders exclude ranks already known dead."""
    self.stop(join=True)
    self._stopped = threading.Event()
    self._queue = queue.Queue()
    self._received = 0
    with self._lock:
      self._threads = []
      self._active = {}
      self._pairs = set(pairs)
      self._failed = set()
      # one end-marker per (server, producer) pair ends the epoch
      self._ends_seen = set()
    for rank, pid in pairs:
      self._spawn_pullers(rank, pid)
    self._started = True

  def _spawn_pullers(self, rank: int, pid: int):
    threads = []
    with self._lock:
      self._active[(rank, pid)] = self.prefetch_size
      for _ in range(self.prefetch_size):
        t = threading.Thread(
            target=self._puller,
            args=(rank, pid, self._queue, self._active, self._stopped,
                  self._failed),
            daemon=True)
        self._threads.append(t)
        threads.append(t)
    for t in threads:
      t.start()

  def add_producer(self, rank: int, pid: int):
    """Attach a replacement producer mid-epoch (failover target): it
    joins this epoch's completion accounting and gets its own pullers.
    The caller must have started the producer's epoch server-side
    first."""
    with self._lock:
      if (rank, pid) in self._pairs:
        return
      self._pairs.add((rank, pid))
    self._spawn_pullers(rank, pid)

  def abandon(self, rank: int, pid: int):
    """Stop pulling from a pair and drop any of its late responses.
    Its pullers exit at the next loop; an in-flight fetch result is
    discarded. The pair stops counting toward epoch completion."""
    with self._lock:
      self._failed.add((rank, pid))

  def recv(self, timeout_ms: int = -1) -> SampleMessage:
    return self.recv_with_meta(timeout_ms)[2]

  def recv_with_meta(self, timeout_ms: int = -1
                     ) -> Tuple[int, int, SampleMessage]:
    """Next message as ``(server_rank, producer_id, msg)``.

    Raises :class:`PeerDeadError` ONCE per failed pair (the caller
    decides whether to fail over via ``add_producer`` or give up),
    :class:`QueueTimeoutError` on an empty window, and StopIteration
    when every live pair has delivered its end marker.
    """
    if not self._started:
      self.start()
    timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
    while True:
      # completion check up front: every pair accounted for (ended or
      # failed-and-handled) and nothing buffered -> epoch complete
      with self._lock:
        done = self._started and \
            self._ends_seen | self._failed >= self._pairs and \
            self._queue.empty()
      if done:
        self._started = False
        raise StopIteration('epoch complete')
      try:
        kind, payload = self._queue.get(timeout=timeout)
      except queue.Empty as e:
        with self._lock:
          n_live = len(self._pairs - self._failed)
          n_done = len(self._ends_seen)
          got = self._received
        raise QueueTimeoutError(
            f'remote channel recv timed out after {timeout_ms}ms '
            f'(servers={sorted(set(self.server_ranks))}, live_pairs='
            f'{n_live}, ended={n_done}, received_so_far={got}) — no '
            'sampling server delivered a batch in the window; check '
            'server liveness') from e
      if kind == 'msg':
        rank, pid, msg = payload
        self._received += 1
        return rank, pid, msg
      if kind == 'dead':
        rank, pid, cause = payload
        raise PeerDeadError(rank, pid, cause)
      if kind == 'end':
        with self._lock:
          self._ends_seen.add(payload)
          if self._ends_seen | self._failed >= self._pairs:
            self._started = False
            raise StopIteration('epoch complete')

  def drain_now(self):
    """Yield every already-buffered (rank, pid, msg) without blocking;
    'end' markers are accounted, 'dead' markers are left queued for the
    next recv. Failover uses this to ack in-flight batches from a dying
    server BEFORE computing its unacked seed set."""
    out = []
    requeue = []
    while True:
      try:
        kind, payload = self._queue.get_nowait()
      except queue.Empty:
        break
      if kind == 'msg':
        self._received += 1
        out.append(payload)
      elif kind == 'end':
        with self._lock:
          self._ends_seen.add(payload)
      else:
        requeue.append((kind, payload))
    for item in requeue:
      self._queue.put(item)
    return out

  def empty(self) -> bool:
    return self._queue.empty()

  def stop(self, join: bool = False, timeout: float = 30.0):
    """Signal pullers to wind down; with ``join`` wait for them to exit
    (each finishes at most one in-flight request)."""
    self._stopped.set()
    if join:
      for t in self._threads:
        t.join(timeout=timeout)
      self._threads = []
    self._started = False
