"""Channel interface + SampleMessage serialization.

TPU-native port of /root/reference/graphlearn_torch/python/channel/base.py
plus the TensorMapSerializer
(/root/reference/graphlearn_torch/include/tensor_map.h: layout
|tensor_num| key | dtype | shape | data |). A SampleMessage is a flat
Dict[str, np.ndarray]; serialization packs it into one contiguous buffer
for the shm ring. Deserialization views arrays over the received buffer
(one copy out of shm — the TPU H2D transfer happens later via
jax.device_put, replacing the reference's pinned-ring CUDA H2D).
"""
import struct
from typing import Dict

import numpy as np

# A flat dict of host arrays, with '#' control keys (reference
# dist_neighbor_sampler.py '#IS_HETERO'/'#META.*' convention).
SampleMessage = Dict[str, np.ndarray]

_MAGIC = 0x474C5431  # 'GLT1'


def serialize_message(msg: SampleMessage) -> bytes:
  """Pack to: magic u32, count u32, then per tensor:
  key_len u16 | key | dtype_len u8 | dtype | ndim u8 | dims i64* | nbytes
  u64 | raw data (8-aligned)."""
  parts = [struct.pack('<II', _MAGIC, len(msg))]
  offset = 8
  for key, arr in msg.items():
    arr = np.ascontiguousarray(arr)
    kb = key.encode()
    db = arr.dtype.str.encode()
    hdr = struct.pack('<H', len(kb)) + kb + struct.pack('<B', len(db)) + db
    hdr += struct.pack('<B', arr.ndim)
    hdr += struct.pack(f'<{arr.ndim}q', *arr.shape) if arr.ndim else b''
    hdr += struct.pack('<Q', arr.nbytes)
    pad = (-(offset + len(hdr))) % 8  # align the data region
    parts.append(hdr + b'\x00' * pad)
    offset += len(hdr) + pad
    parts.append(arr.tobytes())
    offset += arr.nbytes
  return b''.join(parts)


def deserialize_message(buf) -> SampleMessage:
  """Inverse of :func:`serialize_message`; arrays are views over ``buf``
  where alignment allows (reference TensorMapSerializer::Load views over
  shm, tensor_map.cc:143)."""
  mv = memoryview(buf)
  magic, count = struct.unpack_from('<II', mv, 0)
  assert magic == _MAGIC, 'corrupt sample message'
  off = 8
  out: SampleMessage = {}
  for _ in range(count):
    (klen,) = struct.unpack_from('<H', mv, off)
    off += 2
    key = bytes(mv[off:off + klen]).decode()
    off += klen
    (dlen,) = struct.unpack_from('<B', mv, off)
    off += 1
    dtype = np.dtype(bytes(mv[off:off + dlen]).decode())
    off += dlen
    (ndim,) = struct.unpack_from('<B', mv, off)
    off += 1
    shape = struct.unpack_from(f'<{ndim}q', mv, off) if ndim else ()
    off += 8 * ndim
    (nbytes,) = struct.unpack_from('<Q', mv, off)
    off += 8
    off += (-off) % 8  # skip the writer's data-alignment pad
    arr = np.frombuffer(mv, dtype=dtype, count=nbytes // dtype.itemsize,
                        offset=off).reshape(shape)
    off += nbytes
    out[key] = arr
  return out


class QueueTimeoutError(RuntimeError):
  """Reference: include/shm_queue.h QueueTimeoutError.

  When the stalled stream belongs to a known tenant, :meth:`with_context`
  stamps the tenant id and its last-seen quota snapshot onto the error
  so a starved tenant's timeout names WHO hit WHAT limit instead of
  reading as an anonymous stall (docs/multi_tenancy.md).
  """

  tenant: str = None
  quota: dict = None

  def with_context(self, tenant=None, quota=None) -> 'QueueTimeoutError':
    """Attach tenant/quota context and fold it into the message."""
    self.tenant = tenant
    self.quota = dict(quota) if quota else None
    parts = []
    if tenant is not None:
      parts.append(f'tenant={tenant!r}')
    if self.quota:
      parts.append(f'quota={self.quota}')
    if parts and self.args:
      self.args = (f'{self.args[0]} [{", ".join(parts)}]',) + self.args[1:]
    elif parts:
      self.args = (f'[{", ".join(parts)}]',)
    return self


class ChannelBase:
  """Reference: channel/base.py:25-47."""

  def send(self, msg: SampleMessage):
    raise NotImplementedError

  def recv(self, timeout_ms: int = -1) -> SampleMessage:
    raise NotImplementedError

  def empty(self) -> bool:
    raise NotImplementedError
