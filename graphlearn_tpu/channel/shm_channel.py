"""Shared-memory channel over the native C++ ring queue.

TPU-native port of
/root/reference/graphlearn_torch/python/channel/shm_channel.py: wraps the
native SampleQueue (csrc/shm_queue.cc here) with message (de)serialization,
timeout recv, and fork/spawn pickling by shmid (reference
py_export.cc:137-154). `pin_memory` is accepted for API parity; on TPU the
H2D path is jax.device_put from the deserialized views, so there is no
cudaHostRegister equivalent to apply.
"""
import ctypes
import threading
import weakref
from typing import Optional

from .base import (ChannelBase, QueueTimeoutError, SampleMessage,
                   deserialize_message, serialize_message)

# Census of ShmChannels open in THIS process (weak — a collected channel
# drops out even if close() was never called). The shutdown-leak
# regression tests assert this returns to baseline after
# create/kill/destroy cycles; see DistServer.destroy_sampling_producer.
_live_channels: 'weakref.WeakSet' = weakref.WeakSet()


def live_channel_count() -> int:
  """Number of open (not yet close()d) ShmChannels in this process."""
  return sum(1 for c in _live_channels if c._q)


class ShmChannel(ChannelBase):
  """Reference: shm_channel.py:24-66."""

  def __init__(self, capacity: Optional[int] = None,
               shm_size: Optional[int] = None, pin_memory: bool = False,
               _shmid: Optional[int] = None):
    from ..utils.build import load_native
    self._lib = load_native()
    del capacity  # ring is byte-bounded; block count is implicit
    self.shm_size = shm_size or (1 << 26)  # 64 MiB default
    self.pin_memory = pin_memory
    if _shmid is not None:
      self._q = self._lib.shmq_attach(_shmid)
      if not self._q:
        raise RuntimeError(f'shmq_attach({_shmid}) failed')
    else:
      self._q = self._lib.shmq_create(self.shm_size)
      if not self._q:
        raise RuntimeError('shmq_create failed')
    # recv is a peek(size)-then-dequeue pair in two native critical
    # sections; concurrent recv callers in one process (e.g. DistServer
    # handlers on a ThreadingTCPServer) could interleave them and size the
    # dequeue buffer for a different block. Serialize the pair per process
    # (each process re-attaching via __reduce__ builds its own lock).
    self._recv_lock = threading.Lock()
    self._received = 0   # messages recv'd in THIS process (diagnostics)
    _live_channels.add(self)

  @property
  def shmid(self) -> int:
    return self._lib.shmq_id(self._q)

  def send(self, msg: SampleMessage):
    from ..utils.faults import fault_point
    if fault_point('channel.shm.send') == 'drop':
      return   # injected message loss: consumers must survive a gap
    buf = serialize_message(msg)
    rc = self._lib.shmq_enqueue(self._q, buf, len(buf))
    if rc != 0:
      raise RuntimeError(
          f'message of {len(buf)} bytes exceeds ring capacity '
          f'{self.shm_size}')

  def _timeout(self, timeout_ms: int) -> QueueTimeoutError:
    return QueueTimeoutError(
        f'shm channel recv timed out after {timeout_ms}ms '
        f'(shmid={self.shmid}, ring={self.shm_size} bytes, '
        f'received_so_far={self._received} in this process) — producers '
        'sent nothing in the window; check producer worker health')

  def recv(self, timeout_ms: int = -1) -> SampleMessage:
    with self._recv_lock:
      size = self._lib.shmq_next_size(self._q, timeout_ms)
      if size == -1:
        raise self._timeout(timeout_ms)
      if size == -2:
        raise StopIteration('channel finished')
      buf = ctypes.create_string_buffer(size)
      got = self._lib.shmq_dequeue(self._q, buf, size, timeout_ms)
      if got == -1:
        raise self._timeout(timeout_ms)
      if got == -2:
        raise StopIteration('channel finished')
      assert got == size, (got, size)
      self._received += 1
    return deserialize_message(bytes(buf))

  def empty(self) -> bool:
    return self._lib.shmq_count(self._q) == 0

  def finish(self):
    """Producer end-of-epoch mark (end-of-stream protocol)."""
    self._lib.shmq_finish(self._q)

  def reset(self):
    self._lib.shmq_reset_finished(self._q)

  def close(self):
    if self._q:
      self._lib.shmq_close(self._q)
      self._q = None

  # pickling by shmid: consumer processes re-attach
  def __reduce__(self):
    return (ShmChannel, (None, self.shm_size, self.pin_memory, self.shmid))
