from .base import (ChannelBase, QueueTimeoutError, SampleMessage,
                   deserialize_message, serialize_message)
from .mp_channel import MpChannel
from .remote_channel import PeerDeadError, RemoteReceivingChannel
from .shm_channel import ShmChannel, live_channel_count
