from .base import (ChannelBase, QueueTimeoutError, SampleMessage,
                   deserialize_message, serialize_message)
from .mp_channel import MpChannel
from .remote_channel import RemoteReceivingChannel
from .shm_channel import ShmChannel
