"""Multiprocessing-queue channel.

TPU-native port of
/root/reference/graphlearn_torch/python/channel/mp_channel.py: a plain
multiprocessing.Queue fallback (slower than shm, no native dependency).
"""
import multiprocessing as mp
import queue as queue_mod

from .base import ChannelBase, QueueTimeoutError, SampleMessage


class MpChannel(ChannelBase):
  """Reference: channel/mp_channel.py:24-34."""

  def __init__(self, capacity: int = 128, **kwargs):
    ctx = mp.get_context('spawn')
    self._queue = ctx.Queue(maxsize=capacity)
    self._capacity = capacity
    self._received = 0   # messages recv'd in THIS process (diagnostics)

  def send(self, msg: SampleMessage):
    self._queue.put(msg)

  def recv(self, timeout_ms: int = -1) -> SampleMessage:
    try:
      timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
      msg = self._queue.get(timeout=timeout)
    except queue_mod.Empty as e:
      raise QueueTimeoutError(
          f'mp channel recv timed out after {timeout_ms}ms '
          f'(capacity={self._capacity}, received_so_far='
          f'{self._received} in this process) — no producer put a '
          'message in the window; check producer health') from e
    self._received += 1
    return msg

  def empty(self) -> bool:
    return self._queue.empty()
