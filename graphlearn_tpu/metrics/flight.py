"""Epoch flight recorder: one structured JSONL record per epoch.

Long production runs degrade in ways a final loss curve hides — a
failover absorbed mid-epoch, a feature cache slowly losing its hit
rate, a dispatch count creeping up after a refactor. The flight
recorder writes ONE JSON line per epoch to the file named by the
``GLT_RUN_LOG`` environment variable so a finished (or crashed) run
can be diffed epoch-by-epoch after the fact (docs/observability.md
documents the schema and a jq cookbook).

Emitters: ``ScanTrainer``/``DistScanTrainer`` (the scanned epoch
programs), ``OverlappedTrainer``, and the per-step loader loops
(``NodeLoader``/``DistLoader``/remote/mp ``__iter__``). Every record
carries DELTAS over the epoch — metric counters, per-site dispatch
counts — plus wall time, a config fingerprint, and the staged
device-trace key (GLT_PROFILE_DIR) when a trace is being captured.

Hot-path contract: :func:`epoch_begin` and :func:`epoch_end` touch
ONLY host state (the metric registry, the active DispatchCounter, the
clock) — zero device->host fetches and zero extra program dispatches.
The feature fields bit-match the live ``dist_feature.*`` counters
because emitters call :func:`epoch_end` AFTER the loader's existing
once-per-epoch ``publish_stats`` fetch, never by fetching anything
themselves. When ``GLT_RUN_LOG`` is unset, ``epoch_begin`` returns
None and both calls are a single falsy check.
"""
import hashlib
import json
import logging
import os
import threading
import time
from typing import Optional

ENV_VAR = 'GLT_RUN_LOG'
SCHEMA = 1

logger = logging.getLogger('graphlearn_tpu.flight')
_warned_paths = set()   # one write-failure warning per path, not per epoch


class JsonlAppender:
  """Append JSON records to a JSONL trail, tolerating an unwritable
  path with ONE warning (records are then dropped — observability must
  never kill work). Shared by the flight and span recorders.

  ``keep_open=True`` holds a flushed append handle between records —
  the span recorder emits per-RPC/per-request, where a fresh
  open/close per record would tax the very latencies being measured.
  The flight recorder writes once per epoch and keeps the default
  (per-record open), preserving recreate-the-file-under-it semantics.
  A path change (tests pointing the env var at a fresh tmp dir)
  reopens transparently."""

  def __init__(self, env_var: str, keep_open: bool = False):
    self._env_var = env_var
    self._keep_open = keep_open
    self._lock = threading.Lock()
    # keep-open file handle shared by every thread that appends a
    # record — open/write/reset all hold _lock
    # graftlint: shared[_lock]
    self._path: Optional[str] = None
    # graftlint: shared[_lock]
    self._fh = None

  def append(self, path: str, rec: dict) -> bool:
    line = json.dumps(rec, sort_keys=True) + '\n'
    try:
      with self._lock:
        if not self._keep_open:
          with open(path, 'a', encoding='utf-8') as fh:
            fh.write(line)
          return True
        if self._fh is None or self._path != path:
          if self._fh is not None:
            try:
              self._fh.close()
            except OSError:
              pass
          self._fh = open(path, 'a', encoding='utf-8')
          self._path = path
        self._fh.write(line)
        self._fh.flush()   # readers (tests, tail -f) see records live
      return True
    except OSError as e:
      with self._lock:
        self._fh = None
        self._path = None
      if path not in _warned_paths:
        _warned_paths.add(path)
        logger.warning('%s=%s is unwritable (%s) — records for this '
                       'path are being dropped', self._env_var, path, e)
      return False


def read_jsonl(path: Optional[str],
               kind: Optional[str] = None) -> list:
  """Parse a JSONL trail back into record dicts, optionally filtered
  by their ``kind`` field. Unparseable lines are skipped — a run
  killed mid-write must not take the rest of the log with it. Shared
  by flight.read_records and spans.read_log."""
  if not path or not os.path.exists(path):
    return []
  out = []
  with open(path, encoding='utf-8') as fh:
    for line in fh:
      line = line.strip()
      if not line:
        continue
      try:
        rec = json.loads(line)
      except ValueError:
        continue
      if kind is not None and not (isinstance(rec, dict) and
                                   rec.get('kind') == kind):
        continue
      out.append(rec)
  return out


_appender = JsonlAppender(ENV_VAR)


def run_log_path() -> Optional[str]:
  """The active flight-record path, or None (recording disabled)."""
  return os.environ.get(ENV_VAR) or None


def _jsonable(obj):
  """Best-effort JSON coercion: tuple/EdgeType dict keys become
  strings, arrays/odd leaves fall back to str — a flight record must
  never crash an epoch over an exotic config value."""
  if isinstance(obj, dict):
    return {str(k): _jsonable(v) for k, v in obj.items()}
  if isinstance(obj, (list, tuple)):
    return [_jsonable(v) for v in obj]
  if isinstance(obj, (str, int, float, bool)) or obj is None:
    return obj
  return str(obj)


def config_fingerprint(config: dict) -> str:
  """Stable 16-hex digest of an emitter's static configuration —
  records from the same run share it, so a postmortem diff can group
  epochs by configuration across restarts."""
  blob = json.dumps(_jsonable(config or {}), sort_keys=True)
  return hashlib.sha1(blob.encode()).hexdigest()[:16]


def epoch_begin() -> Optional[dict]:
  """Snapshot the counter/dispatch baselines at epoch start. Returns
  an opaque token for :func:`epoch_end`, or None when recording is
  off (the fast path: one env read)."""
  path = run_log_path()
  if not path:
    return None
  from ..utils import trace
  from . import programs
  from .registry import default_registry
  return {'path': path,
          't0': time.perf_counter(),
          'counters': default_registry().counters(),
          'dispatch': trace.dispatch_snapshot(),
          'programs': programs.flight_snapshot()}


def _delta(now: dict, base: dict) -> dict:
  return {k: v - base.get(k, 0) for k, v in now.items()
          if v != base.get(k, 0)}


def epoch_end(token: Optional[dict], emitter: str, epoch: int,
              steps: int, config: Optional[dict] = None,
              completed: bool = True,
              extra: Optional[dict] = None) -> Optional[dict]:
  """Write this epoch's record (no-op when ``token`` is None). Returns
  the record dict that was appended.

  ``dispatch`` is the per-site delta of the ACTIVE ``count_dispatches``
  region (None when no region is active — the recorder never creates
  one); ``feature``/``resilience``/``fault`` split the metric-counter
  deltas by subsystem prefix so the acceptance check — record fields
  bit-match the live counters — is a plain dict compare.
  """
  if token is None:
    return None
  from ..utils import trace
  from . import programs, spans
  from .registry import default_registry
  wall = time.perf_counter() - token['t0']
  cdelta = _delta(default_registry().counters(), token['counters'])
  d_now = trace.dispatch_snapshot()
  if d_now is None or token['dispatch'] is None:
    dispatch = None
  else:
    dispatch = _delta(d_now, token['dispatch'])
  # program-observatory delta: which sites compiled/dispatched THIS
  # epoch (host bookkeeping only — epoch 1 shows the compiles, a
  # steady-state epoch shows pure dispatch counts, and a retrace
  # mid-run shows up as a compiles delta on an old site)
  prog_base = token.get('programs') or {}
  prog = {}
  for site, now in programs.flight_snapshot().items():
    base = prog_base.get(site, {})
    d = {k: round(v - base.get(k, 0), 6) for k, v in now.items()
         if v != base.get(k, 0)}
    if d:
      prog[site] = d

  def split(*prefixes):
    return {k: v for k, v in cdelta.items()
            if any(k.startswith(p + '.') for p in prefixes)}

  feature = split('dist_feature', 'dist_label')
  resilience = split('resilience')
  fault = split('fault')
  # per-epoch staging deltas (the out-of-core tiers, storage/): rows
  # and bytes the chunk-boundary pipeline staged this epoch, plus the
  # synchronous fallback reads (prefetch_miss) — a degrading prefetch
  # hit rate is visible epoch by epoch
  storage = split('storage')
  # multi-tenant backpressure deltas (distributed/tenancy.py): the
  # throttle/starve counters and backpressure_ms a contended epoch
  # accumulated — visible per epoch, next to the resilience story
  tenant = split('tenant')
  known = (set(feature) | set(resilience) | set(fault) | set(storage)
           | set(tenant))
  record = {
      'schema': SCHEMA,
      'kind': 'epoch',
      # run_id joins this record to metric scrapes and span trees from
      # the same run (spans.run_id — GLT_RUN_ID or minted per process)
      'run_id': spans.run_id(),
      'emitter': emitter,
      'epoch': int(epoch),
      'steps': int(steps),
      'completed': bool(completed),
      'wall_s': round(wall, 6),
      'dispatch': dispatch,
      'dispatch_total': (sum(dispatch.values())
                         if dispatch is not None else None),
      'feature': feature,
      'resilience': resilience,
      'fault': fault,
      'storage': storage,
      'tenant': tenant,
      'programs': prog,
      'counters': {k: v for k, v in cdelta.items() if k not in known},
      'config': _jsonable(config or {}),
      'config_fingerprint': config_fingerprint(config or {}),
      'trace': {'profile_dir': os.environ.get('GLT_PROFILE_DIR')},
      'time_unix': round(time.time(), 3),
  }
  if extra:
    record.update(_jsonable(extra))
  _appender.append(token['path'], record)
  return record


def end_for(obj, token: Optional[dict], *, steps: int,
            completed: bool = True, config: Optional[dict] = None,
            extra: Optional[dict] = None, emitter: Optional[str] = None,
            epoch: Optional[int] = None) -> Optional[dict]:
  """:func:`epoch_end` plus the per-emitter epoch counter: reads and
  advances ``obj._flight_epochs`` (lazily initialized) so every
  per-step emitter shares one bookkeeping implementation instead of
  re-rolling the getattr dance. ``epoch`` overrides the recorded
  number (emitters with their own counter, e.g. the remote loaders'
  ``_epoch``) — the instance counter still advances."""
  n = getattr(obj, '_flight_epochs', 0)
  rec = epoch_end(token, emitter=emitter or type(obj).__name__,
                  epoch=n if epoch is None else epoch, steps=steps,
                  completed=completed, config=config, extra=extra)
  obj._flight_epochs = n + 1
  return rec


def read_records(path: Optional[str] = None) -> list:
  """Parse a flight log back into record dicts (postmortem tooling /
  tests). Unparseable lines are skipped — a run killed mid-write must
  not take the rest of the log with it."""
  return read_jsonl(path or run_log_path())
