"""The closed inventory of metric names this package emits.

graftlint's ``metric-registry`` rule (analysis/metric_names.py) parses
this frozenset FROM SOURCE — it never imports the package — and checks
every metric-emitting call site in ``graphlearn_tpu/`` against it:
names must be string literals (or f-strings whose literal head matches
a ``<prefix>.*`` wildcard entry below), and every entry must be
documented in the docs/observability.md naming table. Adding a metric
means registering it here and documenting it there, in the same change
— the same closed-namespace discipline as utils/faults.py
REGISTERED_SITES.

Names are ``<subsystem>.<event>`` (one dot minimum; histograms end in
a unit suffix like ``_ms``). Wildcard entries ``<prefix>.*`` cover
families whose tails are minted at runtime (per-fault-site counters,
the feature stores' published stat keys).
"""

REGISTERED_METRICS = frozenset({
    # resilience events (distributed/resilience.py + consumers)
    'resilience.retry',
    'resilience.server_dead',
    'resilience.failover',
    'resilience.failover_seeds',
    'resilience.worker_restart',
    'resilience.producer_reaped',
    # fault injection: one counter per armed site (utils/faults.py)
    'fault.*',
    # per-epoch feature-store stats published by publish_stats
    # (distributed/dist_feature.py; label stores publish under
    # dist_label so the headline dist_feature parity stays clean)
    'dist_feature.*',
    'dist_label.*',
    # mp sampling workers (distributed/dist_sampling_producer.py)
    'producer.batches',
    'producer.sample_ms',
    # RPC plane latencies (distributed/rpc.py, dist_server.py) — the
    # p50/p99 substrate the serving tier gates on (ROADMAP item 1)
    'rpc.client.request_ms',
    'server.fetch_ms',
    # scrape plumbing (metrics/scrape.py)
    'metrics.scrape_error',
    # online serving endpoint (serving/engine.py) — the end-to-end
    # latency/throughput surface bench.py --gate regression-tracks
    'serving.requests',
    'serving.batches',
    'serving.refreshed',
    'serving.rotations',
    'serving.rotation_swap_ms',
    'serving.rotation_errors',
    'serving.queue_wait_ms',
    'serving.batch_fill',
    'serving.compute_ms',
    'serving.total_ms',
    # program observatory (metrics/programs.py): compiles/retraces at
    # instrumented dispatch sites; per-site detail lives in the
    # ProgramRegistry (flight 'programs' field), not the metric store
    'program.compiles',
    'program.retraces',
    'program.compile_ms',
    'program.retrace_budget_exceeded',
    # out-of-core tiered feature storage (graphlearn_tpu/storage/):
    # the chunk-boundary staging pipeline's counters/latencies plus
    # tier-occupancy gauges (docs/storage.md)
    'storage.staged_rows',
    'storage.staged_bytes',
    'storage.dist_staged_rows',
    'storage.prefetch_miss',
    # demand-paged PER-STEP gather on oversubscribed dist stores
    # (storage/dist.py): one demand_pages tick per get() step, staged
    # row count, and the host routing+gather latency
    'storage.demand_pages',
    'storage.demand_paged_rows',
    'storage.demand_page_ms',
    'storage.stage_ms',
    'storage.promote_ms',
    'storage.ring_rows',
    'storage.hot_rows',
    'storage.warm_rows',
    'storage.disk_rows',
    # chunk-staged remote scan (distributed/remote_scan.py +
    # block_producer.py, docs/remote_scan.md): K-batch block exchange
    # between sampling servers and the scanned client
    'remote.blocks',
    'remote.block_bytes',
    'remote.block_mb_per_chunk',
    'remote.block_fetch_ms',
    'remote.block_stage_ms',
    'remote.prefetch_miss',
    'remote.failover_blocks',
    # chunk-granular recovery (graphlearn_tpu/recovery/): async exact
    # checkpointing at chunk boundaries + mid-epoch resume + scanned
    # failover rollback (docs/recovery.md)
    # Pallas kernel routing (ops/gather_pallas.py, ops/sample_fused.py +
    # sampler/neighbor_sampler.py): evidence-gated kernel-path
    # observability — how often the measured-win flags actually route
    # through a kernel vs fall back to XLA (docs/observability.md)
    'ops.gather_runs',
    'ops.gather_fallbacks',
    'ops.fused_hop_calls',
    'ops.fused_level_calls',
    'ops.gather_ms',
    'checkpoint.saves',
    'checkpoint.bytes',
    'checkpoint.save_ms',
    'checkpoint.capture_ms',
    'checkpoint.sync_fallback',
    'checkpoint.save_errors',
    'checkpoint.torn_skipped',
    'checkpoint.restore_ms',
    'recovery.resumes',
    'recovery.resume_chunks',
    'recovery.rollbacks',
    # one-call autotuner (graphlearn_tpu/tune/, docs/tuning.md):
    # observatory-scored candidate A/Bs behind the config artifact
    'tune.candidates',
    'tune.rejected',
    'tune.probe_ms',
    'tune.artifacts',
    # continuous retuning (tune/retune.py, docs/tuning.md 'Continuous
    # retuning'): drift-trigger fires, successful shadow-retune
    # publishes, and the shadow replica's tune wall
    'tune.retunes',
    'tune.drift_triggers',
    'tune.shadow_wall_ms',
    # run-as-a-program (loader/run_epoch.py): whole-run scans with
    # in-carry eval + early stop — host-side schedule counters only
    # (the stop point itself is device state, read from the report)
    'run.runs',
    'run.epochs_scheduled',
    # multi-tenant service fabric (distributed/tenancy.py +
    # dist_server.py, docs/multi_tenancy.md): admission rejections,
    # fair-scheduler waits, client-visible backpressure, and the
    # per-tenant reap family (tails minted as tenant.reaped.<tenant>)
    'tenant.admit_rejections',
    'tenant.throttled',
    'tenant.starved',
    'tenant.sched_wait_ms',
    'tenant.backpressure_ms',
    'tenant.rebalanced_blocks',
    'tenant.*',
})

# The closed inventory of SPAN names (metrics/spans.py) — the same
# contract as metrics: literal at every spans.span/begin/emit call
# site, registered here, documented in the docs/observability.md span
# table. Enforced by graftlint's ``span-registry`` rule; the baseline
# stays empty.
REGISTERED_SPANS = frozenset({
    # RPC plane (distributed/rpc.py): one client span per round trip,
    # one server span per handled request — the cross-process seam
    'rpc.client.request',
    'rpc.server.handle',
    # epoch drivers (loader/scan_epoch.py, distributed/dist_loader.py)
    'epoch.run',
    'epoch.chunk',
    # remote-loader failover (distributed/dist_loader.py): carries the
    # resilience annotations for the degraded epoch's span tree
    'loader.failover',
    # mp sampling workers (distributed/dist_sampling_producer.py)
    'producer.epoch',
    'producer.batch',
    # online serving (serving/engine.py): the queue→batch→compute→
    # respond tree one request yields (docs/serving.md)
    'serving.request',
    'serving.queue',
    'serving.batch',
    'serving.compute',
    'serving.respond',
    # sharded store rotation (serving/rotation.py): one span per
    # version swap critical section (docs/serving.md)
    'serving.rotate',
    # out-of-core staging pipeline (storage/staging.py): one span per
    # staged chunk on the worker thread
    'storage.stage',
    # demand-paged per-step gather (storage/dist.py): one span per
    # oversubscribed get() step's host routing + tier gather
    'storage.demand_page',
    # chunk-staged remote scan (docs/remote_scan.md): one span per
    # server-side block build and one per client-side block fetch
    'remote.block_stage',
    'remote.block_fetch',
    # chunk-granular recovery (recovery/): one span per snapshot write
    # (worker thread or sync fallback) and one wrapping each mid-epoch
    # resume; the failover rollback reuses `loader.failover` with the
    # rolled-back chunk index in its attrs (docs/recovery.md)
    'checkpoint.save',
    'recovery.resume',
    # one-call autotuner (tune/tuner.py): one span per tune() run, one
    # per candidate A/B (compile + steady epochs inside)
    'tune.run',
    'tune.candidate',
    # continuous retuning (tune/retune.py): one span per shadow
    # retune attempt, carrying the firing drift trigger in its attrs
    'tune.retune',
    # run-as-a-program (loader/run_epoch.py): one span wrapping the
    # whole multi-epoch run; the inherited epoch.run/epoch.chunk spans
    # parent under it
    'run.train',
    # multi-tenant backpressure (distributed/tenancy.py): one span per
    # bounded-backoff throttle wait on the client, parented under the
    # epoch root via the stager's adopted context (docs/multi_tenancy.md)
    'tenant.throttle',
})
