"""graphlearn_tpu.metrics: the unified observability layer.

Three pieces (docs/observability.md):

* a typed, thread-safe, process-local metric registry — Counter /
  Gauge / Histogram with fixed log-spaced buckets and p50/p95/p99
  estimation (``registry``); ``utils.trace.counter_inc`` and friends
  are thin compatibility shims over it, so every existing counter
  call site feeds the same store;
* cross-process scraping — ``scrape_all()`` assembles role-labelled
  snapshots from this process, registered local sources, and every
  connected sampling server (``DistServer.get_metrics`` RPC +
  producer worker snapshots), ``merge_scrape`` folds them into one
  cluster view;
* the epoch flight recorder (``flight``) — one JSONL record per epoch
  to ``GLT_RUN_LOG`` for postmortem diffing of long runs;
* the program observatory (``programs``) — compile/retrace detection
  with signature diffs and opt-in XLA cost attribution at every
  instrumented dispatch site, plus the ``retrace_budget`` guard rail;
* correlated spans (``spans``) — host-clock begin/end records with a
  ``run_id``/request-id context propagated over RPC metadata, the mp
  worker snapshot queue and ``ServingEngine.submit``, recoverable
  across processes from ``scrape_all()`` + ``GLT_SPAN_LOG``.

The package is ZERO-DEPENDENCY (pure stdlib): mp sampling workers,
bench tooling and the static analyzer's fixtures all import it
without pulling jax. Metric names form a closed namespace —
``registry_names.REGISTERED_METRICS`` — enforced by graftlint's
``metric-registry`` rule.

Idiomatic call forms (the forms the lint rule checks)::

    from graphlearn_tpu import metrics
    metrics.inc('resilience.retry')
    metrics.observe('rpc.client.request_ms', dt_ms)
    metrics.set_gauge('serving.queue_depth', n)
    metrics.snapshot()           # this process
    metrics.scrape_all()         # the cluster, role-labelled
"""
from . import flight, programs, spans
from .programs import (ProgramRegistry, RetraceBudgetExceeded,
                       default_program_registry, instrument,
                       retrace_budget)
from .registry import (BUCKET_SCHEMA, HIST_BOUNDS, Counter, Gauge,
                       Histogram, MetricRegistry, default_registry,
                       merge_snapshots, quantile_from_state)
from .registry_names import REGISTERED_METRICS, REGISTERED_SPANS
from .scrape import (merge_scrape, register_source, scrape_all,
                     unregister_source)


def counter(name: str) -> Counter:
  return default_registry().counter(name)


def gauge(name: str) -> Gauge:
  return default_registry().gauge(name)


def histogram(name: str) -> Histogram:
  return default_registry().histogram(name)


def inc(name: str, n: int = 1):
  default_registry().inc(name, n)


def set_gauge(name: str, value: float):
  default_registry().set_gauge(name, value)


def observe(name: str, value: float):
  default_registry().observe(name, value)


def snapshot() -> dict:
  return default_registry().snapshot()


def reset(prefix: str = ''):
  default_registry().reset(prefix)
