"""Program observatory: compile / retrace / cost attribution per
dispatch site.

The repo's perf contract is PROGRAM-shaped — "ceil(steps/K) + 2
dispatches", "ONE executable per chunk length", "one persistent jitted
program per bucket" — yet compiles and retraces are invisible at
runtime: a silent retrace (a new chunk length, an uncommitted sharding,
a dtype drift) multiplies epoch wall clock and until now was only
caught by test-only "one executable" asserts. The observatory makes the
program population a first-class observable:

* :func:`instrument` wraps a jitted callable at its DISPATCH SITE (the
  same sites ``record_dispatch`` already names) and detects compiles by
  watching the jit cache size across the call — pure host bookkeeping,
  ZERO added device dispatches and zero fetches (the GLT_STRICT
  dispatch-budget tests bit-match the live DispatchCounter with the
  observatory armed).
* Every compile records the triggering ABSTRACT SIGNATURE
  (shape/dtype/weak-type/sharding per leaf, repr for statics) and a
  human-readable diff against the site's previous compile — "arg 2:
  f32[8,128] -> bf16[8,128]" — so "why did this retrace" is answered
  from the record, not a re-run under jax logging.
* When ``GLT_PROGRAM_COST=1``, each NEW executable is additionally
  lowered+compiled once through the AOT path to capture XLA
  ``cost_analysis()`` / ``memory_analysis()`` attribution (flops, bytes
  accessed, peak HBM estimate, donation efficacy) — the per-program
  cost signal ROADMAP items 4/5 (Pallas floor attack, one-call
  autotune) take as input. Off by default: the AOT compile is a second
  host-side compilation of the same program (never a dispatch).
* :func:`retrace_budget` turns the test-only "one executable" asserts
  into a production guard rail: exceeding the budget raises under
  ``GLT_STRICT`` and warns otherwise, with the signature diff naming
  the argument that changed.

Everything exports through the existing machinery: ``program.compiles``
/ ``program.retraces`` / ``program.compile_ms`` land in the metric
registry (scraped cluster-wide), and the flight recorder embeds the
per-site delta of :func:`flight_snapshot` as each epoch record's
``programs`` field (docs/observability.md).

Zero-dependency at import: jax is only touched lazily, from inside an
instrumented call — which by construction means jax is already loaded.
"""
import collections
import contextlib
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

COST_ENV = 'GLT_PROGRAM_COST'

#: signatures longer than this keep only a prefix in the stored event
#: (the diff walks the FULL tuples — via each site's last_signature —
#: before the event stores its truncated copy)
_SIG_STORE_LIMIT = 64

#: compile-event ring bound: a pathological retrace storm — the exact
#: failure the observatory exists to surface — must not leak host
#: memory linearly in a long-lived server (cost totals accumulate in
#: running scalars, so eviction never under-reports the aggregate)
_EVENT_RING = 1024


def cost_enabled() -> bool:
  """True when GLT_PROGRAM_COST asks for XLA cost/memory attribution
  (one extra host-side AOT compile per NEW executable, no dispatches)."""
  return os.environ.get(COST_ENV, '') not in ('', '0')


class RetraceBudgetExceeded(RuntimeError):
  """A retrace_budget() region compiled more programs than allowed."""


# ---------------------------------------------------------------- signature


def _leaf_desc(leaf) -> str:
  """One leaf's abstract signature: ``dtype[shape]{@sharding}`` for
  array-likes, ``static:<repr>`` for everything else (static argnums,
  config scalars). Host-only attribute reads — never forces a value."""
  shape = getattr(leaf, 'shape', None)
  dtype = getattr(leaf, 'dtype', None)
  if shape is not None and dtype is not None:
    d = f'{dtype}[{",".join(str(s) for s in shape)}]'
    if getattr(leaf, 'weak_type', False):
      d += '~weak'
    spec = getattr(getattr(leaf, 'sharding', None), 'spec', None)
    if spec is not None:
      d += f'@{spec}'
    return d
  if isinstance(leaf, (int, float, bool, str, bytes, type(None))):
    return f'static:{leaf!r}'
  return f'static:<{type(leaf).__name__}>'


def signature_of(args: tuple, kwargs: dict) -> Tuple[str, ...]:
  """Flat abstract signature of a call's arguments — the host-side
  stand-in for the jit cache key (shapes, dtypes, weak types, sharding
  specs, static values). Computed only when a compile is detected, so
  the per-dispatch cost stays one cache-size read."""
  try:
    import jax
    leaves = jax.tree_util.tree_leaves((args, dict(kwargs or {})))
  except Exception:  # noqa: BLE001 - observatory must not break a call
    leaves = list(args) + list((kwargs or {}).values())
  return tuple(_leaf_desc(leaf) for leaf in leaves)


def diff_signatures(prev: Optional[Tuple[str, ...]],
                    new: Tuple[str, ...], limit: int = 4) -> str:
  """Human-readable "why did this retrace": the per-argument changes
  between the previous compile's signature and this one's."""
  if prev is None:
    return 'first compile'
  msgs = []
  if len(prev) != len(new):
    msgs.append(f'arg count {len(prev)} -> {len(new)}')
  for i, (a, b) in enumerate(zip(prev, new)):
    if a != b:
      msgs.append(f'arg {i}: {a} -> {b}')
  if not msgs:
    return ('signature unchanged — retrace from non-argument state '
            '(donation, compiler options, or a cleared cache)')
  shown = msgs[:limit]
  if len(msgs) > limit:
    shown.append(f'(+{len(msgs) - limit} more)')
  return '; '.join(shown)


# ----------------------------------------------------------------- registry


class CompileEvent:
  """One compile at one site: when, how long the triggering call took,
  what signature triggered it, and why it differed from the last one."""

  __slots__ = ('site', 'index', 'wall_s', 'time_unix', 'signature',
               'diff', 'cost')

  def __init__(self, site: str, index: int, wall_s: float,
               signature: Tuple[str, ...], diff: str,
               cost: Optional[dict] = None):
    self.site = site
    self.index = index          # 0 = first compile; >= 1 = retrace
    self.wall_s = wall_s        # wall of the triggering call (trace +
    self.time_unix = time.time()  # compile + first execute)
    self.signature = signature
    self.diff = diff
    self.cost = cost

  def as_dict(self) -> dict:
    return dict(site=self.site, index=self.index,
                wall_s=round(self.wall_s, 6),
                time_unix=round(self.time_unix, 3),
                signature=list(self.signature[:_SIG_STORE_LIMIT]),
                diff=self.diff, cost=self.cost)


class _Site:
  __slots__ = ('compiles', 'dispatches', 'compile_s', 'last_signature',
               'last_event')

  def __init__(self):
    self.compiles: int = 0
    self.dispatches: int = 0
    self.compile_s: float = 0.0
    self.last_signature: Optional[Tuple[str, ...]] = None
    self.last_event: Optional[CompileEvent] = None


class ProgramRegistry:
  """Process-local, thread-safe site -> compile/dispatch/cost store.

  Fed by :func:`instrument` wrappers at the package's dispatch sites;
  read by ``retrace_budget``, the flight recorder (per-epoch deltas of
  :meth:`flight_snapshot`) and bench.py (:meth:`aggregate`)."""

  def __init__(self):
    self._lock = threading.Lock()
    self._sites: Dict[str, _Site] = {}
    self._events = collections.deque(maxlen=_EVENT_RING)
    self._flops_total: Optional[float] = None
    self._peak_hbm: Optional[float] = None

  def _site(self, name: str) -> _Site:
    s = self._sites.get(name)
    if s is None:
      s = self._sites[name] = _Site()
    return s

  def on_dispatch(self, site: str):
    with self._lock:
      self._site(site).dispatches += 1

  def on_compile(self, site: str, signature: Tuple[str, ...],
                 wall_s: float, cost: Optional[dict] = None
                 ) -> CompileEvent:
    with self._lock:
      s = self._site(site)
      diff = diff_signatures(s.last_signature, signature)
      # the event keeps a TRUNCATED signature copy (the full tuple
      # lives once per site in last_signature, for the next diff) so a
      # retrace storm's event ring holds bounded strings, not hundreds
      # of leaf descriptors per event
      ev = CompileEvent(site, s.compiles, wall_s,
                        signature[:_SIG_STORE_LIMIT], diff, cost)
      s.compiles += 1
      s.dispatches += 1
      s.compile_s += wall_s
      s.last_signature = signature
      s.last_event = ev
      self._events.append(ev)
      if cost and 'error' not in cost:
        if cost.get('flops') is not None:
          self._flops_total = (self._flops_total or 0.0) + \
              float(cost['flops'])
        if cost.get('peak_hbm_bytes') is not None:
          self._peak_hbm = max(self._peak_hbm or 0.0,
                               float(cost['peak_hbm_bytes']))
    # registry metrics AFTER the lock: the metric registry has its own
    from . import registry as _reg
    r = _reg.default_registry()
    r.inc('program.compiles')
    if ev.index > 0:
      r.inc('program.retraces')
    r.observe('program.compile_ms', wall_s * 1e3)
    return ev

  # -- reads -----------------------------------------------------------

  def compile_count(self, site: Optional[str] = None) -> int:
    with self._lock:
      if site is not None:
        s = self._sites.get(site)
        return s.compiles if s else 0
      return sum(s.compiles for s in self._sites.values())

  def retrace_count(self, site: Optional[str] = None) -> int:
    c = self.compile_count(site)
    if site is not None:
      return max(0, c - 1) if c else 0
    with self._lock:
      return sum(max(0, s.compiles - 1) for s in self._sites.values())

  def dispatch_count(self, site: str) -> int:
    with self._lock:
      s = self._sites.get(site)
      return s.dispatches if s else 0

  def last_compile(self, site: str) -> Optional[CompileEvent]:
    with self._lock:
      s = self._sites.get(site)
      return s.last_event if s else None

  def events(self, site: Optional[str] = None) -> List[CompileEvent]:
    with self._lock:
      return [e for e in self._events
              if site is None or e.site == site]

  def sites(self) -> List[str]:
    with self._lock:
      return sorted(self._sites)

  def flight_snapshot(self) -> Dict[str, dict]:
    """{site: {'compiles', 'dispatches', 'compile_s'}} — the flight
    recorder diffs two of these into an epoch's ``programs`` field."""
    with self._lock:
      return {n: dict(compiles=s.compiles, dispatches=s.dispatches,
                      compile_s=round(s.compile_s, 6))
              for n, s in self._sites.items()}

  def stats(self) -> Dict[str, dict]:
    """Per-site detail view (postmortem / bench tooling): counts plus
    the last compile's signature diff and captured cost."""
    with self._lock:
      out = {}
      for n, s in self._sites.items():
        out[n] = dict(
            compiles=s.compiles, retraces=max(0, s.compiles - 1),
            dispatches=s.dispatches, compile_s=round(s.compile_s, 6),
            last=(s.last_event.as_dict() if s.last_event else None))
      return out

  def aggregate(self) -> dict:
    """Whole-process totals — the bench.py keys (compile_count,
    compile_time_s_total, retrace_count, program_flops_total,
    program_peak_hbm_mb). Cost totals are None until any executable
    captured cost (GLT_PROGRAM_COST); they accumulate in running
    scalars, so event-ring eviction never under-reports them."""
    with self._lock:
      flops, peak = self._flops_total, self._peak_hbm
      return dict(
          compile_count=sum(s.compiles for s in self._sites.values()),
          retrace_count=sum(max(0, s.compiles - 1)
                            for s in self._sites.values()),
          compile_time_s_total=round(
              sum(s.compile_s for s in self._sites.values()), 6),
          program_flops_total=flops,
          program_peak_hbm_mb=(round(peak / 2**20, 3)
                               if peak is not None else None))

  def reset(self):
    with self._lock:
      self._sites.clear()
      self._events.clear()
      self._flops_total = None
      self._peak_hbm = None


_default = ProgramRegistry()


def default_program_registry() -> ProgramRegistry:
  return _default


def reset():
  _default.reset()


# -------------------------------------------------------- cost attribution


def capture_cost(fn: Callable, args: tuple, kwargs: dict) -> dict:
  """XLA cost/memory attribution for the executable ``fn`` compiled for
  ``(args, kwargs)``, via the AOT ``lower().compile()`` path — a second
  HOST-side compile of a program that just compiled anyway, never a
  device dispatch. Any failure (backend without cost analysis, deleted
  donated buffers, exotic statics) degrades to an ``{'error': ...}``
  leaf: attribution must never break the program it observes."""
  try:
    lowered = fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
      cost = cost[0] if cost else {}
    cost = cost or {}
    out = dict(
        flops=float(cost.get('flops', 0.0) or 0.0),
        bytes_accessed=float(cost.get('bytes accessed', 0.0) or 0.0))
    mem = compiled.memory_analysis()
    if mem is not None:
      arg_b = float(getattr(mem, 'argument_size_in_bytes', 0) or 0)
      out_b = float(getattr(mem, 'output_size_in_bytes', 0) or 0)
      tmp_b = float(getattr(mem, 'temp_size_in_bytes', 0) or 0)
      ali_b = float(getattr(mem, 'alias_size_in_bytes', 0) or 0)
      gen_b = float(getattr(mem, 'generated_code_size_in_bytes', 0) or 0)
      out.update(
          argument_bytes=arg_b, output_bytes=out_b, temp_bytes=tmp_b,
          alias_bytes=ali_b,
          # peak live-bytes estimate for one execution: args + outputs
          # + XLA temps + code, minus the donated (aliased) inputs that
          # never coexist with their outputs
          peak_hbm_bytes=max(0.0, arg_b + out_b + tmp_b + gen_b - ali_b),
          # donation efficacy: how much of the argument footprint the
          # compiler actually aliased into outputs (1.0 = every donated
          # byte reused; low values flag donations XLA declined)
          donation_efficacy=(ali_b / arg_b if arg_b else None))
    return out
  except Exception as e:  # noqa: BLE001 - attribution is best-effort
    return {'error': f'{type(e).__name__}: {e}'}


# -------------------------------------------------------------- instrument


def _cache_size_reader(fn) -> Optional[Callable[[], int]]:
  """The jit object's executable-cache-size hook, when it has one
  (jax.jit / pjit expose ``_cache_size``; a plain callable doesn't)."""
  reader = getattr(fn, '_cache_size', None)
  return reader if callable(reader) else None


def instrument(fn: Callable, site: str,
               registry: Optional[ProgramRegistry] = None) -> Callable:
  """Wrap a jitted callable so every call feeds the program observatory
  under ``site`` (the site names are the record_dispatch names — one
  vocabulary for budgets, flight records and the observatory).

  Per call: one cache-size read before and after the dispatch. When the
  cache grew, the call compiled: the signature is computed (host-only),
  diffed against the site's previous compile, and — under
  ``GLT_PROGRAM_COST=1`` — the new executable's XLA cost/memory
  attribution is captured once. Callables without cache introspection
  (already-wrapped functions, host fallbacks) degrade to
  dispatch-counting only. Idempotent: instrumenting an instrumented
  wrapper returns it unchanged (same site) or re-sites it."""
  import functools
  inner = getattr(fn, '_glt_instrumented', None)
  if inner is not None:
    fn = inner
  reg = registry or _default
  reader = _cache_size_reader(fn)
  # compile attribution is a WATERMARK on the cache size, advanced
  # under a wrapper-local lock (bookkeeping only — the dispatch itself
  # runs unlocked): two threads racing the same first call both see
  # the cache grow, but only the one that advances the watermark
  # records the compile — no spurious retraces, no double counts
  state = {'seen': reader() if reader is not None else 0}
  state_lock = threading.Lock()

  @functools.wraps(fn)
  def wrapper(*args, **kwargs):
    if reader is None:
      reg.on_dispatch(site)
      return fn(*args, **kwargs)
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    after = reader()
    compiled = False
    if after != state['seen']:
      with state_lock:
        if after > state['seen']:
          # N concurrent distinct-signature first calls may advance the
          # watermark in one jump; the winner records ONE compile (we
          # only hold one signature) — an under-count of N-1 in that
          # race, never a spurious retrace
          state['seen'] = after
          compiled = True
        elif after < state['seen']:
          # the jit cache SHRANK (jax.clear_caches / eviction): re-arm
          # the watermark at the new size and attribute this call as a
          # compile — after a cache clear the very next dispatch IS the
          # recompile, and a frozen high watermark would hide the whole
          # recompile storm from retrace_budget forever
          state['seen'] = after
          compiled = True
    if compiled:
      cost = capture_cost(fn, args, kwargs) if cost_enabled() else None
      reg.on_compile(site, signature_of(args, kwargs),
                     time.perf_counter() - t0, cost)
    else:
      reg.on_dispatch(site)
    return out

  wrapper._glt_instrumented = fn
  wrapper._glt_program_site = site
  # AOT surface passthrough: capture_cost and callers that .lower()
  for attr in ('lower', 'trace', '_cache_size'):
    val = getattr(fn, attr, None)
    if val is not None:
      setattr(wrapper, attr, val)
  return wrapper


# ----------------------------------------------------------- retrace budget


@contextlib.contextmanager
def retrace_budget(site: str, n: int,
                   registry: Optional[ProgramRegistry] = None):
  """Assert at most ``n`` compiles at ``site`` inside the region.

  The production form of the test-only "one executable per chunk
  length" asserts: a region that compiles more than budgeted RAISES
  :class:`RetraceBudgetExceeded` under ``GLT_STRICT`` and warns
  otherwise, and the message carries the last compile's signature diff
  — the argument whose shape/dtype/sharding drifted. Budget ``n`` is
  the number of compiles the region may legitimately pay (0 for a
  steady-state region whose programs must all already exist)."""
  reg = registry or _default
  base = reg.compile_count(site)
  yield
  extra = reg.compile_count(site) - base
  if extra <= n:
    return
  ev = reg.last_compile(site)
  why = f'last retrace: {ev.diff}' if ev is not None else 'no event'
  msg = (f'retrace budget exceeded at site {site!r}: {extra} compile(s) '
         f'in this region, budget {n}; {why}')
  from . import registry as _reg
  _reg.default_registry().inc('program.retrace_budget_exceeded')
  from ..utils.strict import strict_enabled
  if strict_enabled():
    raise RetraceBudgetExceeded(msg)
  warnings.warn(msg, RuntimeWarning, stacklevel=3)


# -------------------------------------------------------- module-level API


def compile_count(site: Optional[str] = None) -> int:
  return _default.compile_count(site)


def retrace_count(site: Optional[str] = None) -> int:
  return _default.retrace_count(site)


def last_compile(site: str) -> Optional[CompileEvent]:
  return _default.last_compile(site)


def stats() -> Dict[str, Any]:
  return _default.stats()


def aggregate() -> dict:
  return _default.aggregate()


def flight_snapshot() -> Dict[str, dict]:
  return _default.flight_snapshot()
