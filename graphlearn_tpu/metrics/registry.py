"""Typed metric registry: Counter / Gauge / Histogram, process-local.

The observability substrate for the decoupled production topology
(docs/observability.md): sampling servers, mp producer workers and
trainer clients each hold ONE process-local :class:`MetricRegistry`
(the module default), and the cross-process layers (DistServer's
``get_metrics`` RPC, the producers' worker snapshot queue,
``metrics.scrape_all()``) move plain-dict :func:`MetricRegistry
.snapshot` values between processes — picklable, JSON-able, and
mergeable with :func:`merge_snapshots`.

Design constraints, in order:

* **Zero-dependency.** Pure stdlib — the registry is imported by mp
  sampling workers (CPU-backend subprocesses), the static analyzer's
  test fixtures, and bench tooling; none of those may pull jax.
* **Thread-safe.** Increments arrive from heartbeat probes, channel
  puller threads, and RPC handler threads concurrently; every mutation
  and every snapshot takes the owning registry's lock (one lock per
  registry — contention is microscopic next to the socket/channel work
  around every call site).
* **Hot-path discipline.** Nothing here touches a device array. The
  scanned-epoch programs keep their on-device accumulators riding the
  scan carry (DistFeature stats rows) and publish into this registry
  once per epoch via the existing ``trace.counter_inc`` shim — the
  registry is where published numbers LAND, never a reason to fetch.

Metric names are ``<subsystem>.<event>`` strings. The exported
namespace is CLOSED: package code may only emit names registered in
``registry_names.REGISTERED_METRICS`` (graftlint's ``metric-registry``
rule enforces literal, registered names at every call site — see
docs/observability.md). The registry itself does not enforce this at
runtime: tests and downstream users may mint ad-hoc names freely.
"""
import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional

# Histogram buckets: fixed log-spaced upper bounds, 4 per decade over
# 1e-6 .. 1e9 (sub-microsecond .. ~31 years in seconds; equally serves
# millisecond latencies, byte counts, and batch sizes). Fixed-for-life
# so snapshots from any process/version merge bucket-for-bucket —
# BUCKET_SCHEMA is embedded in every snapshot and checked on merge.
BUCKETS_PER_DECADE = 4
_DECADE_LO, _DECADE_HI = -6, 9
HIST_BOUNDS: tuple = tuple(
    10.0 ** (k / BUCKETS_PER_DECADE)
    for k in range(_DECADE_LO * BUCKETS_PER_DECADE,
                   _DECADE_HI * BUCKETS_PER_DECADE + 1))
BUCKET_SCHEMA = f'log10:{BUCKETS_PER_DECADE}/decade:' \
                f'{_DECADE_LO}..{_DECADE_HI}'


class Counter:
  """Monotonic event count."""

  __slots__ = ('name', '_value', '_lock')
  kind = 'counter'

  def __init__(self, name: str, lock: threading.Lock):
    self.name = name
    self._value = 0
    self._lock = lock

  def inc(self, n: int = 1):
    with self._lock:
      self._value += n

  @property
  def value(self) -> int:
    with self._lock:
      return self._value


class Gauge:
  """Last-written instantaneous value."""

  __slots__ = ('name', '_value', '_lock')
  kind = 'gauge'

  def __init__(self, name: str, lock: threading.Lock):
    self.name = name
    self._value = 0.0
    self._lock = lock

  def set(self, value: float):
    with self._lock:
      self._value = float(value)

  @property
  def value(self) -> float:
    with self._lock:
      return self._value


class Histogram:
  """Fixed log-spaced-bucket histogram with quantile estimation.

  ``observe(v)`` drops v into one of ``len(HIST_BOUNDS) + 1`` buckets
  (bucket i holds values <= HIST_BOUNDS[i]; the last bucket is the
  +inf overflow). Quantiles interpolate GEOMETRICALLY inside the
  matched bucket (log-spaced bounds make log-linear interpolation the
  unbiased choice) and clamp to the observed min/max, so p50/p95/p99
  land within one bucket ratio (10^0.25 ~ 1.78x) of the exact sample
  quantile — tested against numpy on known distributions. Values <= 0
  clamp into the first bucket (durations and sizes are positive; a
  stray zero must not crash a production counter path).
  """

  __slots__ = ('name', '_counts', '_sum', '_count', '_min', '_max',
               '_lock')
  kind = 'histogram'

  def __init__(self, name: str, lock: threading.Lock):
    self.name = name
    self._counts = [0] * (len(HIST_BOUNDS) + 1)
    self._sum = 0.0
    self._count = 0
    self._min: Optional[float] = None
    self._max: Optional[float] = None
    self._lock = lock

  def observe(self, value: float):
    value = float(value)
    i = bisect.bisect_left(HIST_BOUNDS, value) if value > 0 else 0
    with self._lock:
      self._counts[i] += 1
      self._sum += value
      self._count += 1
      if self._min is None or value < self._min:
        self._min = value
      if self._max is None or value > self._max:
        self._max = value

  @property
  def count(self) -> int:
    with self._lock:
      return self._count

  @property
  def sum(self) -> float:
    with self._lock:
      return self._sum

  def state(self) -> dict:
    """Snapshot leaf (see MetricRegistry.snapshot for the schema)."""
    with self._lock:
      return dict(counts=list(self._counts), sum=self._sum,
                  count=self._count, min=self._min, max=self._max,
                  buckets=BUCKET_SCHEMA)

  def quantile(self, q: float) -> Optional[float]:
    return quantile_from_state(self.state(), q)

  def percentiles(self) -> Dict[str, Optional[float]]:
    """The serving-tier trio: {'p50': ..., 'p95': ..., 'p99': ...}."""
    st = self.state()
    return {f'p{int(100 * q)}': quantile_from_state(st, q)
            for q in (0.5, 0.95, 0.99)}


def quantile_from_state(state: dict, q: float) -> Optional[float]:
  """Quantile estimate from a histogram snapshot leaf (works on merged
  snapshots too — the scrape path's cluster-wide percentiles)."""
  if not 0.0 <= q <= 1.0:
    raise ValueError(f'quantile must be in [0, 1], got {q}')
  total = state['count']
  if not total:
    return None
  lo_clamp = state['min'] if state['min'] is not None else 0.0
  hi_clamp = state['max'] if state['max'] is not None else float('inf')
  target = q * total
  cum = 0
  for i, c in enumerate(state['counts']):
    if not c:
      continue
    if cum + c >= target:
      # geometric interpolation within bucket (lo, hi]
      frac = (target - cum) / c
      hi = HIST_BOUNDS[i] if i < len(HIST_BOUNDS) else hi_clamp
      lo = HIST_BOUNDS[i - 1] if i > 0 else min(lo_clamp, hi)
      if lo <= 0 or hi <= 0 or not math.isfinite(hi):
        est = hi if math.isfinite(hi) else lo
      else:
        est = lo * (hi / lo) ** frac
      return min(max(est, lo_clamp), hi_clamp)
    cum += c
  return hi_clamp if math.isfinite(hi_clamp) else None


_KINDS = {'counter': Counter, 'gauge': Gauge, 'histogram': Histogram}


class MetricRegistry:
  """Get-or-create store of named typed metrics.

  One name maps to one metric of one kind for the registry's lifetime;
  re-requesting a name under a different kind raises (a counter
  silently shadowing a histogram would corrupt every scrape merge
  downstream).
  """

  def __init__(self):
    self._lock = threading.Lock()
    self._metrics: Dict[str, object] = {}

  def _get(self, name: str, kind: str):
    with self._lock:
      m = self._metrics.get(name)
      if m is None:
        m = self._metrics[name] = _KINDS[kind](name, self._lock)
      elif m.kind != kind:
        raise ValueError(
            f'metric {name!r} already registered as a {m.kind}, '
            f'requested as a {kind} — one name, one type')
      return m

  def counter(self, name: str) -> Counter:
    return self._get(name, 'counter')

  def gauge(self, name: str) -> Gauge:
    return self._get(name, 'gauge')

  def histogram(self, name: str) -> Histogram:
    return self._get(name, 'histogram')

  # -- convenience write forms (the package's idiomatic call sites;
  # graftlint's metric-registry rule checks their name arguments) ------

  def inc(self, name: str, n: int = 1):
    self.counter(name).inc(n)

  def set_gauge(self, name: str, value: float):
    self.gauge(name).set(value)

  def observe(self, name: str, value: float):
    self.histogram(name).observe(value)

  # -- reads -----------------------------------------------------------

  def counters(self, prefix: str = '') -> Dict[str, int]:
    """{name: value} for counters matching ``prefix`` — the
    trace.counters() compatibility view."""
    with self._lock:
      return {n: m._value for n, m in self._metrics.items()
              if m.kind == 'counter' and n.startswith(prefix)}

  def counter_value(self, name: str) -> int:
    with self._lock:
      m = self._metrics.get(name)
      return m._value if m is not None and m.kind == 'counter' else 0

  def names(self) -> List[str]:
    with self._lock:
      return sorted(self._metrics)

  def snapshot(self) -> dict:
    """Plain-dict snapshot of everything — the cross-process exchange
    format::

        {'counters':   {name: int},
         'gauges':     {name: float},
         'histograms': {name: {'counts': [...], 'sum': float,
                               'count': int, 'min': ..., 'max': ...,
                               'buckets': BUCKET_SCHEMA}}}
    """
    with self._lock:
      out = {'counters': {}, 'gauges': {}, 'histograms': {}}
      for n, m in self._metrics.items():
        if m.kind == 'counter':
          out['counters'][n] = m._value
        elif m.kind == 'gauge':
          out['gauges'][n] = m._value
        else:
          out['histograms'][n] = dict(
              counts=list(m._counts), sum=m._sum, count=m._count,
              min=m._min, max=m._max, buckets=BUCKET_SCHEMA)
      return out

  def reset(self, prefix: str = ''):
    """Drop metrics whose name matches ``prefix`` (all by default)."""
    with self._lock:
      for n in [n for n in self._metrics if n.startswith(prefix)]:
        del self._metrics[n]

  def reset_counters(self, prefix: str = ''):
    """Drop COUNTERS matching ``prefix``, leaving gauges/histograms —
    the exact semantics of the old trace.reset_counters dict."""
    with self._lock:
      for n in [n for n, m in self._metrics.items()
                if m.kind == 'counter' and n.startswith(prefix)]:
        del self._metrics[n]


def merge_snapshots(snapshots: Iterable[dict]) -> dict:
  """Fold role snapshots into one cluster-wide view: counters and
  histogram buckets ADD; gauges keep the last writer (instantaneous
  values have no meaningful sum). Histogram leaves must share
  BUCKET_SCHEMA — a mismatched producer build raises rather than
  silently mis-binning."""
  out: dict = {'counters': {}, 'gauges': {}, 'histograms': {}}
  for snap in snapshots:
    if not snap:
      continue
    for n, v in snap.get('counters', {}).items():
      out['counters'][n] = out['counters'].get(n, 0) + v
    for n, v in snap.get('gauges', {}).items():
      out['gauges'][n] = v
    for n, h in snap.get('histograms', {}).items():
      if h.get('buckets', BUCKET_SCHEMA) != BUCKET_SCHEMA:
        raise ValueError(
            f'histogram {n!r} bucket schema {h.get("buckets")!r} != '
            f'{BUCKET_SCHEMA!r} — snapshots from incompatible builds '
            'cannot be merged')
      acc = out['histograms'].get(n)
      if acc is None:
        out['histograms'][n] = dict(h, counts=list(h['counts']))
        continue
      acc['counts'] = [a + b for a, b in zip(acc['counts'],
                                             h['counts'])]
      acc['sum'] += h['sum']
      acc['count'] += h['count']
      for k, pick in (('min', min), ('max', max)):
        if h[k] is not None:
          acc[k] = h[k] if acc[k] is None else pick(acc[k], h[k])
  return out


# The process-local default registry — what trace.counter_inc shims
# into and what DistServer.get_metrics / worker snapshots export.
_default = MetricRegistry()


def default_registry() -> MetricRegistry:
  return _default
