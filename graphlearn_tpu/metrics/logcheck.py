"""Schema validation for the observability JSONL trails.

Two record kinds ride JSONL files: epoch flight records
(``GLT_RUN_LOG``, metrics/flight.py) and spans (``GLT_SPAN_LOG``,
metrics/spans.py). Postmortem tooling, the jq cookbook and the chaos
tests all key on their field names — a drifted field silently breaks
every consumer, so the schema is CHECKED, not just documented:

* :func:`validate_flight_record` / :func:`validate_span` return a list
  of problems for one parsed record (empty = valid);
* :func:`check_file` validates a whole JSONL file (mixed kinds are
  fine — the two recorders may share a file);
* the CLI (``python -m graphlearn_tpu.metrics.logcheck [paths...]``)
  exits non-zero on any problem. With NO paths it self-checks: it
  validates a freshly-emitted flight record and span against the
  validators, so scripts/lint.sh catches a recorder/validator drift in
  the same change that introduces it.

Pure stdlib, like the rest of the metrics package.
"""
import json
import os
import sys
from typing import List, Optional

# field name -> allowed types (a tuple feeds isinstance); Optional
# fields may also be null
_FLIGHT_REQUIRED = {
    'schema': (int,),
    'kind': (str,),
    'run_id': (str,),
    'emitter': (str,),
    'epoch': (int,),
    'steps': (int,),
    'completed': (bool,),
    'wall_s': (int, float),
    'feature': (dict,),
    'resilience': (dict,),
    'fault': (dict,),
    'programs': (dict,),
    'counters': (dict,),
    'config': (dict,),
    'config_fingerprint': (str,),
    'trace': (dict,),
    'time_unix': (int, float),
}
_FLIGHT_NULLABLE = {
    'dispatch': (dict,),
    'dispatch_total': (int,),
}
# fields later schema-1 writers added without a version bump: current
# records always carry them, but logs captured by earlier builds must
# still validate (present -> type-checked, absent -> fine)
_FLIGHT_OPTIONAL = {
    'storage': (dict,),
}

_SPAN_REQUIRED = {
    'schema': (int,),
    'kind': (str,),
    'name': (str,),
    'span': (str,),
    'trace': (str,),
    'run': (str,),
    'pid': (int,),
    't0_unix': (int, float),
    'dur_ms': (int, float),
}
_SPAN_NULLABLE = {
    'parent': (str,),
}
_SPAN_OPTIONAL = {
    'attrs': (dict,),
    'profile_key': (str,),
}


def _check_fields(rec: dict, required: dict, nullable: dict,
                  optional: dict, label: str) -> List[str]:
  problems = []
  for field, types in required.items():
    if field not in rec:
      problems.append(f'{label}: missing field {field!r}')
    elif not isinstance(rec[field], types):
      problems.append(
          f'{label}: field {field!r} has type '
          f'{type(rec[field]).__name__}, expected '
          f'{"/".join(t.__name__ for t in types)}')
  for field, types in nullable.items():
    if field in rec and rec[field] is not None and \
        not isinstance(rec[field], types):
      problems.append(
          f'{label}: field {field!r} must be null or '
          f'{"/".join(t.__name__ for t in types)}')
  for field, types in optional.items():
    if field in rec and not isinstance(rec[field], types):
      problems.append(
          f'{label}: field {field!r} must be '
          f'{"/".join(t.__name__ for t in types)}')
  return problems


def validate_flight_record(rec: dict, label: str = 'flight') -> List[str]:
  """Problems with one epoch flight record (empty list = valid)."""
  if rec.get('kind') != 'epoch':
    return [f'{label}: kind {rec.get("kind")!r} != "epoch"']
  return _check_fields(rec, _FLIGHT_REQUIRED, _FLIGHT_NULLABLE,
                       _FLIGHT_OPTIONAL, label)


def validate_span(rec: dict, label: str = 'span') -> List[str]:
  """Problems with one span record (empty list = valid)."""
  if rec.get('kind') != 'span':
    return [f'{label}: kind {rec.get("kind")!r} != "span"']
  problems = _check_fields(rec, _SPAN_REQUIRED, _SPAN_NULLABLE,
                           _SPAN_OPTIONAL, label)
  if isinstance(rec.get('dur_ms'), (int, float)) and rec['dur_ms'] < 0:
    problems.append(f'{label}: negative dur_ms {rec["dur_ms"]}')
  return problems


def validate_record(rec: dict, label: str = 'record') -> List[str]:
  kind = rec.get('kind')
  if kind == 'epoch':
    return validate_flight_record(rec, label)
  if kind == 'span':
    return validate_span(rec, label)
  return [f'{label}: unknown record kind {kind!r} '
          '(expected "epoch" or "span")']


def check_file(path: str) -> List[str]:
  """Validate every parseable line of a JSONL trail (unparseable lines
  are reported — the recorders never emit them; a torn final line from
  a crashed run is the one tolerated shape: reported as a note only
  when it is the last line)."""
  problems: List[str] = []
  with open(path, encoding='utf-8') as fh:
    lines = fh.read().splitlines()
  for i, line in enumerate(lines, 1):
    if not line.strip():
      continue
    label = f'{path}:{i}'
    try:
      rec = json.loads(line)
    except ValueError:
      if i == len(lines):
        continue   # torn final line: a mid-write crash, tolerated
      problems.append(f'{label}: unparseable JSON line')
      continue
    if not isinstance(rec, dict):
      problems.append(f'{label}: line is not a JSON object')
      continue
    problems.extend(validate_record(rec, label))
  return problems


def _self_check() -> List[str]:
  """Emit one flight record and one span through the REAL recorders
  into a temp file and validate them — recorder/validator drift fails
  lint in the change that introduces it."""
  import tempfile
  from . import flight, spans
  problems: List[str] = []
  with tempfile.TemporaryDirectory() as d:
    run_log = os.path.join(d, 'run.jsonl')
    span_log = os.path.join(d, 'spans.jsonl')
    old_run = os.environ.get(flight.ENV_VAR)
    old_span = os.environ.get(spans.ENV_LOG)
    os.environ[flight.ENV_VAR] = run_log
    os.environ[spans.ENV_LOG] = span_log
    try:
      tok = flight.epoch_begin()
      flight.epoch_end(tok, emitter='logcheck', epoch=0, steps=1,
                       config={'self_check': True})
      with spans.span('epoch.run', emitter='logcheck'):
        pass
    finally:
      for var, old in ((flight.ENV_VAR, old_run),
                       (spans.ENV_LOG, old_span)):
        if old is None:
          os.environ.pop(var, None)
        else:
          os.environ[var] = old
    for path in (run_log, span_log):
      if not os.path.exists(path):
        problems.append(f'self-check: recorder wrote nothing to {path}')
        continue
      problems.extend(check_file(path))
  return problems


def main(argv: Optional[List[str]] = None) -> int:
  argv = sys.argv[1:] if argv is None else argv
  paths = [p for p in argv if p not in ('-q', '--quiet')]
  quiet = len(paths) != len(argv)
  if paths:
    problems = []
    for p in paths:
      if not os.path.exists(p):
        problems.append(f'{p}: no such file')
        continue
      problems.extend(check_file(p))
  else:
    problems = _self_check()
  for msg in problems:
    print(msg, file=sys.stderr)
  if not quiet:
    what = ', '.join(paths) if paths else 'recorder self-check'
    print(f'logcheck: {len(problems)} problem(s) ({what})')
  return 1 if problems else 0


if __name__ == '__main__':
  sys.exit(main())
