"""Cluster-wide metric scraping with per-role labels.

The production topology is multi-process (docs/architecture.md):
sampling SERVERS own remote producers and answer RPC, mp PRODUCER
workers sample in subprocesses, and the trainer CLIENT drives epochs.
Each process keeps its own process-local registry;
:func:`scrape_all` assembles the cluster view at the client::

    {'client/0':             <snapshot>,      # this process
     'server/0':             <snapshot>,      # via get_metrics RPC
     'server/0/producer/3':  <snapshot>,      # that server's mp workers
     'producer/1':           <snapshot>}      # locally registered source

The server leg rides ``DistServer.get_metrics`` — a READ-ONLY RPC,
idempotent by construction, so it is scraped with ``idempotent=True``
and survives retry under the fault-injection registry. A server that
fails its scrape contributes an ``{'error': ...}`` entry instead of
poisoning the whole view (monitoring must degrade, never crash the
trainer).

Local sources (client-side mp producers, future serving workers)
register a zero-argument callable returning a snapshot via
:func:`register_source`; sources that raise are skipped with a
``metrics.scrape_error`` count.
"""
import threading
from typing import Callable, Dict, Optional

from .registry import default_registry, merge_snapshots

_sources: Dict[str, Callable[[], dict]] = {}
_sources_lock = threading.Lock()


def register_source(role: str, fn: Callable[[], dict]):
  """Attach a local snapshot source under ``role`` (e.g.
  'producer/0'). Re-registering a role replaces its callable."""
  with _sources_lock:
    _sources[role] = fn


def unregister_source(role: str):
  with _sources_lock:
    _sources.pop(role, None)


def _local_role() -> str:
  try:
    from ..distributed.dist_context import get_context
    ctx = get_context()
  except ImportError:       # pragma: no cover - distributed always ships
    ctx = None
  if ctx is None:
    return 'local'
  if ctx.is_server():
    return f'server/{ctx.rank}'
  if ctx.is_client():
    return f'client/{ctx.rank}'
  return f'worker/{ctx.rank}'


def scrape_all(include_local: bool = True,
               timeout: Optional[float] = 10.0) -> Dict[str, dict]:
  """{role: snapshot} across this process, registered local sources,
  and every connected sampling server (plus their producers' mp
  workers). Server snapshots come over the retry-safe ``get_metrics``
  RPC; unreachable servers yield ``{'error': ...}`` entries.

  ``timeout`` bounds each RPC attempt (seconds). The default is
  deliberately short of the 180 s socket default: a partitioned
  (blackholed, no RST) server must degrade to its error entry in
  seconds, not stall every healthy server's snapshot behind a dead
  connect. Pass None to fall back to the retry policy's budget."""
  from . import spans as _spans
  out: Dict[str, dict] = {}
  if include_local:
    snap = default_registry().snapshot()
    # run_id + span ring ride the snapshot as extra keys (ignored by
    # merge_snapshots): a scrape, a flight record and a span tree from
    # the same run join on run_id, and spans.from_scrape() recovers a
    # request's spans from the scrape result by id alone
    snap['run_id'] = _spans.run_id()
    snap['spans'] = _spans.export(limit=_spans.SCRAPE_EXPORT_LIMIT)
    out[_local_role()] = snap
  with _sources_lock:
    sources = dict(_sources)
  for role, fn in sources.items():
    try:
      snap = fn()
    except Exception as e:  # noqa: BLE001 - monitoring must degrade
      default_registry().inc('metrics.scrape_error')
      out[role] = {'error': f'{type(e).__name__}: {e}'}
      continue
    if snap:
      out[role] = snap
  from ..distributed import dist_client
  client = dist_client.get_client()
  if client is None:
    return out
  # fan the server legs out concurrently (the RpcClient's own pool):
  # per-leg timeouts must not ADD UP — three blackholed servers in a
  # 16-server scrape would otherwise stall every healthy leg behind
  # them for attempts x timeout each
  futures = {rank: client.request_async(rank, 'get_metrics',
                                        timeout=timeout,
                                        idempotent=True)
             for rank in client.targets}
  for rank, fut in futures.items():
    try:
      remote = fut.result()
    except Exception as e:  # noqa: BLE001 - a dead server is a data point
      default_registry().inc('metrics.scrape_error')
      out[f'server/{rank}'] = {'error': f'{type(e).__name__}: {e}'}
      continue
    out[f'server/{rank}'] = remote.get('server', {})
    for pid, snap in remote.get('producers', {}).items():
      out[f'server/{rank}/producer/{pid}'] = snap
  return out


def merge_scrape(scrapes: Dict[str, dict]) -> dict:
  """One cluster-wide snapshot from a :func:`scrape_all` result
  (error entries are skipped). Counters and histogram buckets add
  across roles; see registry.merge_snapshots."""
  return merge_snapshots(
      s for s in scrapes.values() if s and 'error' not in s)
