"""Correlated spans: host-clock begin/end records joinable across the
cluster by one id.

The metrics layer answers "how many / how fast"; spans answer "WHICH
request / WHICH epoch, across WHICH processes". A span is a tiny
host-side record — name, span id, parent id, trace id, begin time,
duration, attrs — kept in a bounded in-process ring and (opt-in,
``GLT_SPAN_LOG``) appended as JSONL next to the flight recorder. No
device clocks, no fetches, no dispatches: one perf_counter read at each
end and a dict append (docs/observability.md documents the schema).

Correlation model:

* every process owns a ``run_id`` (``GLT_RUN_ID`` or minted once);
* a span's ``trace`` id defaults to the current thread's propagated
  trace, falling back to the process run_id — so an epoch's spans all
  carry the driving process's run_id, and a request's spans carry the
  request id minted at its edge;
* the context crosses processes explicitly: the RPC client puts
  :func:`wire_context` in request metadata and the server adopts it for
  the handler (``rpc.py``); the mp sampling producer ships it with each
  epoch command and workers adopt it (``dist_sampling_producer.py``);
  ``ServingEngine.submit`` captures it into the request so dispatcher-
  thread spans still join the submitting caller's trace.

Recovery: the local ring exports through ``spans.export()``;
``DistServer.get_metrics`` attaches the server's ring (and the
producers' worker rings) to its snapshot, so ``metrics.scrape_all()``
carries every role's spans — :func:`from_scrape` + :func:`build_tree`
reassemble one request's tree from the scrape plus the local ring, by
id alone. Span NAMES are a closed namespace
(``registry_names.REGISTERED_SPANS``, graftlint rule ``span-registry``)
exactly like metric names.

Zero-dependency (pure stdlib), thread-safe, process-local.
"""
import collections
import contextlib
import logging
import os
import sys
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

ENV_LOG = 'GLT_SPAN_LOG'
ENV_RUN = 'GLT_RUN_ID'
ENV_BUFFER = 'GLT_SPAN_BUFFER'
SCHEMA = 1

#: newest spans a scrape leg ships (get_metrics, scrape_all's local
#: snapshot, worker epoch-end publishes): a busy ring re-serialized on
#: every monitoring poll must stay bounded; full-fidelity recovery is
#: the GLT_SPAN_LOG JSONL's job, the scrape carries the recent window
SCRAPE_EXPORT_LIMIT = 1024

logger = logging.getLogger('graphlearn_tpu.spans')

_lock = threading.Lock()
_run_id: Optional[str] = None
_proc_tag = uuid.uuid4().hex[:8]
_counter = 0
_tls = threading.local()


def run_id() -> str:
  """This process's run identity: ``GLT_RUN_ID`` when set (one value
  across a whole launch joins every process's records), else minted
  once per process. Stamped into flight records and scrape snapshots so
  a flight line and a scrape from the same run join on it."""
  global _run_id
  if _run_id is None:
    with _lock:
      if _run_id is None:
        _run_id = os.environ.get(ENV_RUN) or uuid.uuid4().hex[:16]
  return _run_id


def span_log_path() -> Optional[str]:
  return os.environ.get(ENV_LOG) or None


def _next_span_id() -> str:
  global _counter
  with _lock:
    _counter += 1
    return f'{_proc_tag}-{_counter:x}'


def _stack() -> list:
  st = getattr(_tls, 'stack', None)
  if st is None:
    st = _tls.stack = []
  return st


def current() -> Tuple[Optional[str], Optional[str]]:
  """(trace_id, span_id) of the innermost attached span on this thread,
  or the adopted remote context, or (None, None)."""
  st = _stack()
  return st[-1] if st else (None, None)


def current_trace() -> str:
  """The trace id new spans on this thread will join: the propagated
  context when one is attached, else the process run_id."""
  trace, _ = current()
  return trace or run_id()


def wire_context() -> Dict[str, Optional[str]]:
  """The propagation payload for RPC metadata / mp command payloads:
  ``{'trace': ..., 'span': ...}`` (span may be None at a trace root)."""
  trace, span_id = current()
  return {'trace': trace or run_id(), 'span': span_id}


@contextlib.contextmanager
def adopt(ctx: Optional[dict]):
  """Adopt a remote :func:`wire_context` for this thread (RPC handler,
  mp worker epoch): spans opened inside join the remote trace and
  parent under the remote span. A None/empty ctx is a no-op."""
  if not ctx or not ctx.get('trace'):
    yield
    return
  st = _stack()
  st.append((ctx['trace'], ctx.get('span')))
  try:
    yield
  finally:
    if st and st[-1] == (ctx['trace'], ctx.get('span')):
      st.pop()


@contextlib.contextmanager
def new_trace(trace_id: Optional[str] = None):
  """Mint (or adopt) a fresh trace id — the REQUEST id pattern: open
  one around a client call and every span it causes, across every
  process it touches, joins that id. Yields the id."""
  trace_id = trace_id or uuid.uuid4().hex[:16]
  st = _stack()
  st.append((trace_id, None))
  try:
    yield trace_id
  finally:
    if st and st[-1] == (trace_id, None):
      st.pop()


# ----------------------------------------------------------------- recorder


class SpanRecorder:
  """Bounded ring of finished span records (plain dicts)."""

  def __init__(self, maxlen: int = 4096):
    self._lock = threading.Lock()
    self._ring = collections.deque(maxlen=maxlen)

  def record(self, rec: dict):
    with self._lock:
      self._ring.append(rec)

  def export(self, trace: Optional[str] = None,
             limit: Optional[int] = None) -> List[dict]:
    with self._lock:
      out = [r for r in self._ring
             if trace is None or r.get('trace') == trace]
    return out[-limit:] if limit else out

  def reset(self):
    with self._lock:
      self._ring.clear()


def _ring_maxlen() -> int:
  # a malformed tuning knob must not make the package unimportable
  # (observability never kills work): unparseable values fall back
  try:
    return max(64, int(os.environ.get(ENV_BUFFER, '') or 4096))
  except ValueError:
    logger.warning('%s=%r is not an integer — using the default 4096',
                   ENV_BUFFER, os.environ.get(ENV_BUFFER))
    return 4096


_recorder = SpanRecorder(maxlen=_ring_maxlen())


def recorder() -> SpanRecorder:
  return _recorder


def export(trace: Optional[str] = None,
           limit: Optional[int] = None) -> List[dict]:
  """Finished spans from this process's ring (newest last)."""
  return _recorder.export(trace, limit)


def reset():
  _recorder.reset()


def _profile_key() -> Optional[str]:
  """The active jax-profiler trace key, when a maybe_start_trace
  session is live — stamps device traces onto host spans so a Perfetto
  trace and a span tree correlate (sys.modules probe keeps this module
  zero-dependency and cycle-free)."""
  tr = sys.modules.get('graphlearn_tpu.utils.trace')
  if tr is not None and getattr(tr, '_active', False):
    return (getattr(tr, '_active_dir', None)
            or os.environ.get('GLT_PROFILE_DIR'))
  return None


# spans emit per-RPC / per-request: the shared appender keeps a
# flushed handle open between records instead of paying an open/close
# per span on the very hot paths the spans are timing (flight.py owns
# the implementation; flight itself writes once per epoch, unbuffered)
from .flight import JsonlAppender, read_jsonl as _read_jsonl  # noqa: E402

_writer = JsonlAppender(ENV_LOG, keep_open=True)


def _write(rec: dict):
  path = span_log_path()
  if path:
    _writer.append(path, rec)


def _jsonable_attrs(attrs: dict) -> dict:
  from .flight import _jsonable
  return {str(k): _jsonable(v) for k, v in attrs.items()}


# ------------------------------------------------------------ span lifecycle


class _SpanToken:
  __slots__ = ('name', 'span_id', 'parent', 'trace', 't0', 't0_unix',
               'attrs', 'attached', 'done')

  def __init__(self, name, span_id, parent, trace, attrs, attached):
    self.name = name
    self.span_id = span_id
    self.parent = parent
    self.trace = trace
    self.t0 = time.perf_counter()
    self.t0_unix = time.time()
    self.attrs = attrs
    self.attached = attached
    self.done = False


def begin(name: str, parent: Optional[str] = None,
          trace: Optional[str] = None, attach: bool = True,
          **attrs) -> _SpanToken:
  """Open a span. With ``attach=True`` (default) it becomes this
  thread's current span until :func:`end` — children opened on the
  thread parent under it. ``attach=False`` is for spans that live
  across threads (a serving request handed to the dispatcher): pass
  ``parent``/``trace`` explicitly or let them default to the caller's
  current context."""
  cur_trace, cur_span = current()
  tok = _SpanToken(name, _next_span_id(),
                   parent if parent is not None else cur_span,
                   trace or cur_trace or run_id(), dict(attrs), attach)
  if attach:
    _stack().append((tok.trace, tok.span_id))
  return tok


def end(tok: Optional[_SpanToken], **attrs) -> Optional[dict]:
  """Close a span and record it (idempotent; None token is a no-op —
  the epoch_begin/epoch_end falsy-token convention)."""
  if tok is None or tok.done:
    return None
  tok.done = True
  if tok.attached:
    st = _stack()
    if (tok.trace, tok.span_id) in st:
      st.remove((tok.trace, tok.span_id))
  if attrs:
    tok.attrs.update(attrs)
  rec = {
      'schema': SCHEMA, 'kind': 'span', 'name': tok.name,
      'span': tok.span_id, 'parent': tok.parent, 'trace': tok.trace,
      'run': run_id(), 'pid': os.getpid(),
      't0_unix': round(tok.t0_unix, 6),
      'dur_ms': round((time.perf_counter() - tok.t0) * 1e3, 6),
  }
  if tok.attrs:
    rec['attrs'] = _jsonable_attrs(tok.attrs)
  key = _profile_key()
  if key:
    rec['profile_key'] = key
  _recorder.record(rec)
  _write(rec)
  return rec


@contextlib.contextmanager
def span(name: str, **attrs):
  """``with spans.span('epoch.chunk', k=4):`` — begin/end with error
  annotation on an exception escaping the block."""
  tok = begin(name, **attrs)
  try:
    yield tok
  except BaseException as e:
    end(tok, error=f'{type(e).__name__}: {e}')
    raise
  finally:
    end(tok)


def emit(name: str, *, trace: Optional[str] = None,
         parent: Optional[str] = None, t0_unix: Optional[float] = None,
         dur_ms: float = 0.0, **attrs) -> dict:
  """Record a RETROACTIVE span — a phase whose bounds were measured as
  plain timestamps (queue wait measured at batch pickup). Same record
  shape as begin/end."""
  rec = {
      'schema': SCHEMA, 'kind': 'span', 'name': name,
      'span': _next_span_id(), 'parent': parent,
      'trace': trace or current_trace(), 'run': run_id(),
      'pid': os.getpid(),
      't0_unix': round(t0_unix if t0_unix is not None else time.time(),
                       6),
      'dur_ms': round(dur_ms, 6),
  }
  if attrs:
    rec['attrs'] = _jsonable_attrs(attrs)
  key = _profile_key()
  if key:
    rec['profile_key'] = key
  _recorder.record(rec)
  _write(rec)
  return rec


# ------------------------------------------------------------ tree assembly


def read_log(path: Optional[str] = None) -> List[dict]:
  """Parse a GLT_SPAN_LOG back into span records (garbage lines
  skipped — the shared flight.read_jsonl tolerance)."""
  return _read_jsonl(path or span_log_path(), kind='span')


def from_scrape(scrapes: Dict[str, dict],
                trace: Optional[str] = None) -> List[dict]:
  """Every span a ``metrics.scrape_all()`` result carries (each role
  snapshot's ``spans`` list), optionally filtered by trace id."""
  out: List[dict] = []
  for snap in scrapes.values():
    if not isinstance(snap, dict) or 'error' in snap:
      continue
    for rec in snap.get('spans', ()) or ():
      if trace is None or rec.get('trace') == trace:
        out.append(rec)
  return out


def dedupe(spans_: Iterable[dict]) -> List[dict]:
  """One record per span id (a span can arrive via both the local ring
  and a scrape leg, or the ring and the JSONL)."""
  seen, out = set(), []
  for rec in spans_:
    sid = rec.get('span')
    if sid in seen:
      continue
    seen.add(sid)
    out.append(rec)
  return out


def build_tree(spans_: Iterable[dict]) -> dict:
  """{'roots': [span_id...], 'children': {span_id: [span_id...]},
  'spans': {span_id: record}, 'orphans': [span_id...]} — orphans are
  spans whose parent id is set but absent from the collection (the
  chaos suite asserts there are none after a failover/respawn)."""
  spans_ = dedupe(spans_)
  index = {rec['span']: rec for rec in spans_}
  children: Dict[str, list] = {}
  roots, orphans = [], []
  for rec in sorted(spans_, key=lambda r: r.get('t0_unix', 0.0)):
    parent = rec.get('parent')
    if parent is None:
      roots.append(rec['span'])
    elif parent in index:
      children.setdefault(parent, []).append(rec['span'])
    else:
      orphans.append(rec['span'])
  return dict(roots=roots, children=children, spans=index,
              orphans=orphans)
