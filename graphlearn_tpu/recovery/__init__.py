"""Chunk-granular recovery for the scanned epoch programs.

Three pieces (docs/recovery.md):

* :mod:`snapshot` — the atomic, torn-proof snapshot file format
  (tmp + fsync + rename; header-checksummed payload).
* :class:`ChunkCheckpointer` — async exact checkpointing riding the
  trainers' ``stage_hook``/``ack_hook`` chunk-boundary seams, plus
  :meth:`~ChunkCheckpointer.resume_epoch`, which restarts a SIGKILLed
  epoch mid-flight with the remaining chunks bit-identical to the
  uninterrupted run.
* :class:`FailoverRunner` — chunk-granular failover for
  ``DistScanTrainer``: a dead mesh shard (detected via the PR 2
  Heartbeat) rolls the epoch back at most one chunk, the data
  re-slices over the survivors, and the epoch completes with exact
  seed coverage.
"""
from .checkpoint import ChunkCheckpointer
from .failover import FailoverRunner, ShardDeadError, remaining_seeds
from .snapshot import (Snapshot, TornSnapshotError, list_snapshots,
                       load_snapshot, write_snapshot)

__all__ = [
    'ChunkCheckpointer', 'FailoverRunner', 'ShardDeadError',
    'remaining_seeds', 'Snapshot', 'TornSnapshotError', 'list_snapshots',
    'load_snapshot', 'write_snapshot',
]
