"""Atomic, torn-proof chunk-boundary snapshots (the recovery substrate).

One snapshot file captures everything the counter-addressed PRNG
contract does NOT replay for free: the train-state pytree leaves, the
per-step losses/accs already produced, the epoch/chunk position, the
sampler's ``state_dict`` (base key + ``call_count``), the overflow
flag, and per-trainer extras (DistScanTrainer feature-cache stats rows,
TieredScanTrainer staging watermarks). Everything else — the seed
permutation, every per-step sampling draw, the exact chunk boundaries —
is a pure function of that state (the PR 1/4 replay contracts), which
is what keeps the snapshot TINY and the resume EXACT
(docs/recovery.md).

File format (single self-validating file)::

    MAGIC 'GLTCKPT1' | u32be header_len | header JSON | npz payload

The header carries the payload's byte length and sha256, so a torn
write — a crash mid-``write()``, a truncated copy, a partial disk —
is always DETECTED (:class:`TornSnapshotError`), never silently
restored. Writes are atomic by construction: the bytes are assembled
in memory, written to a same-directory temp file, fsync'd, and
``os.replace``'d onto the final name (then the directory entry is
fsync'd), so a crash at ANY point leaves either the previous snapshot
or the new one — never a half file under the final name. The
``recovery.save`` / ``recovery.restore`` fault sites
(docs/failure_model.md) arm the chaos suite's writer-death and
restore-under-fault scenarios.
"""
import hashlib
import io
import json
import os
import re
import struct
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.checkpoint import _dejsonify, _jsonify
from ..utils.faults import fault_point

MAGIC = b'GLTCKPT1'
_NAME_RE = re.compile(r'^ckpt-(\d+)-(\d+)\.glt$')


class TornSnapshotError(RuntimeError):
  """A snapshot file failed its integrity check (truncated header,
  payload length or sha256 mismatch) — the restore path skips it and
  falls back to the previous snapshot."""


@dataclass
class Snapshot:
  """A loaded (validated) snapshot: JSON meta + named numpy arrays."""
  meta: dict
  arrays: Dict[str, np.ndarray]
  path: Optional[str] = None

  @property
  def epoch(self) -> int:
    return int(self.meta['epoch'])

  @property
  def next_start(self) -> int:
    """First step NOT yet covered by this snapshot (the resume point,
    a chunk boundary by construction)."""
    return int(self.meta['next_start'])


def snapshot_path(directory: str, epoch: int, next_start: int) -> str:
  return os.path.join(directory, f'ckpt-{epoch:06d}-{next_start:06d}.glt')


def encode(meta: dict, arrays: Dict[str, np.ndarray]) -> bytes:
  """Serialize to the self-validating byte layout (pure, for tests)."""
  buf = io.BytesIO()
  np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
  payload = buf.getvalue()
  header = json.dumps({
      'meta': _jsonify(meta),
      'payload_bytes': len(payload),
      'payload_sha256': hashlib.sha256(payload).hexdigest(),
  }, sort_keys=True).encode()
  return MAGIC + struct.pack('>I', len(header)) + header + payload


def decode(blob: bytes, label: str = 'snapshot') -> Snapshot:
  """Parse + integrity-check one encoded snapshot. Raises
  :class:`TornSnapshotError` on ANY mismatch — a torn file must never
  restore as a shorter-but-plausible state."""
  if len(blob) < len(MAGIC) + 4 or blob[:len(MAGIC)] != MAGIC:
    raise TornSnapshotError(f'{label}: bad magic or truncated prologue')
  (hlen,) = struct.unpack('>I', blob[len(MAGIC):len(MAGIC) + 4])
  hstart = len(MAGIC) + 4
  if len(blob) < hstart + hlen:
    raise TornSnapshotError(f'{label}: truncated header '
                            f'({len(blob) - hstart} of {hlen} bytes)')
  try:
    header = json.loads(blob[hstart:hstart + hlen])
  except ValueError as e:
    raise TornSnapshotError(f'{label}: unparseable header: {e}') from e
  payload = blob[hstart + hlen:]
  want = int(header.get('payload_bytes', -1))
  if len(payload) != want:
    raise TornSnapshotError(
        f'{label}: payload is {len(payload)} bytes, header says {want}')
  sha = hashlib.sha256(payload).hexdigest()
  if sha != header.get('payload_sha256'):
    raise TornSnapshotError(f'{label}: payload sha256 mismatch')
  with np.load(io.BytesIO(payload), allow_pickle=False) as z:
    arrays = {k: z[k] for k in z.files}
  return Snapshot(meta=_dejsonify(header['meta']), arrays=arrays)


def write_snapshot(directory: str, meta: dict,
                   arrays: Dict[str, np.ndarray]) -> Tuple[str, int]:
  """Atomically write one snapshot; returns ``(path, bytes)``. The
  ``recovery.save`` fault site sits here — BOTH the async writer thread
  and the degraded synchronous path funnel through this one function."""
  fault_point('recovery.save')
  os.makedirs(directory, exist_ok=True)
  blob = encode(meta, arrays)
  path = snapshot_path(directory, int(meta['epoch']),
                       int(meta['next_start']))
  fd, tmp = tempfile.mkstemp(prefix='.ckpt-', suffix='.tmp',
                             dir=directory)
  try:
    with os.fdopen(fd, 'wb') as fh:
      fh.write(blob)
      fh.flush()
      os.fsync(fh.fileno())
    os.replace(tmp, path)
  except BaseException:
    try:
      os.unlink(tmp)
    except OSError:
      pass
    raise
  # fsync the directory entry so the rename itself is durable
  try:
    dfd = os.open(directory, os.O_RDONLY)
    try:
      os.fsync(dfd)
    finally:
      os.close(dfd)
  except OSError:
    pass   # platform without directory fsync: the rename is still atomic
  return path, len(blob)


def load_snapshot(path: str) -> Snapshot:
  """Read + validate one snapshot file. The ``recovery.restore`` fault
  site arms the restore-under-fault chaos scenario."""
  fault_point('recovery.restore')
  with open(path, 'rb') as fh:
    blob = fh.read()
  snap = decode(blob, label=os.path.basename(path))
  snap.path = path
  return snap


def list_snapshots(directory: str) -> List[Tuple[int, int, str]]:
  """``(epoch, next_start, path)`` for every snapshot file, sorted
  oldest -> newest by (epoch, next_start)."""
  if not os.path.isdir(directory):
    return []
  out = []
  for name in os.listdir(directory):
    m = _NAME_RE.match(name)
    if m:
      out.append((int(m.group(1)), int(m.group(2)),
                  os.path.join(directory, name)))
  out.sort()
  return out
