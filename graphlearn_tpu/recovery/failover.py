"""Chunk-granular failover for the scanned distributed epoch.

The per-step remote loaders fail over at BATCH granularity (PR 2: a
dead server's unacked seeds redistribute to survivors) — but
``DistScanTrainer`` dispatches a K-step chunk as ONE program, so there
is no per-batch host point to ack from. This module lifts the ack
protocol to the chunk: the unit of loss on a shard death is AT MOST
ONE CHUNK.

:class:`FailoverRunner` drives one scanned distributed epoch with:

* **liveness** — any object with ``dead_ranks() -> {rank: cause}``
  (``distributed.resilience.Heartbeat`` is the production
  implementation: survivors learn of a dead shard in
  ``interval x miss`` seconds). The runner polls it at every chunk
  boundary (the ``stage_hook`` seam) and raises
  :class:`ShardDeadError` BEFORE dispatching into a broken mesh.
* **per-chunk rollback buffer** — a memory-only
  :class:`~..recovery.checkpoint.ChunkCheckpointer` (``mem_every=1``)
  snapshots the boundary state after every chunk, so the roll-back
  target is always the LAST ACKED chunk boundary.
* **rebuild + deterministic replay** — on a death the runner computes
  the epoch's REMAINING seeds by replaying the seed-matrix math on the
  host (``storage.planner.replay_seed_matrix`` — threefry is
  bit-identical across backends, the same property the prefetch
  planner trusts), calls the caller's ``rebuild(remaining_seeds,
  num_survivors)`` factory — which re-partitions the data, rebuilds
  the mesh and the cached feature stores (the rebuild-on-failover
  contract, docs/feature_cache.md) — and replays forward from the
  rollback state. Every seed of the original epoch is trained EXACTLY
  ONCE across the segments (chaos-tested).

The ``loader.failover`` span carries the ROLLED-BACK CHUNK INDEX,
the dead rank and the survivor count, and parents the replacement
epoch's ``epoch.run`` span — one joinable tree for the degraded epoch,
orphan-free (docs/observability.md). The aborted attempt's own flight
record lands ``completed=False`` with the step it reached (the
trainers' bracket), and ``recovery.roll_back`` is the fault site the
chaos suite arms against the rollback path itself.
"""
import logging
from typing import Any, Callable, List, Optional

import numpy as np

from .. import metrics
from ..metrics import spans
from ..utils.faults import fault_point
from .checkpoint import ChunkCheckpointer

logger = logging.getLogger('graphlearn_tpu.recovery')


class ShardDeadError(RuntimeError):
  """A mesh shard was declared dead at a chunk boundary.

  Carries the rank, the liveness cause, and the index of the next
  chunk that was ABOUT to dispatch (everything before it is acked)."""

  def __init__(self, rank: int, cause: str = '', chunk: int = 0):
    super().__init__(f'mesh shard rank {rank} declared dead at chunk '
                     f'{chunk}' + (f': {cause}' if cause else ''))
    self.rank = rank
    self.cause = cause
    self.chunk = chunk


def remaining_seeds(trainer, boundary_step: int) -> np.ndarray:
  """The epoch-ordered seeds NOT yet consumed at ``boundary_step``
  (a chunk boundary) of ``trainer``'s CURRENT epoch — replayed on the
  host from the same permutation stream the device seed program draws
  (``trainer._epochs`` is un-advanced while the epoch is in flight,
  so the fold_in index is the aborted epoch's)."""
  import jax

  from ..storage import planner
  loader = trainer.loader
  full_steps = len(loader)
  perm_key = jax.random.fold_in(trainer._perm_key, trainer._epochs)
  seed_mat, mask_mat = planner.replay_seed_matrix(
      np.asarray(loader.input_seeds), perm_key, full_steps,
      trainer._batch_size, loader.shuffle, nparts=trainer._nparts)
  # [P, steps, B] -> epoch order [steps, P, B]; pad slots (cyclic tail)
  # are masked invalid and drop out, so every seed appears exactly once
  sm = seed_mat.transpose(1, 0, 2)[boundary_step:]
  mm = mask_mat.transpose(1, 0, 2)[boundary_step:]
  return np.asarray(sm[mm], dtype=np.int64)


class FailoverRunner:
  """Run one DistScanTrainer epoch with chunk-granular failover.

  Args:
    trainer: the initial ``loader.DistScanTrainer`` over the full mesh.
    rebuild: ``rebuild(remaining_seeds, num_survivors) -> trainer`` —
      builds a replacement DistScanTrainer over the surviving shard
      count whose loader iterates EXACTLY ``remaining_seeds`` with
      ``shuffle=False`` (the runner hands seeds already in epoch
      order; a reshuffle would double/drop seeds). The factory owns
      re-partitioning and store rebuilds.
    liveness: object with ``dead_ranks() -> {rank: cause}`` (e.g. a
      started ``resilience.Heartbeat``); polled at every chunk
      boundary. None disables detection (the runner then only reacts
      to a ShardDeadError raised by a hook).
    max_failovers: deaths tolerated in one epoch before giving up
      (the original error re-raises).

  Usage::

      hb = Heartbeat(range(P), probe_fn, interval=1.0); hb.start()
      runner = FailoverRunner(trainer, rebuild, liveness=hb)
      state, losses, accs, report = runner.run_epoch(state)
  """

  def __init__(self, trainer, rebuild: Callable[[np.ndarray, int], Any],
               liveness=None, max_failovers: int = 1):
    self.trainer = trainer
    self.rebuild = rebuild
    self.liveness = liveness
    self.max_failovers = int(max_failovers)

  def _install_liveness_hook(self, trainer):
    prev = trainer.stage_hook
    liveness = self.liveness
    handled = self._handled

    def hook(c, start, k):
      if prev is not None:
        prev(c, start, k)
      if liveness is not None:
        for rank, cause in liveness.dead_ranks().items():
          if rank not in handled:
            raise ShardDeadError(rank, cause, chunk=c)

    trainer.stage_hook = hook
    return prev

  def run_epoch(self, state, max_steps: Optional[int] = None):
    """One failure-tolerant epoch. Returns ``(state, losses, accs,
    report)``: losses/accs are HOST float arrays over every optimizer
    step actually taken (completed-chunk prefix + replayed remainder —
    step COUNT can differ from the undisturbed epoch when the batch
    grid re-slices over fewer shards, seed coverage cannot), and
    ``report`` records the failovers (rank, cause, rolled_back_chunk,
    survivors) plus per-segment step counts."""
    if max_steps is not None:
      raise ValueError('FailoverRunner covers full epochs: max_steps '
                       'would make "remaining seeds" ambiguous across '
                       'failover segments')
    trainer = self.trainer
    self._handled: set = set()
    survivors = trainer._nparts
    losses_parts: List[np.ndarray] = []
    accs_parts: List[np.ndarray] = []
    report = dict(failovers=[], segments=[])
    open_spans = []
    failures = 0
    state_in = state
    ovf0 = False
    try:
      while True:
        ckpt = ChunkCheckpointer(None, every=1, mem_every=1)
        prev_stage = self._install_liveness_hook(trainer)
        ckpt.attach(trainer)
        try:
          # a shard already dead at epoch start: its whole share fails
          # over before anything dispatches (PR 2's epoch-start path)
          if self.liveness is not None:
            for rank, cause in self.liveness.dead_ranks().items():
              if rank not in self._handled:
                raise ShardDeadError(rank, cause, chunk=0)
          state_out, losses, accs = trainer.run_epoch(
              state_in, resume_overflow=ovf0)
          losses_parts.append(np.asarray(losses))
          accs_parts.append(np.asarray(accs))
          report['segments'].append(
              dict(num_parts=trainer._nparts,
                   steps=int(np.asarray(losses).shape[0])))
          return (state_out, np.concatenate(losses_parts),
                  np.concatenate(accs_parts), report)
        except ShardDeadError as e:
          failures += 1
          self._handled.add(e.rank)
          if failures > self.max_failovers:
            raise
          fault_point('recovery.roll_back')
          metrics.inc('recovery.rollbacks')
          rolled = ckpt.latest_mem
          boundary = (int(rolled['meta']['next_start'])
                      if rolled is not None else 0)
          k = trainer.chunk_size
          fo_span = spans.begin('loader.failover', rank=e.rank,
                                cause=str(e.cause)[:200],
                                rolled_back_chunk=boundary // k,
                                detected_chunk=e.chunk,
                                survivors=survivors - 1)
          open_spans.append(fo_span)
          logger.warning(
              'shard rank %d died (%s): rolling back to chunk '
              'boundary %d (step %d) and re-slicing over %d survivors',
              e.rank, e.cause, boundary // k, boundary, survivors - 1)
          rem = remaining_seeds(trainer, boundary)
          if rolled is not None:
            losses_parts.append(np.asarray(rolled['losses']))
            accs_parts.append(np.asarray(rolled['accs']))
            state_in = rolled['state']
            ovf0 = bool(rolled['meta']['overflow'])
          report['segments'].append(
              dict(num_parts=trainer._nparts, steps=boundary))
          report['failovers'].append(
              dict(rank=e.rank, cause=str(e.cause)[:200],
                   rolled_back_chunk=boundary // k,
                   detected_chunk=e.chunk, remaining_seeds=len(rem),
                   survivors=survivors - 1))
          survivors -= 1
          if survivors < 1:
            raise
        finally:
          ckpt.detach()
          trainer.stage_hook = prev_stage
        # rebuild OUTSIDE the hook bracket: the replacement trainer's
        # epoch.run span parents under the open loader.failover span
        trainer = self.rebuild(rem, survivors)
        if trainer.loader.shuffle:
          raise ValueError('rebuild() must return a shuffle=False '
                           'loader over the seeds it was handed — a '
                           'reshuffle would break exact-once coverage')
    finally:
      for sp in reversed(open_spans):
        spans.end(sp)
