"""ChunkCheckpointer: async exact checkpointing at scanned-chunk
boundaries, and the mid-epoch resume that replays bit-identically.

The scanned trainers (ScanTrainer / DistScanTrainer /
TieredScanTrainer) run an epoch as ``ceil(steps/K) + 2`` dispatches
with every random draw addressed by a host counter (the PR 1/4 replay
contracts). That contract makes recovery CHEAP: a checkpoint at a
chunk boundary needs only the train-state leaves, the losses already
produced, and a handful of counters — the seed permutation and every
remaining per-step draw replay from them exactly, so a
:meth:`resume_epoch` after a crash produces the remaining chunks'
losses and the final params BIT-IDENTICAL to the uninterrupted run
(tests/test_recovery.py pins this for all three trainers).

Mechanics (the ChunkStager pattern, storage/staging.py):

* :meth:`attach` rides the trainers' existing ``ack_hook`` seam. At
  every K-chunk cadence hit the dispatch thread materializes a HOST
  copy of the boundary state (one explicit ``jax.device_get`` — the
  strict_guards region only rejects implicit transfers, and the copy
  must happen before the next chunk dispatch donates the buffers) and
  hands it to a bounded writer thread. Zero extra program dispatches:
  the GLT_STRICT dispatch-budget tests bit-match ``ceil(steps/K)+2``
  with a checkpointer attached.
* The writer serializes + atomically writes the snapshot
  (recovery/snapshot.py) off the critical path. A slow or failed
  writer DEGRADES TO SYNC — the boundary writes inline
  (``checkpoint.sync_fallback``) — and a failing save never kills the
  epoch (``checkpoint.save_errors``): checkpointing is insurance, not
  a new failure mode. Torn files are impossible by construction
  (tmp + fsync + rename) and DETECTED if produced by outside forces
  (``checkpoint.torn_skipped`` — restore falls back to the previous
  snapshot).
* :meth:`resume_epoch` restores the newest valid snapshot into a
  FRESH trainer (config-fingerprint-checked), rewinds the sampler /
  epoch counters, and re-runs ``run_epoch(start_step=...)`` over the
  remaining chunks. A resume that fails mid-replay still writes its
  ``completed=False`` flight record with the chunk it reached — that
  bracket lives in the trainers themselves.

Observability: ``checkpoint.*`` metrics + the ``checkpoint.save`` /
``recovery.resume`` spans (docs/observability.md); fault sites
``recovery.save`` / ``recovery.restore`` (docs/failure_model.md).
"""
import logging
import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import metrics
from ..metrics import flight, spans
from . import snapshot as snapshot_lib
from .snapshot import Snapshot, TornSnapshotError

logger = logging.getLogger('graphlearn_tpu.recovery')


class _AckChain:
  """The installed ack_hook: run any previously-installed hook, then
  the checkpointer's boundary capture. A module-level callable (not a
  closure) on purpose — hooks are HOST-side objects, and graftlint's
  nested-def-in-builder convention would otherwise read a closure here
  as a traced program body."""

  __slots__ = ('ckpt', 'prev')

  def __init__(self, ckpt, prev):
    self.ckpt = ckpt
    self.prev = prev

  def __call__(self, c, start, k):
    if self.prev is not None:
      self.prev(c, start, k)
    self.ckpt._on_ack(c, start, k)


class ChunkCheckpointer:
  """Chunk-cadence exact checkpointing for the scanned trainers.

  Args:
    directory: snapshot directory, or None for MEMORY-ONLY snapshots
      (the failover runner's rollback buffer — nothing touches disk).
    every: disk-write cadence in chunks (a snapshot lands after chunks
      ``every-1``, ``2*every-1``, ... and always after the final
      chunk). The resume replays at most ``every`` chunks of lost
      work.
    keep: newest snapshots retained on disk (older ones pruned after
      each successful write; >= 2 keeps a fallback for torn files).
    mem_every: in-memory snapshot cadence (None = same boundaries as
      ``every``). The failover runner sets 1: rollback then loses at
      most the in-flight chunk.
    max_pending: bounded writer queue depth; a boundary that finds it
      full writes synchronously instead of stalling the ring.

  Usage::

      ckpt = ChunkCheckpointer('/ckpts/run1', every=4).attach(trainer)
      state, losses, accs = trainer.run_epoch(state)   # checkpointed
      ...
      # after a crash, in a fresh process:
      ckpt = ChunkCheckpointer('/ckpts/run1').attach(fresh_trainer)
      state, losses, accs = ckpt.resume_epoch(fresh_trainer, template)
  """

  def __init__(self, directory: Optional[str] = None, every: int = 4,
               keep: int = 2, mem_every: Optional[int] = None,
               max_pending: int = 2):
    if every < 1:
      raise ValueError(f'every must be >= 1, got {every}')
    if keep < 1:
      raise ValueError(f'keep must be >= 1, got {keep}')
    self.directory = directory
    self.every = int(every)
    self.keep = int(keep)
    self.mem_every = int(mem_every) if mem_every is not None else None
    self.max_pending = int(max_pending)
    self.latest_mem: Optional[dict] = None   # structured host snapshot
    self.degraded = False    # a writer-thread save failed this run
    self._trainer = None
    self._prev_ack = None
    self._q: 'queue.Queue' = queue.Queue(maxsize=max(1, max_pending))
    self._worker: Optional[threading.Thread] = None
    self._wlock = threading.Lock()   # serializes file writes + prunes
    self._stop = False

  # ------------------------------------------------------------- lifecycle

  def attach(self, trainer) -> 'ChunkCheckpointer':
    """Hook this checkpointer onto ``trainer``'s ``ack_hook`` seam
    (chaining any hook already installed). Returns self."""
    if self._trainer is not None:
      raise RuntimeError('already attached; detach() first')
    self._trainer = trainer
    self._prev_ack = trainer.ack_hook
    trainer.ack_hook = _AckChain(self, self._prev_ack)
    return self

  def detach(self):
    """Restore the trainer's previous ack_hook."""
    if self._trainer is not None:
      self._trainer.ack_hook = self._prev_ack
      self._trainer = None
      self._prev_ack = None

  def flush(self):
    """Block until every queued async write has hit disk."""
    self._q.join()

  def close(self):
    """Drain pending writes and stop the writer thread."""
    self.flush()
    self._stop = True
    self._q.put(None)
    w = self._worker
    if w is not None:
      w.join(timeout=10.0)
    self._worker = None
    self._stop = False
    try:     # drain a leftover sentinel (the ChunkStager close contract)
      while True:
        self._q.get_nowait()
        self._q.task_done()
    except queue.Empty:
      pass

  def _ensure_worker(self):
    if self._worker is not None and self._worker.is_alive():
      return
    self._worker = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-chunk-checkpointer')
    self._worker.start()

  def _loop(self):
    while True:
      item = self._q.get()
      try:
        if item is None or self._stop:
          return
        self._write_item(item, sync=False)
      finally:
        self._q.task_done()

  # --------------------------------------------------------------- capture

  def _on_ack(self, c: int, start: int, k: int):
    """Chunk boundary: decide cadence, materialize the host snapshot,
    route it to memory and/or the writer. Never raises — a checkpoint
    failure must not kill the epoch it exists to protect."""
    try:
      trainer = self._trainer
      carry = getattr(trainer, '_chunk_carry', None)
      if carry is None:
        return
      next_start = start + k
      steps = int(carry['steps'])
      final = next_start >= steps
      disk_hit = self.directory is not None and (
          (c + 1) % self.every == 0 or final)
      mem_hit = (c + 1) % (self.mem_every or self.every) == 0 or final
      if not (disk_hit or mem_hit):
        return
      t0 = time.perf_counter()
      host = self._capture(trainer, carry, c, next_start)
      if host is None:
        return
      metrics.observe('checkpoint.capture_ms',
                      (time.perf_counter() - t0) * 1e3)
      if mem_hit:
        self.latest_mem = host
      if disk_hit:
        self._submit(host)
    except Exception:
      metrics.inc('checkpoint.save_errors')
      logger.exception('chunk checkpoint capture failed — epoch '
                       'continues unprotected past this boundary')

  def _capture(self, trainer, carry: dict, c: int,
               next_start: int) -> Optional[dict]:
    """One explicit device->host fetch of the boundary state. Runs on
    the dispatch thread BEFORE the next chunk dispatch donates the
    carry buffers (the strict_guards region allows explicit
    transfers). Returns None when the boundary cannot yield a
    WHOLE-epoch snapshot (a resumed epoch whose pre-crash loss prefix
    is unknown) — a partial-loss snapshot would silently break the
    bit-identity contract at the next resume."""
    import jax
    start_step = int(carry.get('start_step', 0))
    prefix = None
    if start_step:
      # a resumed epoch produces losses only for [start_step, now);
      # resume_epoch stashes the checkpointed prefix so snapshots
      # taken DURING the replay still cover the whole epoch (a second
      # crash resumes exactly like the first)
      prefix = getattr(trainer, '_recovery_prefix', None)
      if (prefix is None or prefix['epoch'] != int(trainer._epochs)
          or prefix['start_step'] != start_step):
        logger.warning(
            'chunk %d boundary of a start_step=%d epoch has no loss '
            'prefix (run_epoch(start_step=...) called outside '
            'resume_epoch?) — skipping this snapshot rather than '
            'writing a partial-loss one', c, start_step)
        return None
    meta_extra, dev_extra = trainer._recovery_capture(carry)
    bundle = dict(state=carry['state'], ovf=carry['ovf'],
                  losses=list(carry['losses']), accs=list(carry['accs']),
                  extra=dev_extra)
    host = jax.device_get(bundle)
    losses = (np.concatenate([np.atleast_1d(a) for a in host['losses']])
              if host['losses'] else np.zeros((0,), np.float32))
    accs = (np.concatenate([np.atleast_1d(a) for a in host['accs']])
            if host['accs'] else np.zeros((0,), np.float32))
    if prefix is not None:
      losses = np.concatenate([prefix['losses'], losses])
      accs = np.concatenate([prefix['accs'], accs])
    meta = dict(format=1, trainer=trainer._NAME,
                epoch=int(trainer._epochs), chunk=int(c),
                next_start=int(next_start), steps=int(carry['steps']),
                full_steps=int(carry['full_steps']),
                chunk_size=int(trainer.chunk_size),
                overflow=bool(host['ovf']),
                # the STREAM-tight config (flight config + sampler
                # strategy/window/dedup + seed-pool digest): resume
                # refuses any drift that would replay different draws
                config_fingerprint=flight.config_fingerprint(
                    trainer._recovery_config()))
    meta.update(meta_extra)
    return dict(meta=meta, state=host['state'], losses=losses,
                accs=accs, extra=host['extra'])

  # ----------------------------------------------------------------- write

  def _submit(self, host: dict):
    item = self._flatten(host)
    self._ensure_worker()
    if self.degraded or self._worker is None or \
        not self._worker.is_alive():
      metrics.inc('checkpoint.sync_fallback')
      self._write_item(item, sync=True)
      return
    try:
      self._q.put_nowait(item)
    except queue.Full:
      # slow writer: never stall the ring unbounded — write inline
      metrics.inc('checkpoint.sync_fallback')
      self._write_item(item, sync=True)

  @staticmethod
  def _flatten(host: dict) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Structured host snapshot -> (meta, named arrays) for the file
    format. Leaf order is the pytree flatten order; the resume
    template re-supplies the structure."""
    import jax
    leaves = jax.tree_util.tree_leaves(host['state'])
    meta = dict(host['meta'], n_leaves=len(leaves))
    arrays = {f'leaf_{i:05d}': np.asarray(a)
              for i, a in enumerate(leaves)}
    arrays['losses'] = host['losses']
    arrays['accs'] = host['accs']
    for key, arr in (host['extra'] or {}).items():
      arrays[f'extra:{key}'] = np.asarray(arr)
    return meta, arrays

  def _write_item(self, item: Tuple[dict, Dict[str, np.ndarray]],
                  sync: bool):
    meta, arrays = item
    try:
      with self._wlock:
        with spans.span('checkpoint.save', epoch=meta['epoch'],
                        next_start=meta['next_start'], sync=sync):
          t0 = time.perf_counter()
          _, nbytes = snapshot_lib.write_snapshot(self.directory, meta,
                                                  arrays)
          metrics.observe('checkpoint.save_ms',
                          (time.perf_counter() - t0) * 1e3)
          metrics.inc('checkpoint.saves')
          metrics.inc('checkpoint.bytes', nbytes)
        self._prune()
    except Exception as e:
      # a failed save degrades (later boundaries write sync) but NEVER
      # propagates — the epoch it protects must finish
      self.degraded = True
      metrics.inc('checkpoint.save_errors')
      logger.warning('checkpoint save at epoch %s step %s failed (%s) '
                     '— degrading to synchronous writes',
                     meta.get('epoch'), meta.get('next_start'), e)

  def _prune(self):
    snaps = snapshot_lib.list_snapshots(self.directory)
    for _, _, path in snaps[:-self.keep]:
      try:
        import os
        os.unlink(path)
      except OSError:
        pass

  # ---------------------------------------------------------------- resume

  def latest(self) -> Optional[Snapshot]:
    """Newest VALID on-disk snapshot (torn/corrupt files are skipped
    with ``checkpoint.torn_skipped``), or None."""
    if self.directory is None:
      return None
    t0 = time.perf_counter()
    for _, _, path in reversed(snapshot_lib.list_snapshots(
        self.directory)):
      try:
        snap = snapshot_lib.load_snapshot(path)
        metrics.observe('checkpoint.restore_ms',
                        (time.perf_counter() - t0) * 1e3)
        return snap
      except (TornSnapshotError, OSError, ValueError) as e:
        metrics.inc('checkpoint.torn_skipped')
        logger.warning('skipping unrestorable snapshot %s: %s', path, e)
      except Exception as e:  # noqa: BLE001 - injected restore faults land here
        metrics.inc('checkpoint.torn_skipped')
        logger.warning('snapshot %s failed to restore (%s) — falling '
                       'back to the previous one', path, e)
    return None

  def resume_epoch(self, trainer, state_template: Any,
                   snapshot: Optional[Snapshot] = None):
    """Restore the newest snapshot into ``trainer`` and finish its
    epoch. Returns ``(state, losses, accs)`` with losses/accs HOST
    float arrays covering the WHOLE epoch (checkpointed prefix +
    replayed remainder) — bit-identical to the uninterrupted run.

    ``trainer`` is typically a FRESH instance over an identically
    configured loader (same seeds, batch size, shuffle, chunk_size) —
    the snapshot's config fingerprint is checked against it, so a
    drifted configuration fails loudly instead of resuming a
    different stream. ``state_template`` supplies the train-state
    pytree STRUCTURE (e.g. a fresh ``create_train_state`` result);
    its leaf values are discarded.
    """
    import jax
    if self._trainer is not None and self._trainer is not trainer:
      raise RuntimeError('attached to a different trainer; detach() '
                         'or attach to the one being resumed')
    if self._worker is not None:
      self.flush()
    snap = snapshot or self.latest()
    if snap is None:
      raise FileNotFoundError(
          f'no restorable snapshot in {self.directory!r}')
    meta = snap.meta
    if meta.get('trainer') != trainer._NAME:
      raise ValueError(
          f"snapshot was written by {meta.get('trainer')!r}, resuming "
          f'into {trainer._NAME!r} would diverge')
    fp = flight.config_fingerprint(trainer._recovery_config())
    if meta.get('config_fingerprint') != fp:
      raise ValueError(
          'snapshot config fingerprint '
          f"{meta.get('config_fingerprint')} != this trainer's {fp} — "
          'loader/trainer/sampler configuration drifted (batch, chunk '
          'size, fanouts, shuffle, sampling strategy/window, or the '
          'seed pool itself); resuming would not replay the same '
          'stream (docs/recovery.md)')
    leaves_t, treedef = jax.tree_util.tree_flatten(state_template)
    n = int(meta['n_leaves'])
    if len(leaves_t) != n:
      raise ValueError(f'state template has {len(leaves_t)} leaves, '
                       f'snapshot has {n}')
    host_leaves = []
    for i, tmpl in enumerate(leaves_t):
      leaf = snap.arrays[f'leaf_{i:05d}']
      t_shape = tuple(np.shape(tmpl))
      if tuple(leaf.shape) != t_shape:
        raise ValueError(f'leaf {i}: snapshot shape {leaf.shape} != '
                         f'template shape {t_shape}')
      host_leaves.append(leaf)
    # EXPLICIT upload of the restored leaves: the chunk programs run
    # under strict_guards (transfer_guard('disallow')), which would
    # reject a host numpy state arriving implicitly at dispatch. The
    # dist trainer re-commits to its replicated mesh sharding itself.
    state = jax.device_put(
        jax.tree_util.tree_unflatten(treedef, host_leaves))
    extras = {k[len('extra:'):]: v for k, v in snap.arrays.items()
              if k.startswith('extra:')}
    steps, next_start = int(meta['steps']), int(meta['next_start'])
    saved_losses = np.asarray(snap.arrays['losses'])
    saved_accs = np.asarray(snap.arrays['accs'])
    metrics.inc('recovery.resumes')
    if next_start >= steps:
      # the epoch completed before the crash: position the counters
      # AFTER it (not at its start — _recovery_load is the replay
      # path's rewind, and re-restoring already-published stats or
      # rewinding the padded-table seed here would double-count the
      # finished epoch) and hand back its final state — the caller
      # starts the next epoch
      trainer._recovery_advance(meta)
      return state, saved_losses, saved_accs
    trainer._recovery_load(meta, extras)
    k = int(meta['chunk_size'])
    replay_chunks = -(-(steps - next_start) // k)
    metrics.inc('recovery.resume_chunks', replay_chunks)
    max_steps = steps if steps < int(meta['full_steps']) else None
    # snapshots taken DURING the replay must still cover the whole
    # epoch: hand the checkpointed loss prefix to _capture (cleared
    # afterwards — it is only meaningful for this epoch's replay)
    trainer._recovery_prefix = dict(epoch=int(meta['epoch']),
                                    start_step=next_start,
                                    losses=saved_losses,
                                    accs=saved_accs)
    try:
      with spans.span('recovery.resume', epoch=meta['epoch'],
                      start_step=next_start,
                      replay_chunks=replay_chunks):
        state, losses, accs = trainer.run_epoch(
            state, max_steps=max_steps, start_step=next_start,
            resume_overflow=bool(meta.get('overflow', False)))
    finally:
      trainer._recovery_prefix = None
    return (state,
            np.concatenate([saved_losses, np.asarray(losses)]),
            np.concatenate([saved_accs, np.asarray(accs)]))
