"""One-call autotuned fast-path configuration (docs/tuning.md).

``tune(dataset, loader_cfg)`` runs the calibration probes + short
observatory-scored candidate A/Bs and emits a versioned,
sha1-fingerprinted :class:`TuneArtifact` that the scan trainers and
the serving engine accept directly via ``config=`` — every scenario
lands on the fast path from one call, and a config that would retrace
is rejected by construction.
"""
from .artifact import (ARTIFACT_VERSION, KERNEL_CHOICE_DEFAULTS,
                       KERNEL_CHOICE_KEYS, TOPOLOGY_CHOICE_DEFAULTS,
                       TOPOLOGY_CHOICE_KEYS, TuneArtifact,
                       apply_kernel_routing, dataset_fingerprint)
from .retune import (RetuneScheduler, hit_rate_decay_probe,
                     p99_creep_probe, retrace_overrun_probe)
from .topology import (TOPOLOGY_KNOBS, TOPOLOGY_SITES,
                       TopologyCandidate, default_topology_candidates,
                       screen_candidate, tune_topology)
from .tuner import (Candidate, default_candidates, kernel_candidates,
                    retrace_probe_candidate, score_candidate, tune)

__all__ = [
    'ARTIFACT_VERSION', 'KERNEL_CHOICE_DEFAULTS', 'KERNEL_CHOICE_KEYS',
    'TOPOLOGY_CHOICE_DEFAULTS', 'TOPOLOGY_CHOICE_KEYS',
    'TuneArtifact', 'apply_kernel_routing', 'dataset_fingerprint',
    'RetuneScheduler', 'hit_rate_decay_probe', 'p99_creep_probe',
    'retrace_overrun_probe',
    'TOPOLOGY_KNOBS', 'TOPOLOGY_SITES', 'TopologyCandidate',
    'default_topology_candidates', 'screen_candidate', 'tune_topology',
    'Candidate', 'default_candidates', 'kernel_candidates',
    'retrace_probe_candidate', 'score_candidate', 'tune',
]

# `graphlearn_tpu.tune(dataset, loader_cfg)` IS the advertised one
# call (README quickstart) — make the subpackage itself callable so
# the package attribute serves both as the namespace
# (tune.TuneArtifact) and as the entry point. Module-class override is
# the supported mechanism (the module object's type gains __call__);
# nothing else about import semantics changes.
import sys as _sys


class _CallableTuneModule(type(_sys.modules[__name__])):

  def __call__(self, dataset, loader_cfg, **kwargs):
    return tune(dataset, loader_cfg, **kwargs)


_sys.modules[__name__].__class__ = _CallableTuneModule
