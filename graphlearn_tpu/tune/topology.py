"""Per-topology candidate fields for `tune()` (docs/tuning.md
'Topology candidates').

The homo local-scan path tunes loader-level knobs; the distributed
topologies' marquee knobs are STORE-CONSTRUCTION parameters — the dist
exchange's ``bucket_frac``/``split_ratio``/wire dtype, the remote
block streams' ``block_ahead``/``block_wire_dtype``, the tiered
exchange's slab caps and ``hot_prefix_rows``. A candidate therefore
cannot be expressed as loader kwargs over one shared dataset: each one
is a freshly BUILT scenario. The caller supplies that constructor as
``loader_cfg['make_scenario'](knobs, chunk_k) -> (trainer, state)``
and this module runs every candidate scenario through the same
observatory scoring rule the local path trusts:

1. **Feasibility screen first** (no device work): the dist exchange's
   analytic all_to_all volume (``dist_feature.feature_exchange_mb``),
   the remote block frames' in-flight MB
   (``block_producer.block_mb_per_chunk`` x ``block_ahead``), and the
   tiered slab plan's pow2 cap (``storage.staging.pow2_slab_cap`` /
   ``planner.plan_exchange`` via ``loader_cfg['plan_fn']``) are
   checked against the caller's quotas — an infeasible candidate
   (slab overflow, quota-busting ring bytes) is rejected WITH the
   analytic numbers before burning an A/B epoch.
2. **Compile epoch, then steady epoch**: the scenario's own program
   sites (``TOPOLOGY_SITES``) are watched; ANY steady-state compile
   disqualifies by construction, with the signature diff naming the
   drifted argument. Qualified candidates rank by steady wall per
   step; under ``GLT_PROGRAM_COST=1`` near-ties break on cost.

The result is one fingerprint-validated
:class:`~graphlearn_tpu.tune.artifact.TuneArtifact` per topology that
the MATCHING trainer's ``config=`` path accepts (and a mismatched one
refuses — tune/artifact.py ``topology``).
"""
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import metrics
from ..metrics import programs, spans
from . import probes
from .artifact import TuneArtifact, dataset_fingerprint

#: the trainer scenarios tune() fields candidates for, and the program
#: sites each one dispatches through — the population the "zero
#: steady-state compiles" acceptance counts per topology
TOPOLOGY_SITES = {
    'local': ('epoch_seeds', 'scan_chunk', 'metrics_concat'),
    'dist': ('dist_epoch_seeds', 'dist_scan_chunk',
             'dist_metrics_concat'),
    'tiered_dist': ('dist_epoch_seeds', 'dist_scan_chunk',
                    'dist_metrics_concat'),
    'remote': ('remote_epoch_begin', 'remote_scan_chunk',
               'remote_metrics_concat'),
}

#: which artifact choice keys each topology's candidate knobs may set
#: — a candidate naming a knob outside its topology's field is a
#: construction error, not evidence
TOPOLOGY_KNOBS = {
    'dist': frozenset({'bucket_frac', 'split_ratio', 'wire_dtype'}),
    'remote': frozenset({'block_ahead', 'block_wire_dtype'}),
    'tiered_dist': frozenset({'bucket_frac', 'split_ratio',
                              'wire_dtype', 'slab_cap',
                              'hot_prefix_rows'}),
}


class TopologyCandidate:
  """One scenario candidate for a topology A/B.

  Args:
    name: evidence-log label.
    knobs: the scenario-construction knobs (TOPOLOGY_KNOBS subset for
      the topology) handed to ``make_scenario``.
    chunk_k: per-candidate chunk override (None = the probed K).
    exact_semantics: False for certified relaxations (bf16 wire).
  """

  def __init__(self, name: str, knobs: Dict, chunk_k: Optional[int] = None,
               exact_semantics: bool = True):
    self.name = name
    self.knobs = dict(knobs)
    self.chunk_k = chunk_k
    self.exact_semantics = exact_semantics


def default_topology_candidates(topology: str, cfg: Dict,
                                exact: bool) -> List[TopologyCandidate]:
  """The stock candidate field per topology: the full-width exact
  baseline first (the stable-sort tie-break anchor), the cache/prefetch
  variants, then the accuracy-matrix-certified bf16 wire unless
  ``exact=True`` pinned the exact set. Tiered fields need the
  caller's hot-prefix ladder (``cfg['hot_prefix_choices']``) — there
  is no topology-free default for a knob bounded by the shard's own
  row count."""
  if topology == 'dist':
    cands = [
        TopologyCandidate('dist_fullwidth',
                          dict(bucket_frac=None, split_ratio=0.0,
                               wire_dtype=None)),
        TopologyCandidate('dist_bucketed',
                          dict(bucket_frac=2.0, split_ratio=0.25,
                               wire_dtype=None)),
    ]
    if not exact:
      cands.append(TopologyCandidate(
          'dist_bucketed_bf16',
          dict(bucket_frac=2.0, split_ratio=0.25, wire_dtype='bf16'),
          exact_semantics=False))
    return cands
  if topology == 'remote':
    cands = [
        TopologyCandidate('remote_ahead2', dict(block_ahead=2,
                                                block_wire_dtype=None)),
        TopologyCandidate('remote_ahead1', dict(block_ahead=1,
                                                block_wire_dtype=None)),
    ]
    if not exact:
      cands.append(TopologyCandidate(
          'remote_ahead2_bf16',
          dict(block_ahead=2, block_wire_dtype='bf16'),
          exact_semantics=False))
    return cands
  if topology == 'tiered_dist':
    hots = cfg.get('hot_prefix_choices')
    if not hots:
      raise ValueError(
          "tune(topology='tiered_dist') needs either explicit "
          "candidates= or loader_cfg['hot_prefix_choices'] (the "
          'hot-prefix row ladder to field) — the knob is bounded by '
          'the shard row count, which only the caller knows '
          '(docs/tuning.md)')
    return [TopologyCandidate(f'tiered_hot{h}',
                              dict(hot_prefix_rows=int(h)))
            for h in hots]
  raise ValueError(f'no default candidate field for topology '
                   f'{topology!r}')


# ------------------------------------------------------------ analytics


def _choice_fanouts(fanouts):
  """Artifact-choice form of a fanout spec: typed dicts serialize with
  canonical string etype keys (typing.as_str) so the JSON round-trip
  is loss-free; flat lists stay flat."""
  if isinstance(fanouts, dict):
    from ..typing import as_str
    return {(as_str(et) if isinstance(et, (list, tuple)) else str(et)):
            [int(k) for k in f]
            for et, f in sorted(fanouts.items(), key=lambda kv:
                                str(kv[0]))}
  return [int(k) for k in fanouts]


def _flatten_fanouts(fanouts) -> List[int]:
  """Per-hop effective fan-out of a fanout spec. A typed dict sums the
  per-etype counts hop-wise — a frontier node can fan out along every
  relation at once, so the analytic budget is the hop-wise SUM, the
  same worst case the hetero CapacityPlan closes its shapes over
  (docs/capacity_plans.md)."""
  if isinstance(fanouts, dict):
    hops = max(len(f) for f in fanouts.values())
    return [sum(int(f[h]) for f in fanouts.values() if h < len(f))
            for h in range(hops)]
  return [int(k) for k in fanouts]


def _node_budget(fanouts, batch_size: int) -> int:
  """Worst-case per-step frontier node budget (seeds + every hop's
  full fan-out) — the static plan the feasibility analytics size
  against when the caller supplies no calibrated caps. Accepts a flat
  per-hop list or a typed per-etype dict."""
  total, width = batch_size, batch_size
  for k in _flatten_fanouts(fanouts):
    width *= int(k)
    total += width
  return int(total)


def screen_candidate(topology: str, cand: TopologyCandidate,
                     chunk_k: int, cfg: Dict) -> Tuple[bool, dict]:
  """Analytic feasibility of one candidate against the caller's
  quotas, BEFORE any device work. Returns (feasible, evidence). The
  quotas are opt-in (``max_exchange_mb`` / ``max_block_mb`` /
  ``max_slab_rows``); with none set every candidate screens feasible
  and the evidence still records the analytic volumes."""
  ev = dict(kind='feasibility', name=cand.name, topology=topology,
            feasible=True)
  unknown = set(cand.knobs) - TOPOLOGY_KNOBS[topology]
  if unknown:
    raise ValueError(
        f'candidate {cand.name!r} names knobs {sorted(unknown)} '
        f'outside the {topology!r} field {sorted(TOPOLOGY_KNOBS[topology])} '
        '(docs/tuning.md "Topology candidates")')
  fanouts = cfg['fanouts'] if isinstance(cfg['fanouts'], dict) \
      else [int(k) for k in cfg['fanouts']]
  batch = int(cfg['batch_size'])
  feat_dim = cfg.get('feat_dim')
  width = int(cfg.get('request_width') or _node_budget(fanouts, batch))
  if topology in ('dist', 'tiered_dist') and feat_dim:
    from ..distributed.dist_feature import feature_exchange_mb
    wire = cand.knobs.get('wire_dtype')
    mb = feature_exchange_mb(
        width, int(cfg.get('num_partitions', 1)), int(feat_dim),
        bucket_frac=cand.knobs.get('bucket_frac', 2.0),
        wire_bytes=2 if wire == 'bf16' else 4,
        hit_rate=float(cand.knobs.get('split_ratio') or 0.0))
    ev['exchange_mb'] = round(mb, 4)
    quota = cfg.get('max_exchange_mb')
    if quota is not None and mb > float(quota):
      ev.update(feasible=False, quota_mb=float(quota),
                rejected=f'analytic exchange volume {mb:.3f} MB/shard '
                         f'exceeds max_exchange_mb={quota} — rejected '
                         'before the A/B epoch')
  if topology == 'tiered_dist':
    from ..storage.staging import pow2_slab_cap
    plan_fn = cfg.get('plan_fn')
    if plan_fn is not None:
      # caller-supplied planner hook (typically a closure over
      # storage.planner.plan_exchange on the real seed matrix): the
      # EXACT per-chunk miss volume this candidate would stage
      miss = int(plan_fn(dict(cand.knobs), int(chunk_k)))
    else:
      hot = int(cand.knobs.get('hot_prefix_rows') or 0)
      rows = int(cfg.get('rows_per_shard') or 0)
      hot_frac = min(1.0, hot / rows) if rows else 0.0
      miss = int(chunk_k * width * (1.0 - hot_frac))
    cap = pow2_slab_cap(max(1, miss))
    ev['planned_miss_rows'] = int(miss)
    ev['slab_cap'] = int(cap)
    quota = cfg.get('max_slab_rows')
    if quota is not None and cap > int(quota):
      ev.update(feasible=False, quota_rows=int(quota),
                rejected=f'planned slab cap {cap} rows overflows '
                         f'max_slab_rows={quota} — rejected before '
                         'the A/B epoch')
  if topology == 'remote' and feat_dim:
    from ..distributed.block_producer import block_mb_per_chunk
    node_cap = int(cfg.get('node_cap') or _node_budget(fanouts, batch))
    edge_cap = int(cfg.get('edge_cap') or
                   (_node_budget(fanouts, batch) - batch))
    ahead = int(cand.knobs.get('block_ahead') or 2)
    mb = block_mb_per_chunk(int(chunk_k), node_cap, edge_cap,
                            int(feat_dim),
                            cand.knobs.get('block_wire_dtype'))
    ev['block_mb_per_chunk'] = round(mb, 4)
    ev['inflight_mb'] = round(mb * ahead, 4)
    quota = cfg.get('max_block_mb')
    if quota is not None and mb * ahead > float(quota):
      ev.update(feasible=False, quota_mb=float(quota),
                rejected=f'{ahead} in-flight block(s) x {mb:.3f} MB '
                         f'exceed max_block_mb={quota} — rejected '
                         'before the A/B epoch')
  if not ev['feasible']:
    metrics.inc('tune.rejected')
  return bool(ev['feasible']), ev


# -------------------------------------------------------------- scoring


def score_scenario_candidate(cand: TopologyCandidate, topology: str,
                             make_scenario: Callable, chunk_k: int,
                             probe_steps: Optional[int]) -> dict:
  """Build one candidate's scenario and run its compile + steady
  epochs under the topology's program sites — the same record shape
  (and the same disqualify-on-steady-compile rule) as the local
  path's ``score_candidate``."""
  import jax
  sites = TOPOLOGY_SITES[topology]
  k = int(cand.chunk_k or chunk_k)
  steps = int(probe_steps or 2 * k)
  steps = max(k, (steps // k) * k)
  rec = dict(kind='candidate', name=cand.name, topology=topology,
             knobs=dict(cand.knobs), chunk_k=k,
             exact_semantics=cand.exact_semantics,
             probe_steps=steps)
  metrics.inc('tune.candidates')
  t_start = time.perf_counter()
  trainer = None
  try:
    with spans.span('tune.candidate', candidate=cand.name,
                    topology=topology, chunk_k=k):
      trainer, state = make_scenario(dict(cand.knobs), k)
      base = {s: programs.compile_count(s) for s in sites}
      # compile epoch: the executable population is built here
      state, losses, _ = trainer.run_epoch(state, max_steps=steps)
      jax.block_until_ready(losses)
      after_compile = {s: programs.compile_count(s) for s in sites}
      # steady epoch: the measured one — ANY compile here disqualifies
      t0 = time.perf_counter()
      state, losses, _ = trainer.run_epoch(state, max_steps=steps)
      jax.block_until_ready(losses)
      wall = time.perf_counter() - t0
      after_steady = {s: programs.compile_count(s) for s in sites}
      rec['compile_epoch_compiles'] = {
          s: after_compile[s] - base[s] for s in sites}
      steady = {s: after_steady[s] - after_compile[s] for s in sites}
      rec['steady_epoch_compiles'] = steady
      rec['wall_s'] = round(wall, 6)
      retraced = sum(steady.values()) > 0
      rec['qualified'] = not retraced
      if retraced:
        site = max(steady, key=steady.get)
        ev = programs.last_compile(site)
        rec['rejected'] = (
            f'steady-state epoch compiled {sum(steady.values())} '
            f'program(s) — a tuned config must dispatch a CLOSED '
            'executable set')
        rec['retrace_diff'] = ev.diff if ev is not None else None
        metrics.inc('tune.rejected')
      if programs.cost_enabled():
        ev = programs.last_compile(sites[1])
        if ev is not None and ev.cost and 'error' not in ev.cost:
          rec['cost'] = dict(
              flops=ev.cost.get('flops'),
              peak_hbm_bytes=ev.cost.get('peak_hbm_bytes'))
  except Exception as e:  # a broken candidate is evidence, not a crash
    rec['qualified'] = False
    rec['rejected'] = f'{type(e).__name__}: {e}'[:300]
    metrics.inc('tune.rejected')
  finally:
    for fin in ('shutdown', 'close'):
      fn = getattr(trainer, fin, None)
      if fn is not None:
        try:
          fn()
        except Exception:  # noqa: BLE001 - teardown must not mask the score
          pass
        break
  metrics.observe('tune.probe_ms',
                  (time.perf_counter() - t_start) * 1e3)
  return rec


def _budget_ladder(records: List[dict], pending: List, budget_s: float,
                   first_wall: float) -> Tuple[List, dict]:
  """Tune-the-tuner: truncate the remaining candidate ladder to what
  an explicit wall-clock budget affords, using the FIRST scored
  candidate's measured wall as the per-candidate unit. The evidence
  record makes the truncation loud — a budget-bounded tune says which
  candidates it never fielded (docs/tuning.md 'Budgeted tuning')."""
  per = max(first_wall, 1e-6)
  afford = max(0, int(budget_s / per) - len(records))
  kept, dropped = pending[:afford], pending[afford:]
  ev = dict(kind='budget', budget_s=float(budget_s),
            per_candidate_wall_s=round(per, 6),
            scored=len(records), kept=[c.name for c in kept],
            dropped=[c.name for c in dropped])
  return kept, ev


def tune_topology(topology: str, dataset, loader_cfg: Dict, *,
                  exact: bool = False,
                  candidates: Optional[Sequence[TopologyCandidate]] = None,
                  probe_steps: Optional[int] = None,
                  budget_s: Optional[float] = None,
                  out_path: Optional[str] = None) -> TuneArtifact:
  """tune() for a distributed topology (module docstring;
  dispatched from :func:`graphlearn_tpu.tune.tune` via
  ``topology='dist'|'remote'|'tiered_dist'``).

  ``loader_cfg`` must carry ``make_scenario(knobs, chunk_k) ->
  (trainer, state)`` plus ``fanouts`` and ``batch_size``; optional
  keys feed the feasibility analytics (``feat_dim``,
  ``num_partitions``, ``rows_per_shard`` / ``plan_fn``, ``node_cap``/
  ``edge_cap``) and quotas (``max_exchange_mb``, ``max_block_mb``,
  ``max_slab_rows``). ``epoch_steps`` (or ``input_nodes``) sizes the
  chunk-K probe."""
  from .tuner import _pick_winner
  if topology not in TOPOLOGY_SITES or topology == 'local':
    raise ValueError(
        f'unknown tune topology {topology!r} — the scenario set is '
        f"closed ({sorted(TOPOLOGY_SITES)}; 'local' takes the "
        'homo-scan path, docs/tuning.md)')
  cfg = dict(loader_cfg)
  make_scenario = cfg.get('make_scenario')
  if not callable(make_scenario):
    raise ValueError(
        f"tune(topology={topology!r}) needs loader_cfg"
        "['make_scenario'](knobs, chunk_k) -> (trainer, state): the "
        'scenario knobs are store-construction parameters, so every '
        'candidate is a freshly built scenario (docs/tuning.md '
        '"Topology candidates")')
  if 'fanouts' not in cfg or 'batch_size' not in cfg:
    raise ValueError("loader_cfg needs 'fanouts' and 'batch_size' — "
                     'they pin the artifact choices and size the '
                     'feasibility analytics')
  evidence: List[dict] = []
  with spans.span('tune.run', topology=topology, exact=exact):
    if 'epoch_steps' in cfg:
      steps = int(cfg['epoch_steps'])
    elif 'input_nodes' in cfg:
      inp = cfg['input_nodes']
      if isinstance(inp, tuple) and len(inp) == 2 and \
          isinstance(inp[0], str):
        inp = inp[1]  # typed seeds: ('ntype', ids)
      steps = probes.epoch_steps(
          np.asarray(inp).reshape(-1).shape[0],
          int(cfg['batch_size']), bool(cfg.get('drop_last', False)))
    else:
      steps = 2 * probes.CHUNK_K_LADDER[-1]
    chunk_k, ev = probes.probe_chunk_k(steps)
    evidence.append(ev)
    fp = dataset_fingerprint(dataset)
    if fp is None:
      # structured fingerprint-gap record (satellite of ROADMAP item
      # 3): the artifact says OUT LOUD that no dataset identity could
      # be computed, so an unvalidated acceptance downstream is a
      # recorded fact, not a silent one
      evidence.append(dict(
          kind='fingerprint_gap', topology=topology,
          dataset_type=type(dataset).__name__,
          note='dataset has no computable fingerprint — config= '
               'acceptors will warn instead of validating '
               '(docs/tuning.md "Fingerprints")'))
    cands = list(candidates) if candidates is not None \
        else default_topology_candidates(topology, cfg, exact)
    if exact:
      dropped = [c.name for c in cands if not c.exact_semantics]
      cands = [c for c in cands if c.exact_semantics]
      if dropped:
        evidence.append(dict(
            kind='exact_pin', dropped_candidates=dropped,
            note='exact=True pins the accuracy-matrix exact set'))
    feasible: List[TopologyCandidate] = []
    for cand in cands:
      ok, ev = screen_candidate(topology, cand,
                                int(cand.chunk_k or chunk_k), cfg)
      evidence.append(ev)
      if ok:
        feasible.append(cand)
      else:
        evidence.append(dict(kind='candidate', name=cand.name,
                             topology=topology, knobs=dict(cand.knobs),
                             qualified=False,
                             rejected=ev.get('rejected')))
    if not feasible:
      raise RuntimeError(
          f'tune(topology={topology!r}): every candidate screened '
          'infeasible against the configured quotas — see the '
          'feasibility evidence records')
    records: List[dict] = []
    pending = list(feasible)
    while pending:
      cand = pending.pop(0)
      records.append(score_scenario_candidate(
          cand, topology, make_scenario, chunk_k, probe_steps))
      if budget_s is not None and len(records) == 1 and pending:
        pending, ev = _budget_ladder(records, pending, budget_s,
                                     records[0].get('wall_s') or 0.0)
        evidence.append(ev)
    evidence.extend(records)
    best = _pick_winner(records)
    knobs = best.get('knobs') or {}
    evidence.append(dict(kind='winner', name=best['name'],
                         topology=topology, wall_s=best['wall_s'],
                         tie_break=best.get('tie_break', 'wall'),
                         knobs=dict(knobs)))
    choices = dict(
        mode='map',
        frontier_caps=cfg.get('frontier_caps'),
        padded_window=None,
        wire_dtype=knobs.get('wire_dtype'),
        chunk_k=int(best['chunk_k']),
        split_ratio=knobs.get('split_ratio'),
        bucket_frac=knobs.get('bucket_frac'),
        slab_cap=knobs.get('slab_cap'),
        serving_buckets=None,
        batch_size=int(cfg['batch_size']),
        fanouts=_choice_fanouts(cfg['fanouts']),
        exact=bool(exact),
        topology=topology,
        hot_prefix_rows=knobs.get('hot_prefix_rows'),
        block_ahead=knobs.get('block_ahead'),
        block_wire_dtype=knobs.get('block_wire_dtype'))
    art = TuneArtifact(choices, fp, evidence)
  metrics.inc('tune.artifacts')
  if out_path is not None:
    art.save(out_path)
  return art
