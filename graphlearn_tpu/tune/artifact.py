"""The tuned-config artifact: a versioned, fingerprinted, evidence-
carrying JSON record of every fast-path knob `tune()` chose.

The fast path spans ~10 coupled knobs (dedup mode, frontier caps,
padded window, cache split, wire dtype, scan chunk K, staging slab
caps, serving buckets). An artifact pins one consistent assignment of
ALL of them, together with:

* a **dataset fingerprint** (node/edge counts, feature dim, a sha1 of
  the degree sequence) — the constructors that accept a ``config=``
  artifact (ScanTrainer / DistScanTrainer / TieredScanTrainer /
  ServingEngine) refuse a drifted dataset by fingerprint, the same
  loud-refusal contract the recovery snapshots use for drifted
  configs (docs/recovery.md);
* an **evidence log**: for every knob, the probe that chose it and the
  measured values behind the choice — including the observatory
  verdict on each candidate A/B (a candidate whose steady-state epoch
  retraced is recorded as rejected WITH the signature diff naming the
  drifted argument, metrics/programs.py);
* a whole-artifact sha1 **fingerprint** over (version, dataset,
  choices) so two artifacts are comparable at a glance and a
  hand-edited one is self-evidently no longer the tuner's.

The artifact is plain JSON (docs/tuning.md documents the schema):
ship it with the model checkpoint, load it anywhere, and every
constructor lands on the same program population.
"""
import hashlib
import json
from typing import Any, Dict, List, Optional

import numpy as np

#: bump when the schema changes shape (loaders refuse unknown versions;
#: versions 1/2 — pre-kernel-routing / pre-topology — load with the
#: documented defaults via the per-version upgrade path below)
ARTIFACT_VERSION = 3

#: version 1's closed knob set — a v1 file is validated against THIS
#: set (and its own version-1 fingerprint) before the upgrade path
#: fills in the kernel-routing keys it predates
_V1_CHOICE_KEYS = frozenset({
    'mode', 'frontier_caps', 'padded_window', 'wire_dtype', 'chunk_k',
    'split_ratio', 'bucket_frac', 'slab_cap', 'serving_buckets',
    'batch_size', 'fanouts', 'exact',
})

#: the kernel-routing knobs added in schema version 2 (docs/tuning.md
#: 'Kernel candidates'): which Pallas fast paths the observatory A/Bs
#: selected, and their grid points (benchmarks/prof_gather2.py space)
KERNEL_CHOICE_KEYS = frozenset({
    'use_pallas_v2', 'gather2_block_rows', 'gather2_run_span',
    'use_fused_hop', 'fused_hop_window',
})

#: the defaults a choices dict missing kernel keys (hand-built, or a
#: version-1 artifact on the upgrade path) is completed with: KERNELS
#: OFF — routing a kernel in is an evidence-backed choice, never an
#: implicit one
KERNEL_CHOICE_DEFAULTS = {
    'use_pallas_v2': False, 'gather2_block_rows': 256,
    'gather2_run_span': 8, 'use_fused_hop': False,
    'fused_hop_window': 512,
}

#: version 2's closed knob set — v1 plus kernel routing; a v2 file is
#: validated against THIS set (and its own version-2 fingerprint)
#: before the upgrade path fills in the topology keys it predates
_V2_CHOICE_KEYS = _V1_CHOICE_KEYS | KERNEL_CHOICE_KEYS

#: the per-topology knobs added in schema version 3 (docs/tuning.md
#: 'Topology candidates'): which trainer scenario the artifact was
#: tuned FOR, plus the scenario knobs only that topology consumes
#: (remote block streams, tiered hot prefix)
TOPOLOGY_CHOICE_KEYS = frozenset({
    'topology', 'hot_prefix_rows', 'block_ahead', 'block_wire_dtype',
})

#: defaults for a choices dict missing topology keys (hand-built, or a
#: version-1/2 artifact on the upgrade path): a LOCAL artifact — the
#: pre-v3 tuner only ever scored the homo local-scan path, so that is
#: exactly what an upgraded file's choices were measured on
TOPOLOGY_CHOICE_DEFAULTS = {
    'topology': 'local', 'hot_prefix_rows': None, 'block_ahead': None,
    'block_wire_dtype': None,
}

#: the knob set every artifact carries (docs/tuning.md knob table) —
#: a choices dict is validated against this closed set on load
CHOICE_KEYS = _V2_CHOICE_KEYS | TOPOLOGY_CHOICE_KEYS

#: each schema version's own closed knob set — from_json validates a
#: file against ITS version's set (and its own fingerprint) before any
#: upgrade fills in the keys that version predates
_VERSION_CHOICE_KEYS = {
    1: _V1_CHOICE_KEYS,
    2: _V2_CHOICE_KEYS,
    3: CHOICE_KEYS,
}


def _csr_fingerprint(graph) -> Optional[Dict[str, Any]]:
  """Identity of ONE CSR (local Graph/Topology or stacked DistGraph):
  shape counts plus a sha1 of the degree sequence — host-side arrays
  only, never a device fetch (the calibrate.py convention)."""
  src = getattr(graph, 'topo', graph)
  indptr = getattr(src, 'indptr', None)
  if indptr is None:
    return None
  indptr = np.asarray(indptr, np.int64)
  if indptr.ndim == 2:
    # stacked sharded partitions (distributed DistGraph, [P, r_max+1]):
    # fingerprint the per-shard degree sequences plus the partition
    # book — the identity a dist/tiered topology artifact is tuned FOR
    # (a repartition or a node-ownership change both shift the
    # exchange volumes every dist knob was measured against)
    deg = np.diff(indptr, axis=1)
    fp = dict(
        num_partitions=int(indptr.shape[0]),
        degree_sha1=hashlib.sha1(
            np.ascontiguousarray(deg).tobytes()).hexdigest()[:16])
    node_pb = getattr(graph, 'node_pb', None)
    if node_pb is not None and not isinstance(node_pb, dict):
      node_pb = np.asarray(node_pb, np.int64)
      fp['num_nodes'] = int(node_pb.shape[0])
      fp['node_pb_sha1'] = hashlib.sha1(
          np.ascontiguousarray(node_pb).tobytes()).hexdigest()[:16]
    return fp
  deg = np.diff(indptr)
  fp = dict(
      num_nodes=int(indptr.shape[0] - 1),
      num_edges=int(indptr[-1]),
      degree_sha1=hashlib.sha1(
          np.ascontiguousarray(deg).tobytes()).hexdigest()[:16])
  indices = getattr(src, 'indices', None)
  if indices is not None:
    # degree sequences alone can collide (a regular graph rewires
    # without changing any degree) — fold in a deterministic strided
    # sample of the adjacency targets, bounded at ~1M entries so the
    # fingerprint stays O(1M) work at any graph scale
    idx = np.asarray(indices)
    stride = max(1, idx.shape[0] // 1_000_000)
    fp['edges_sha1'] = hashlib.sha1(
        np.ascontiguousarray(idx[::stride].astype(np.int64))
        .tobytes()).hexdigest()[:16]
  return fp


def _feature_dim(store) -> Optional[int]:
  fdim = getattr(store, 'feature_dim', None)
  if fdim is not None:
    return int(fdim)
  shape = getattr(store, 'shape', None)
  if shape is not None and len(shape) > 1:
    return int(shape[1])
  return None


def _hetero_fingerprint(dataset, graph) -> Optional[Dict[str, Any]]:
  """Typed dataset identity: one per-etype CSR fingerprint (local dict
  graphs and DistHeteroGraph sub-CSRs alike) plus per-ntype partition
  books and feature dims — the identity a hetero CapacityPlan's closed
  shapes are derived from (docs/capacity_plans.md)."""
  from ..typing import as_str
  subs = graph if isinstance(graph, dict) else \
      getattr(graph, 'sub', None)
  if not subs:
    return None
  etypes = {}
  for et in sorted(subs, key=str):
    sub_fp = _csr_fingerprint(subs[et])
    if sub_fp is not None:
      etypes[as_str(et) if isinstance(et, tuple) else str(et)] = sub_fp
  if not etypes:
    return None
  fp: Dict[str, Any] = dict(hetero=True, etypes=etypes)
  node_pb = getattr(graph, 'node_pb', None)
  if isinstance(node_pb, dict):
    fp['num_partitions'] = int(getattr(graph, 'num_partitions', 0))
    fp['num_nodes'] = {str(t): int(np.asarray(pb).shape[0])
                       for t, pb in sorted(node_pb.items())}
    fp['node_pb_sha1'] = {
        str(t): hashlib.sha1(
            np.ascontiguousarray(np.asarray(pb, np.int64))
            .tobytes()).hexdigest()[:16]
        for t, pb in sorted(node_pb.items())}
  feats = getattr(dataset, 'node_features', None)
  if isinstance(feats, dict):
    dims = {str(t): _feature_dim(s) for t, s in sorted(feats.items())}
    dims = {t: d for t, d in dims.items() if d is not None}
    if dims:
      fp['feature_dim'] = dims
  return fp


def dataset_fingerprint(dataset) -> Optional[Dict[str, Any]]:
  """Identity of the graph a config was tuned FOR: shape counts plus a
  sha1 of the degree sequence per CSR (the host-side Topology arrays —
  never a device fetch, the calibrate.py convention). Hetero datasets
  (dict graphs, DistHeteroGraph) fingerprint TYPED: one record per
  edge type plus per-ntype partition books and feature dims, so a
  hetero artifact validates on load exactly like a homo one. Returns
  None only when the dataset carries no graph structure at all —
  validation then degrades to a warning, never a spurious refusal."""
  graph = getattr(dataset, 'graph', dataset)
  if graph is None:
    return None
  if isinstance(graph, dict) or getattr(graph, 'is_hetero', False):
    return _hetero_fingerprint(dataset, graph)
  fp = _csr_fingerprint(graph)
  if fp is None:
    return None
  feats = getattr(dataset, 'node_features', None)
  if feats is not None and not isinstance(feats, dict):
    fdim = _feature_dim(feats)
    if fdim is not None:
      fp['feature_dim'] = int(fdim)
  return fp


def _canonical(obj) -> str:
  return json.dumps(obj, sort_keys=True, separators=(',', ':'),
                    default=str)


def compute_fingerprint(version: int, dataset_fp: Optional[dict],
                        choices: dict) -> str:
  payload = dict(version=version, dataset=dataset_fp, choices=choices)
  return hashlib.sha1(_canonical(payload).encode()).hexdigest()


class TuneArtifact:
  """One tuned configuration + the evidence that chose it.

  Attributes:
    choices: the knob assignment (CHOICE_KEYS; docs/tuning.md table).
    dataset: the dataset fingerprint the config was tuned for.
    evidence: list of probe/candidate records — each names the knob(s)
      it informed, the measured values, and (for candidate A/Bs) the
      observatory verdict: compiles / retraces / the disqualifying
      signature diff / cost attribution / steady-state wall.
    fingerprint: sha1 over (version, dataset, choices).
  """

  def __init__(self, choices: Dict[str, Any],
               dataset: Optional[Dict[str, Any]] = None,
               evidence: Optional[List[dict]] = None):
    unknown = set(choices) - CHOICE_KEYS
    if unknown:
      raise ValueError(f'unknown choice keys {sorted(unknown)} — the '
                       f'artifact knob set is closed (docs/tuning.md)')
    self.version = ARTIFACT_VERSION
    self.choices = dict(choices)
    # kernel-routing and topology keys are part of the closed v3 set:
    # complete a partial dict with the documented defaults (kernels
    # off, local topology) so the fingerprint is a function of the
    # FULL assignment
    for key, default in KERNEL_CHOICE_DEFAULTS.items():
      self.choices.setdefault(key, default)
    for key, default in TOPOLOGY_CHOICE_DEFAULTS.items():
      self.choices.setdefault(key, default)
    topo = self.choices['topology']
    if topo not in ('local', 'dist', 'remote', 'tiered_dist'):
      raise ValueError(f'unknown topology {topo!r} — the artifact '
                       "topology set is closed ('local', 'dist', "
                       "'remote', 'tiered_dist'; docs/tuning.md)")
    self.dataset = dict(dataset) if dataset is not None else None
    self.evidence = list(evidence or [])
    self.fingerprint = compute_fingerprint(self.version, self.dataset,
                                           self.choices)

  # ------------------------------------------------------------- (de)ser

  def to_json(self) -> dict:
    return dict(version=self.version, fingerprint=self.fingerprint,
                dataset=self.dataset, choices=self.choices,
                evidence=self.evidence)

  @classmethod
  def from_json(cls, obj: dict) -> 'TuneArtifact':
    v = obj.get('version')
    if v not in _VERSION_CHOICE_KEYS:
      raise ValueError(f'unsupported tune-artifact version {v!r} '
                       f'(this build reads versions '
                       f'{sorted(_VERSION_CHOICE_KEYS)})')
    stored = obj.get('fingerprint')
    if v < ARTIFACT_VERSION:
      # older-schema artifact: validate against ITS OWN closed knob
      # set and its own-version fingerprint (the file must still be
      # the tuner's, untouched), then upgrade — the keys it predates
      # load as the documented defaults (kernels off for v1, local
      # topology for v1/v2; docs/tuning.md 'Artifact schema'), never
      # as a refusal
      choices = dict(obj['choices'])
      unknown = set(choices) - _VERSION_CHOICE_KEYS[v]
      if unknown:
        raise ValueError(f'unknown choice keys {sorted(unknown)} — the '
                         f'version-{v} artifact knob set is closed '
                         '(docs/tuning.md)')
      if stored is not None:
        expect = compute_fingerprint(v, obj.get('dataset'), choices)
        if stored != expect:
          raise ValueError(
              f'tune-artifact fingerprint mismatch: stored {stored}, '
              f'recomputed {expect} — the file was edited after the '
              'tuner emitted it; re-run tune() instead of hand-patching '
              'a signed artifact (docs/tuning.md)')
      art = cls(choices, obj.get('dataset'), obj.get('evidence'))
      art.evidence.append(dict(
          kind='schema_upgrade', from_version=v,
          to_version=ARTIFACT_VERSION,
          note=('pre-kernel-routing artifact: kernel choices defaulted '
                'to off, topology to local (docs/tuning.md)' if v == 1
                else
                'pre-topology artifact: topology defaulted to local — '
                'the only scenario the v2 tuner scored '
                '(docs/tuning.md)')))
      return art
    art = cls(obj['choices'], obj.get('dataset'),
              obj.get('evidence'))
    if stored is not None and stored != art.fingerprint:
      raise ValueError(
          f'tune-artifact fingerprint mismatch: stored {stored}, '
          f'recomputed {art.fingerprint} — the file was edited after '
          'the tuner emitted it; re-run tune() instead of hand-patching '
          'a signed artifact (docs/tuning.md)')
    return art

  def save(self, path: str) -> str:
    with open(path, 'w') as f:
      json.dump(self.to_json(), f, indent=2, sort_keys=True)
      f.write('\n')
    return path

  @classmethod
  def load(cls, path: str) -> 'TuneArtifact':
    with open(path) as f:
      return cls.from_json(json.load(f))

  # ---------------------------------------------------------- validation

  def validate_dataset(self, dataset, where: str = 'config'):
    """Refuse a dataset that drifted from the one this config was
    tuned for — a tuned cap/cache/chunk assignment on a different
    graph silently loses the evidence behind every choice. Hetero
    datasets validate TYPED (per-etype CSR records, per-ntype books);
    degrades to a warning only when the dataset has no computable
    fingerprint at all (e.g. a remote client holding no graph)."""
    if self.dataset is None:
      return
    fp = dataset_fingerprint(dataset)
    if fp is None:
      import warnings
      warnings.warn(
          f'{where}: dataset has no computable fingerprint — tuned '
          'config accepted unvalidated', RuntimeWarning, stacklevel=3)
      return
    drift = {k: (self.dataset.get(k), fp.get(k))
             for k in set(self.dataset) | set(fp)
             if self.dataset.get(k) != fp.get(k)}
    if drift:
      raise ValueError(
          f'{where}: tuned-config dataset fingerprint mismatch '
          f'{drift} — this artifact was tuned for a different graph '
          '(artifact fingerprint '
          f'{self.fingerprint}); re-run graphlearn_tpu.tune() on the '
          'current dataset (docs/tuning.md)')

  # --------------------------------------------------------- constructor
  # accessors: the kwarg bundles the loader / trainer / serving
  # constructors consume (docs/tuning.md quickstart)

  def loader_kwargs(self) -> dict:
    """NeighborLoader kwargs for the chosen sampling mode."""
    mode = self.choices['mode']
    kw = dict(batch_size=self.choices['batch_size'], dedup=mode)
    if mode in ('map', 'sort', 'merge') and \
        self.choices.get('frontier_caps') is not None:
      # caps clamp the EXACT-dedup buffer plan; the relaxed tree mode
      # sizes its own computation-tree layout
      kw['frontier_caps'] = list(self.choices['frontier_caps'])
    if self.choices.get('padded_window') is not None:
      kw['padded_window'] = self.choices['padded_window']
    if self.choices.get('use_fused_hop'):
      # the tuned fused-hop kernel routing rides the loader flags
      # (sampler/neighbor_sampler.py use_fused_hop) — off stays absent
      # so pre-kernel loaders see an unchanged kwarg surface
      kw['use_fused_hop'] = self.choices['use_fused_hop']
      kw['fused_hop_window'] = int(
          self.choices.get('fused_hop_window',
                           KERNEL_CHOICE_DEFAULTS['fused_hop_window']))
    return kw

  def kernel_kwargs(self) -> dict:
    """The tuned kernel-routing bundle (KERNEL_CHOICE_KEYS): which
    Pallas fast paths the observatory A/Bs selected. Kernels default
    off — a key absent from an older choices dict reads as off."""
    return {k: self.choices.get(k, KERNEL_CHOICE_DEFAULTS[k])
            for k in KERNEL_CHOICE_KEYS}

  def apply_kernel_routing(self, target) -> bool:
    """Stamp the tuned gather-kernel routing onto ``target``'s feature
    / embedding store (the ``config=`` acceptors call this so kernel
    selection is an artifact choice, not an env var). Returns True
    when at least one store accepted the flags."""
    return apply_kernel_routing(target, self.kernel_kwargs())

  @property
  def topology(self) -> str:
    """Which trainer scenario this artifact was tuned for ('local' /
    'dist' / 'remote' / 'tiered_dist'). The ``config=`` acceptors
    refuse a mismatched non-local topology — a remote block-stream
    assignment says nothing about a tiered exchange (docs/tuning.md
    'Topology candidates')."""
    return self.choices.get('topology') or 'local'

  def topology_kwargs(self) -> dict:
    """The tuned scenario knobs only this artifact's topology consumes
    (TOPOLOGY_CHOICE_KEYS minus the topology tag itself), Nones
    dropped: ``block_ahead``/``block_wire_dtype`` for remote block
    streams, ``hot_prefix_rows`` for the tiered exchange."""
    out = {k: self.choices.get(k)
           for k in TOPOLOGY_CHOICE_KEYS if k != 'topology'}
    return {k: v for k, v in out.items() if v is not None}

  def trainer_kwargs(self) -> dict:
    """Scan-trainer kwargs (chunk K); the trainers also re-validate the
    dataset fingerprint when handed the artifact via ``config=``."""
    return dict(chunk_size=int(self.choices['chunk_k']))

  def serving_kwargs(self) -> dict:
    """ServingEngine kwargs (the calibrated padded-bucket ladder)."""
    return dict(buckets=tuple(self.choices['serving_buckets']))


def apply_kernel_routing(target, kernel: Optional[dict] = None) -> bool:
  """Route the chosen gather kernel into every store hanging off
  ``target`` that understands ``set_kernel_routing`` (data.Feature /
  storage.TieredFeature via their UnifiedTensor, serving's
  EmbeddingStore). ``target`` may be a Dataset (its ``node_features``
  are walked, hetero dicts included), a feature store, or an embedding
  store. Keys absent from ``kernel`` fall back to the kernels-off
  defaults, so applying is idempotent AND resets flags a previous
  candidate probe set (tune/tuner.py scores candidates in sequence
  over one dataset)."""
  kw = dict(KERNEL_CHOICE_DEFAULTS)
  kw.update({k: v for k, v in (kernel or {}).items() if v is not None})
  stores = getattr(target, 'node_features', target)
  if not isinstance(stores, dict):
    stores = {None: stores}
  applied = False
  for store in stores.values():
    if hasattr(store, 'set_kernel_routing'):
      store.set_kernel_routing(
          use_pallas_v2=bool(kw['use_pallas_v2']),
          block_rows=int(kw['gather2_block_rows']),
          run_span=int(kw['gather2_run_span']))
      applied = True
  return applied
