"""Workload probes behind `tune()`: each returns (value, evidence).

Every probe is HOST-side numpy over the CSR topology (the
sampler/calibrate.py discipline: no device work, no jit, no
device->host fetches — safe on remote-dispatch runtimes) and returns
both the chosen value and an evidence record naming what was measured,
so the artifact can answer "why this cap / split / K" from the record
alone. The device-measured half of tuning — the observatory-scored
candidate A/Bs — lives in tuner.py.

Probe inventory (docs/tuning.md knob table):

* frontier caps     -> sampler.calibrate.estimate_frontier_caps
* cache split       -> in-degree hotness mass coverage (data/reorder's
                       hotness estimator: what fraction of expected
                       accesses the hottest rows absorb)
* scan chunk K      -> divisor-preferring ladder over the epoch's step
                       count (fewest chunk-length executables first,
                       dispatch count second)
* staging slab cap  -> pow2 of the planned per-chunk miss volume
                       (storage/staging.py's closed-shape convention)
* serving buckets   -> pow2 ladder under the calibrated batch cap
"""
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..sampler import calibrate
from ..storage.staging import pow2_slab_cap

#: candidate chunk sizes, largest preferred (fewer dispatches) — the
#: ladder the divisor rule walks (docs/tuning.md)
CHUNK_K_LADDER = (64, 32, 16, 8, 4)

#: default serving-bucket ladder seed (serving/engine.py
#: DEFAULT_BUCKETS) — the probe extends it to cover the batch cap
SERVING_BUCKET_BASE = (16, 64, 256)


def probe_frontier_caps(graph, fanouts: Sequence[int], batch_size: int,
                        input_nodes=None, num_probes: int = 8,
                        slack: float = 1.5, seed: int = 0
                        ) -> Tuple[List[int], dict]:
  """Calibrated per-hop post-dedup caps (the existing probe, evidence-
  wrapped): worst-case static plan vs measured caps, so the artifact
  records how much buffer the calibration actually bought."""
  caps = calibrate.estimate_frontier_caps(
      graph, fanouts, batch_size, input_nodes=input_nodes,
      num_probes=num_probes, slack=slack, seed=seed)
  worst = [batch_size]
  for k in fanouts:
    worst.append(worst[-1] * k)
  worst = worst[1:]
  evidence = dict(
      knob='frontier_caps', probe='estimate_frontier_caps',
      value=list(caps), worst_case_plan=worst,
      num_probes=num_probes, slack=slack,
      plan_reduction_x=round(float(sum(worst)) / max(1, sum(caps)), 2))
  return list(caps), evidence


def probe_cache_split(graph, num_nodes: int, coverage: float = 0.75,
                      max_split: float = 0.5
                      ) -> Tuple[float, float, dict]:
  """(split_ratio, bucket_frac, evidence): the smallest hot fraction
  whose in-degree hotness mass reaches ``coverage`` of expected
  accesses (DCI's workload-aware allocation, arxiv 2503.01281, on the
  one signal a static graph gives us: in-degree ~ access frequency
  under uniform seed draws). bucket_frac then sizes the miss-exchange
  packing at the UNCOVERED mass plus slack — a hot split that absorbs
  more hits needs a narrower wire."""
  from ..data.reorder import in_degree_hotness
  hot = np.asarray(in_degree_hotness(
      getattr(graph, 'topo', graph), num_nodes), np.float64)
  total = float(hot.sum())
  if total <= 0:
    evidence = dict(knob='split_ratio', probe='in_degree_hotness',
                    value=0.0, note='degenerate graph (no edges)')
    return 0.0, 1.0, evidence
  mass = np.cumsum(np.sort(hot)[::-1]) / total
  # smallest prefix fraction reaching the coverage target, clamped to
  # max_split (a cache past half the table stops being a cache); the
  # covered mass is read AT THE CLAMPED prefix — bucket_frac must size
  # the miss wire for what the chosen split actually absorbs, not for
  # the coverage an unclamped split would have reached
  idx = int(np.searchsorted(mass, coverage)) + 1
  idx = max(1, min(idx, num_nodes, int(max_split * num_nodes) or 1))
  split = idx / num_nodes
  covered = float(mass[idx - 1])
  bucket_frac = round(min(1.0, max(0.25, (1.0 - covered) * 1.5)), 2)
  evidence = dict(
      knob='split_ratio', probe='in_degree_hotness',
      value=round(float(split), 4), coverage_target=coverage,
      coverage_at_split=round(covered, 4),
      bucket_frac=bucket_frac,
      note='bucket_frac = clamp(1.5 x uncovered access mass)')
  return round(float(split), 4), bucket_frac, evidence


def probe_chunk_k(steps: int, ladder: Sequence[int] = CHUNK_K_LADDER
                  ) -> Tuple[int, dict]:
  """Scan chunk K: prefer the largest ladder K that DIVIDES the epoch
  (one chunk-length executable, fewest dispatches); otherwise the
  largest K whose tail chunk is the only extra executable. K is the
  dispatch-count lever — ceil(steps/K)+2 — but every distinct chunk
  length compiles once, so divisibility outranks raw size."""
  steps = max(1, int(steps))
  fits = [k for k in ladder if k <= steps]
  if not fits:
    choice, why = steps, 'epoch shorter than the ladder: one chunk'
  else:
    divisors = [k for k in fits if steps % k == 0]
    if divisors:
      choice = divisors[0]
      why = f'largest ladder divisor of {steps} steps (one executable)'
    else:
      choice = fits[0]
      why = (f'no ladder divisor of {steps} steps; largest K with one '
             'tail executable')
  evidence = dict(
      knob='chunk_k', probe='divisor_ladder', value=int(choice),
      steps=steps, ladder=list(ladder),
      dispatches=-(-steps // choice) + 2, why=why)
  return int(choice), evidence


def probe_slab_cap(chunk_k: int, frontier_caps: Sequence[int],
                   batch_size: int, split_ratio: float
                   ) -> Tuple[int, dict]:
  """Staging slab capacity: pow2 of the planned per-chunk miss volume
  — chunk_k steps x the calibrated unique-node budget x the slice the
  hot split does NOT absorb (storage/staging.py pads slabs to pow2
  with INT32_MAX ids, so this is the closed-shape knob)."""
  node_budget = int(batch_size + sum(frontier_caps))
  miss = max(1, int(chunk_k * node_budget * (1.0 - split_ratio)))
  cap = pow2_slab_cap(miss)
  evidence = dict(
      knob='slab_cap', probe='planned_miss_volume', value=int(cap),
      per_step_node_budget=node_budget, chunk_k=int(chunk_k),
      split_ratio=split_ratio, planned_miss_rows=miss)
  return int(cap), evidence


def probe_serving_buckets(batch_size: int,
                          base: Sequence[int] = SERVING_BUCKET_BASE
                          ) -> Tuple[List[int], dict]:
  """Serving bucket ladder: the engine's default pow2-ish ladder
  extended until one bucket covers the training batch cap (an online
  request fan-in rarely exceeds the trained batch; oversize requests
  split at the largest cap — serving/engine.py)."""
  buckets = sorted(set(int(b) for b in base))
  top = buckets[-1]
  while top < batch_size:
    top *= 4
    buckets.append(top)
  evidence = dict(knob='serving_buckets', probe='batch_cap_ladder',
                  value=list(buckets), batch_size=int(batch_size))
  return buckets, evidence


def epoch_steps(num_seeds: int, batch_size: int,
                drop_last: bool = False) -> int:
  """The SeedBatcher step arithmetic, duplicated nowhere else."""
  if drop_last:
    return num_seeds // batch_size
  return -(-num_seeds // batch_size)


def wire_dtype_choice(exact: bool) -> Tuple[Optional[str], dict]:
  """bf16 wire is certified semantics-free for FEATURE payloads by the
  accuracy matrix (benchmarks/accuracy_matrix.py: precision delta
  only, bounded by bf16 rounding of inputs) — chosen unless the caller
  pinned the exact set."""
  value = None if exact else 'bf16'
  evidence = dict(
      knob='wire_dtype', probe='accuracy_matrix',
      value=value,
      note=('exact=True pins full-width f32 wire' if exact else
            'bf16 feature wire: accuracy-matrix-certified relaxation '
            '(benchmarks/accuracy_matrix.py)'))
  return value, evidence
