"""Continuous retuning: the drift-watching daemon that keeps a tuned
config current as the workload shifts (docs/tuning.md 'Continuous
retuning').

A :class:`TuneArtifact` pins knob choices to the workload they were
measured on; DCI (arxiv 2503.01281) shows those choices rot as the
graph and traffic drift. :class:`RetuneScheduler` is the
`serving.rotation.RotationScheduler` pattern applied to configs
instead of embeddings: a daemon thread polls the observatory's drift
signals, and when one fires it re-runs ``tune()`` on a SHADOW replica
— a caller-supplied ``shadow_tune_fn`` that must never touch the
serving/training program stream — then publishes the fresh artifact
through the same fingerprint-validated ``config=`` path everything
else uses.

Failure semantics mirror rotation's: a failed or crashed shadow retune
(chaos-tested with the ``tune.shadow_retune`` fault) leaves the
previously published config serving untouched — ``publish_fn`` is only
called with a successfully built artifact, and an exception anywhere
in the build/publish pair keeps ``current`` as it was. A drift probe
that RAISES counts as not-drifted: observability hooks must never
take the serving path down.

Triggers are **edge-latched**: a sustained condition fires its
retune once, then re-arms only after the probe reads False again
(falling edge). A FAILED retune re-arms the firing trigger
immediately, so a still-drifted condition retries on the next poll —
"exactly once per sustained condition" counts successful publishes.

Drift-probe factories for the three stock signals live here too:
retrace-budget overruns (``program.retrace_budget_exceeded``),
feature-cache hit-rate decay (``dist_feature.*``), and serving p99
creep (``serving.total_ms``).
"""
import logging
import threading
import time
from typing import Callable, Dict, Optional

from .. import metrics
from ..metrics import spans
from ..utils.faults import fault_point

logger = logging.getLogger('graphlearn_tpu.tune')


# ---------------------------------------------------------- drift probes


def retrace_overrun_probe() -> Callable[[], bool]:
  """Drifted when ``program.retrace_budget_exceeded`` ADVANCED since
  the last poll — a steady-state program population that starts
  compiling again is the observatory's own signal that the tuned
  shapes no longer fit the workload (metrics/programs.py
  ``retrace_budget``)."""
  src = metrics.counter('program.retrace_budget_exceeded')
  last = [src.value]

  def probe() -> bool:
    now = src.value
    grew = now > last[0]
    last[0] = now
    return grew

  return probe


def hit_rate_decay_probe(floor: float) -> Callable[[], bool]:
  """Drifted when the feature cache's hit rate over the lookups SINCE
  THE LAST POLL fell below ``floor`` — the cached hot set no longer
  matches the access distribution (the DCI drift signal, on the
  headline ``dist_feature.hits`` / ``dist_feature.misses`` counters
  ``publish_stats`` lands once per epoch)."""
  hits_c = metrics.counter('dist_feature.hits')
  miss_c = metrics.counter('dist_feature.misses')
  last = [hits_c.value, miss_c.value]

  def probe() -> bool:
    h, m = hits_c.value, miss_c.value
    dh, dm = h - last[0], m - last[1]
    last[0], last[1] = h, m
    total = dh + dm
    return total > 0 and (dh / total) < floor

  return probe


def p99_creep_probe(limit_ms: float,
                    min_count: int = 1) -> Callable[[], bool]:
  """Drifted when ``serving.total_ms``'s p99 sits above ``limit_ms``
  (with at least ``min_count`` observations — an empty histogram is
  not evidence). The serving tier's own SLO lens, reused as the
  retune trigger."""
  hist = metrics.histogram('serving.total_ms')

  def probe() -> bool:
    if hist.count < min_count:
      return False
    q = hist.quantile(0.99)
    return q is not None and q > limit_ms

  return probe


# ------------------------------------------------------------- scheduler


class RetuneScheduler:
  """Drives shadow retunes off observatory drift signals (module
  docstring; docs/tuning.md 'Continuous retuning').

  Args:
    shadow_tune_fn: ``() -> TuneArtifact`` — runs ``tune()`` on the
      SHADOW replica (a scenario factory over replica resources,
      never the serving/training stream) and returns the fresh
      artifact. Raising keeps the previous config published.
    publish_fn: ``(artifact) -> None`` — installs the artifact through
      the fingerprint-validated ``config=`` path (rebuild a trainer,
      swap a serving engine's config, write the artifact file an
      orchestrator watches). Only ever called with a successfully
      built artifact; raising keeps the previous config.
    triggers: ``{name: () -> bool}`` drift probes (the factories
      above, or any closure). At least one is required. Edge-latched;
      a raising probe counts as not-drifted.
    initial: the currently published artifact, if any — ``current``
      reads it until the first successful retune.
    poll_s: daemon poll cadence.
  """

  def __init__(self, shadow_tune_fn: Callable, publish_fn: Callable,
               triggers: Dict[str, Callable[[], bool]],
               initial=None, poll_s: float = 0.5):
    if not triggers:
      raise ValueError('RetuneScheduler needs at least one drift '
                       'trigger (docs/tuning.md "Continuous '
                       'retuning")')
    self.shadow_tune_fn = shadow_tune_fn
    self.publish_fn = publish_fn
    self.triggers = dict(triggers)
    self.poll_s = float(poll_s)
    self.current = initial       # last successfully PUBLISHED artifact
    self.retunes = 0             # successful shadow-retune publishes
    self.failures = 0            # failed attempts (previous config kept)
    self.last_error: Optional[str] = None
    self.last_trigger: Optional[str] = None
    self._latched = {name: False for name in self.triggers}
    self._stop = threading.Event()
    self._wake = threading.Event()   # stop/retune_now interrupt a poll
    self._thread: Optional[threading.Thread] = None

  _force = False

  # ------------------------------------------------------------ lifecycle

  def start(self) -> 'RetuneScheduler':
    if self._thread is not None and self._thread.is_alive():
      return self
    self._stop.clear()
    self._thread = threading.Thread(target=self._loop, daemon=True,
                                    name='glt-retune-scheduler')
    self._thread.start()
    return self

  def stop(self, timeout: float = 30.0):
    """Signal the loop to exit and join it. An in-flight shadow retune
    completes first — a publish is never abandoned half-installed."""
    self._stop.set()
    self._wake.set()
    t = self._thread
    if t is not None:
      t.join(timeout=timeout)
      if t.is_alive():
        raise TimeoutError(
            f'retune scheduler did not stop within {timeout}s (a '
            'shadow retune is still running; it will finish on the '
            'daemon thread)')
    self._thread = None

  def retune_now(self):
    """Force the next poll to retune regardless of drift signals."""
    self._force = True
    self._wake.set()

  # ----------------------------------------------------------------- loop

  def _fired(self) -> Optional[str]:
    """Poll every probe (all of them — falling edges must re-arm even
    while another trigger fires) and return the first NEWLY drifted
    trigger's name, edge-latched."""
    fired = None
    for name, probe in self.triggers.items():
      try:
        drifted = bool(probe())
      except Exception:  # noqa: BLE001 - a broken probe must not fire a retune
        drifted = False
        logger.exception('retune drift probe %r raised — treating as '
                         'not-drifted', name)
      if drifted:
        if not self._latched[name] and fired is None:
          self._latched[name] = True
          fired = name
      else:
        self._latched[name] = False   # falling edge re-arms
    return fired

  def _attempt(self, trigger: str):
    metrics.inc('tune.drift_triggers')
    t0 = time.perf_counter()
    try:
      with spans.span('tune.retune', trigger=trigger):
        # chaos seam: a killed/crashed shadow retune must leave the
        # live config untouched (tests/test_retune.py arms this)
        fault_point('tune.shadow_retune')
        art = self.shadow_tune_fn()
        self.publish_fn(art)
      # state flips only AFTER a successful build+publish pair — any
      # exception above leaves `current` exactly as it was
      self._force = False
      self.current = art
      self.retunes += 1
      self.last_error = None
      self.last_trigger = trigger
      metrics.inc('tune.retunes')
      metrics.observe('tune.shadow_wall_ms',
                      (time.perf_counter() - t0) * 1e3)
    except Exception as e:  # noqa: BLE001 - degrade, keep previous config
      self.failures += 1
      self.last_error = f'{type(e).__name__}: {e}'
      if trigger in self._latched:
        # a still-drifted condition should retry on the next poll —
        # the once-per-sustained-condition guarantee counts
        # successful publishes, not attempts
        self._latched[trigger] = False
      logger.warning(
          'shadow retune (trigger %r) failed (%s) — previous config '
          'keeps serving; will retry while the drift persists',
          trigger, self.last_error)

  def _loop(self):
    while not self._stop.is_set():
      trigger = 'forced' if self._force else self._fired()
      if trigger is not None:
        self._attempt(trigger)
      self._wake.wait(self.poll_s)
      self._wake.clear()
