"""`tune()`: one call from (dataset, loader_cfg) to a validated
fast-path config artifact.

Landing on the fast path today means hand-picking ~10 coupled knobs
(dedup mode, frontier caps, cache split, wire dtype, scan chunk K,
slab caps, serving buckets). This module automates the choice the way
GNNSampler (arxiv 2108.11571) argues samplers should be configured —
workload-aware and hardware-matched — using machinery the repo
already trusts:

1. **Host probes** (tune/probes.py): the calibration simulation for
   frontier caps, in-degree hotness mass for the cache split, the
   divisor ladder for chunk K, planned miss volume for slab caps.
2. **Observatory-scored candidate A/Bs**: each candidate sampling
   mode runs a short ScanTrainer epoch twice — a compile epoch, then
   a steady-state epoch. The program observatory
   (metrics/programs.py) watches every dispatch site: a candidate
   whose STEADY epoch compiles anything is disqualified BY
   CONSTRUCTION, and the rejection records the signature diff naming
   the drifted argument. Qualified candidates rank by steady-state
   wall; under ``GLT_PROGRAM_COST=1`` near-ties (within
   ``COST_TIE_MARGIN``) break on XLA cost attribution (flops, then
   peak HBM) — on CPU replicas, where device wall is a weak signal,
   the cost tie-break is the sharper lens.
3. **Semantics**: the accuracy matrix (benchmarks/accuracy_matrix.py)
   certifies which relaxations are exact-equivalent. ``exact=True``
   pins the exact set — calibrated exact dedup, f32 wire — and only
   A/Bs within it; the default also fields the certified relaxations
   (tree dedup, bf16 wire).

The result is a :class:`~graphlearn_tpu.tune.artifact.TuneArtifact`
(JSON on disk via ``out_path=``) that the trainer / serving
constructors accept directly via ``config=`` (docs/tuning.md).
"""
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import metrics
from ..metrics import programs, spans
from . import probes
from .artifact import (KERNEL_CHOICE_DEFAULTS, TuneArtifact,
                       apply_kernel_routing, dataset_fingerprint)

#: wall ratio under which two qualified candidates count as tied and
#: the GLT_PROGRAM_COST attribution (flops, then peak HBM) breaks the
#: tie — device wall on a CPU replica is noisy at exactly this margin
COST_TIE_MARGIN = 0.05

#: the program sites a local scanned candidate dispatches through —
#: the population the "one executable per site" acceptance counts
CANDIDATE_SITES = ('epoch_seeds', 'scan_chunk', 'metrics_concat')

#: the gather-v2 autotune space (benchmarks/prof_gather2.py's full
#: grid) the kernel candidate field draws its grid points from —
#: a point outside the profiled space would be an unmeasured claim
GATHER2_GRID_BLOCKS = (64, 128, 256, 512)
GATHER2_GRID_SPANS = (1, 4, 8, 16, 32)

#: default kernel-routing grid points fielded per base candidate
#: (docs/tuning.md 'Kernel candidates'): the prof_gather2 default
#: (256, 8) plus the small-block point that wins on short runs
DEFAULT_GATHER2_POINTS = ((256, 8), (128, 4))

#: default fused-hop window variants fielded (off is the base
#: candidate itself; windows must be 128-lane multiples)
DEFAULT_FUSED_HOP_WINDOWS = (512,)


class Candidate:
  """One sampling-mode candidate for the observatory A/B.

  Args:
    name: evidence-log label.
    loader_kwargs: NeighborLoader overrides (dedup, frontier_caps,
      padded_window, ...) layered over the shared loader_cfg.
    chunk_k: per-candidate chunk override (None = the probed K).
    exact_semantics: True when the candidate is bit-equivalent to
      exact dedup (the accuracy-matrix certification line).
    perturb_chunk: SELF-TEST knob — perturb the chunk length between
      the compile and steady epochs, forcing a steady-state retrace.
      This is how tests (and operators validating a deployment) prove
      the disqualification path is live: the candidate MUST be
      rejected with the signature diff in the evidence log.
    kernel: kernel-routing overrides (KERNEL_CHOICE_KEYS subset —
      use_pallas_v2 / gather2 grid point / use_fused_hop / window)
      applied to the dataset's feature store and loader flags for
      this candidate's epochs. Keys absent read as the kernels-off
      defaults, so scoring one candidate RESETS the previous
      candidate's routing.
  """

  def __init__(self, name: str, loader_kwargs: Dict,
               chunk_k: Optional[int] = None,
               exact_semantics: bool = True,
               perturb_chunk: bool = False,
               kernel: Optional[Dict] = None):
    self.name = name
    self.loader_kwargs = dict(loader_kwargs)
    self.chunk_k = chunk_k
    self.exact_semantics = exact_semantics
    self.perturb_chunk = perturb_chunk
    self.kernel = dict(kernel or {})


def retrace_probe_candidate(base: Candidate) -> Candidate:
  """A deliberately retracing copy of ``base`` — the live-fire check
  that the observatory scoring actually rejects a retracing config
  (tests/test_tune.py; docs/tuning.md 'The observatory scoring
  rule')."""
  return Candidate(f'{base.name}+retrace_probe', base.loader_kwargs,
                   chunk_k=base.chunk_k,
                   exact_semantics=base.exact_semantics,
                   perturb_chunk=True, kernel=base.kernel)


def kernel_candidates(base: Candidate,
                      gather2_points=DEFAULT_GATHER2_POINTS,
                      fused_hop_windows=DEFAULT_FUSED_HOP_WINDOWS
                      ) -> List[Candidate]:
  """Kernel-routing variants of ``base`` (docs/tuning.md 'Kernel
  candidates'): the fused sample+gather hop kernel at each window, and
  the run-segmented DMA gather v2 at each (block_rows, run_span) grid
  point from the prof_gather2 autotune space. Every variant is
  bit-identical to ``base`` (the kernels' parity contract), so
  ``exact_semantics`` carries over — only the program route differs,
  which is exactly what the observatory A/B measures. Off-TPU the
  kernels fall back to their XLA twins in-program, so a CPU-replica
  tune() scores them honestly (ties break toward ``base``: the
  stable sort prefers the earlier, kernels-off field entry)."""
  out = []
  for w in fused_hop_windows:
    if w % 128:
      raise ValueError(f'fused_hop window {w} must be a multiple of '
                       '128 (the lane width — ops/sample_fused.py)')
    out.append(Candidate(
        f'{base.name}+fused_hop_w{w}',
        dict(base.loader_kwargs, use_fused_hop=True,
             fused_hop_window=int(w)),
        chunk_k=base.chunk_k, exact_semantics=base.exact_semantics,
        kernel=dict(use_fused_hop=True, fused_hop_window=int(w))))
  for br, rs in gather2_points:
    if br not in GATHER2_GRID_BLOCKS or rs not in GATHER2_GRID_SPANS:
      raise ValueError(
          f'gather2 grid point ({br}, {rs}) is outside the profiled '
          f'autotune space {GATHER2_GRID_BLOCKS} x {GATHER2_GRID_SPANS} '
          '(benchmarks/prof_gather2.py)')
    out.append(Candidate(
        f'{base.name}+gather2_b{br}r{rs}', base.loader_kwargs,
        chunk_k=base.chunk_k, exact_semantics=base.exact_semantics,
        kernel=dict(use_pallas_v2=True, gather2_block_rows=int(br),
                    gather2_run_span=int(rs))))
  return out


def default_candidates(caps: List[int], exact: bool,
                       kernels: bool = True) -> List[Candidate]:
  """The stock candidate field: calibrated exact dedup always (first —
  the stable-sort tie-break baseline), the accuracy-matrix-certified
  tree relaxation unless ``exact=True`` pinned the exact set, then the
  kernel-routing variants of the calibrated base (``kernels=False``
  drops them for a probes-only field)."""
  base = Candidate('map_calibrated',
                   dict(dedup='map', frontier_caps=list(caps)),
                   exact_semantics=True)
  cands = [base]
  if not exact:
    cands.append(Candidate('tree', dict(dedup='tree'),
                           exact_semantics=False))
  if kernels:
    cands.extend(kernel_candidates(base))
  return cands


def _is_hetero_dataset(dataset) -> bool:
  """Typed-dataset dispatch for tune(): hetero datasets route to the
  typed candidate field (per-etype fanouts, RGNN proxy, hetero
  fingerprint — docs/capacity_plans.md) instead of the homo probe
  chain."""
  graph = getattr(dataset, 'graph', dataset)
  return isinstance(graph, dict) or \
      bool(getattr(graph, 'is_hetero', False)) or \
      isinstance(getattr(dataset, 'node_features', None), dict)


def hetero_fanout_candidates(fanouts: Dict) -> List:
  """The typed candidate field: the requested per-etype fanout dict as
  the base, plus one per-etype trimmed variant (that edge type's
  per-hop fanouts halved). Each variant changes exactly ONE type's
  closed shapes, so the A/B isolates which relation's frontier the
  wall is actually paying for (docs/tuning.md 'Hetero datasets')."""
  from ..typing import as_str
  base = {et: [int(k) for k in f] for et, f in fanouts.items()}
  out = [Candidate('typed_base', dict(fanouts=base))]
  for et in sorted(base, key=str):
    if max(base[et]) <= 1:
      continue  # nothing left to trim on this relation
    trimmed = {e: list(f) for e, f in base.items()}
    trimmed[et] = [max(1, k // 2) for k in base[et]]
    out.append(Candidate(f'trim_{as_str(et)}', dict(fanouts=trimmed)))
  return out


def _refuse_padded_candidates(cands: Sequence[Candidate]):
  """PR 15 residual (b), resolved as a loud refusal: a padded-window
  config cannot ride the whole-run program stream — the per-epoch
  padded-table reseed is a HOST-side adjacency rebuild
  (NodeLoader._begin_epoch), which RunTrainer refuses for exactly that
  reason (loader/run_epoch.py). An artifact tune() signed with
  padded_window set would therefore be accepted by the per-epoch
  trainers but refused by RunTrainer — a split this error documents
  instead of leaving silent."""
  bad = [c.name for c in cands
         if c.loader_kwargs.get('padded_window') is not None]
  if bad:
    raise ValueError(
        f'tune(): padded-window candidates {bad} are not tunable — '
        'the per-epoch padded-table reseed is a host-side adjacency '
        'rebuild that cannot fold into the whole-run program stream, '
        'so RunTrainer(config=) would refuse the resulting artifact '
        '(loader/run_epoch.py). Drop padded_window from the candidate '
        'field, or hand-tune it for per-epoch ScanTrainer use only '
        '(docs/tuning.md "Padded windows")')


def _norm_cfg(loader_cfg: Dict) -> Dict:
  cfg = dict(loader_cfg)
  if 'fanouts' not in cfg:
    if 'num_neighbors' in cfg:
      cfg['fanouts'] = cfg.pop('num_neighbors')
    else:
      raise ValueError("loader_cfg needs 'fanouts' (the sampler "
                       'fanout list)')
  if 'input_nodes' not in cfg:
    raise ValueError("loader_cfg needs 'input_nodes' (the seed pool)")
  if isinstance(cfg['fanouts'], dict):
    # typed fanouts: {edge_type: [per-hop counts]} — the hetero
    # CapacityPlan inputs (docs/capacity_plans.md)
    cfg['fanouts'] = {et: [int(k) for k in f]
                     for et, f in cfg['fanouts'].items()}
  else:
    cfg['fanouts'] = [int(k) for k in cfg['fanouts']]
  inp = cfg['input_nodes']
  if isinstance(inp, tuple) and len(inp) == 2 and isinstance(inp[0], str):
    # typed seeds: ('ntype', ids) — the hetero loader convention
    cfg['input_nodes'] = (inp[0], np.asarray(inp[1]).reshape(-1))
  else:
    cfg['input_nodes'] = np.asarray(inp).reshape(-1)
  cfg.setdefault('batch_size', 64)
  cfg.setdefault('shuffle', False)
  cfg.setdefault('drop_last', False)
  cfg.setdefault('seed', 0)
  return cfg


def _num_classes(dataset, cfg: Dict) -> int:
  if cfg.get('num_classes'):
    return int(cfg['num_classes'])
  labels = getattr(dataset, 'node_labels', None)
  if isinstance(labels, dict) and isinstance(cfg['input_nodes'], tuple):
    seed_t = cfg['input_nodes'][0]
    if seed_t in labels and labels[seed_t] is not None:
      return int(np.asarray(labels[seed_t]).max()) + 1
  if labels is None or isinstance(labels, dict):
    raise ValueError("pass loader_cfg['num_classes'] — the dataset "
                     'carries no label array for the seed pool to '
                     'infer it from')
  return int(np.asarray(labels).max()) + 1


def _default_model(cfg: Dict, num_classes: int):
  from ..models import GraphSAGE
  return GraphSAGE(hidden_dim=16, out_dim=num_classes,
                   num_layers=len(cfg['fanouts']))


def _default_hetero_model(fanouts: Dict, seed_type: str,
                          num_classes: int):
  # proxy model for typed ranking: same shape family the hetero
  # trainers run (RGNN over reversed relations, logits on the seed
  # type) — candidate RANKING is program-shape-driven, so a small
  # proxy suffices exactly as in the homo path
  from ..models import RGNN
  from ..typing import reverse_edge_type
  etypes = tuple(reverse_edge_type(et) for et in sorted(fanouts))
  layers = max(len(f) for f in fanouts.values())
  return RGNN(etypes=etypes, hidden_dim=16, out_dim=num_classes,
              num_layers=layers, out_ntype=seed_type)


def _site_compiles() -> Dict[str, int]:
  return {s: programs.compile_count(s) for s in CANDIDATE_SITES}


def _candidate_record(cand: Candidate, chunk_k: int) -> dict:
  return dict(kind='candidate', name=cand.name,
              loader_kwargs={k: v for k, v in cand.loader_kwargs.items()},
              chunk_k=int(cand.chunk_k or chunk_k),
              exact_semantics=cand.exact_semantics,
              kernel=dict(cand.kernel))


def score_candidate(cand: Candidate, dataset, cfg: Dict, num_classes:
                    int, chunk_k: int, probe_steps: Optional[int],
                    model=None, tx=None) -> dict:
  """Run one candidate's compile + steady epochs and return its
  evidence record: qualified?, steady wall, per-site compile counts,
  the disqualifying retrace diff (if any), and — under
  GLT_PROGRAM_COST — the chunk program's cost attribution."""
  import jax
  import optax

  from .. import loader as loader_mod
  from ..models import train as train_lib
  k = int(cand.chunk_k or chunk_k)
  rec = _candidate_record(cand, chunk_k)
  metrics.inc('tune.candidates')
  t_start = time.perf_counter()
  try:
    with spans.span('tune.candidate', candidate=cand.name, chunk_k=k):
      # stamp THIS candidate's kernel routing on the dataset's feature
      # store (keys absent -> kernels-off defaults, which also resets
      # whatever the previous candidate routed in)
      apply_kernel_routing(dataset, cand.kernel)
      lkw = dict(batch_size=cfg['batch_size'], shuffle=cfg['shuffle'],
                 drop_last=cfg['drop_last'], seed=cfg['seed'],
                 overflow_policy='off')
      lkw.update(cand.loader_kwargs)
      make_loader = lambda: loader_mod.NeighborLoader(
          dataset, cfg['fanouts'], cfg['input_nodes'], **lkw)
      first = train_lib.batch_to_dict(next(iter(make_loader())))
      mdl = model or _default_model(cfg, num_classes)
      if tx is None:
        tx = optax.adam(1e-3)
      state, _ = train_lib.create_train_state(
          mdl, jax.random.PRNGKey(0), first, optimizer=tx)
      trainer = loader_mod.ScanTrainer(make_loader(), mdl, tx,
                                       num_classes, chunk_size=k)
      steps = trainer._epoch_steps()
      if probe_steps is None:
        probe_steps = min(steps, 2 * k)
      probe_steps = min(steps, max(k, (probe_steps // k) * k))
      base = _site_compiles()
      # compile epoch: the executable population is built here
      state, losses, _ = trainer.run_epoch(state, max_steps=probe_steps)
      jax.block_until_ready(losses)
      after_compile = _site_compiles()
      if cand.perturb_chunk:
        # the self-test probe: a mid-run chunk-length drift is exactly
        # the silent production retrace the scoring must catch
        trainer.chunk_size = max(1, k // 2)
      # steady epoch: the measured one — ANY compile here disqualifies
      t0 = time.perf_counter()
      state, losses, _ = trainer.run_epoch(state, max_steps=probe_steps)
      jax.block_until_ready(losses)
      wall = time.perf_counter() - t0
      after_steady = _site_compiles()
      rec['probe_steps'] = int(probe_steps)
      rec['compile_epoch_compiles'] = {
          s: after_compile[s] - base[s] for s in CANDIDATE_SITES}
      steady = {s: after_steady[s] - after_compile[s]
                for s in CANDIDATE_SITES}
      rec['steady_epoch_compiles'] = steady
      rec['wall_s'] = round(wall, 6)
      retraced = sum(steady.values()) > 0
      rec['qualified'] = not retraced
      if retraced:
        site = max(steady, key=steady.get)
        ev = programs.last_compile(site)
        rec['rejected'] = (
            f'steady-state epoch compiled {sum(steady.values())} '
            f'program(s) — a tuned config must dispatch a CLOSED '
            'executable set')
        rec['retrace_diff'] = ev.diff if ev is not None else None
        metrics.inc('tune.rejected')
      if programs.cost_enabled():
        ev = programs.last_compile('scan_chunk')
        if ev is not None and ev.cost and 'error' not in ev.cost:
          rec['cost'] = dict(
              flops=ev.cost.get('flops'),
              peak_hbm_bytes=ev.cost.get('peak_hbm_bytes'))
  except Exception as e:  # a broken candidate is evidence, not a crash
    rec['qualified'] = False
    rec['rejected'] = f'{type(e).__name__}: {e}'[:300]
    metrics.inc('tune.rejected')
  metrics.observe('tune.probe_ms',
                  (time.perf_counter() - t_start) * 1e3)
  return rec


def score_hetero_candidate(cand: Candidate, dataset, cfg: Dict,
                           num_classes: int, chunk_k: int,
                           probe_steps: Optional[int], model=None,
                           tx=None) -> dict:
  """Run one typed fanout candidate's compile + steady epochs over the
  per-batch hetero NeighborLoader and return its evidence record. The
  observatory sites only see scanned programs, so the retrace check
  here counts TRACES of the jitted train step directly: a steady epoch
  that traces anything means the candidate's typed shapes are not
  closed — disqualified by the same rule as the homo path."""
  import jax
  import jax.numpy as jnp
  import optax

  from .. import loader as loader_mod
  from ..typing import as_str
  fans = cand.loader_kwargs['fanouts']
  rec = dict(kind='candidate', name=cand.name,
             fanouts={as_str(et): list(f)
                      for et, f in sorted(fans.items(), key=str)},
             chunk_k=int(cand.chunk_k or chunk_k),
             exact_semantics=True, kernel=dict(cand.kernel))
  metrics.inc('tune.candidates')
  t_start = time.perf_counter()
  try:
    with spans.span('tune.candidate', candidate=cand.name,
                    chunk_k=int(cand.chunk_k or chunk_k)):
      apply_kernel_routing(dataset, cand.kernel)
      seed_t, seeds = cfg['input_nodes']
      make_loader = lambda: loader_mod.NeighborLoader(
          dataset, fans, (seed_t, seeds),
          batch_size=cfg['batch_size'], shuffle=cfg['shuffle'],
          drop_last=cfg['drop_last'], seed=cfg['seed'])
      mdl = model or _default_hetero_model(fans, seed_t, num_classes)
      if tx is None:
        tx = optax.adam(1e-3)
      b0 = next(iter(make_loader()))
      params = mdl.init(jax.random.PRNGKey(0), b0.x, b0.edge_index,
                        b0.edge_mask)
      opt_state = tx.init(params)
      traces = dict(n=0)

      def _step(params, opt_state, x, ei, em, y, num_seed):
        traces['n'] += 1  # python body runs once per TRACE only

        def loss_fn(p):
          logits = mdl.apply(p, x, ei, em)
          seed_mask = jnp.arange(logits.shape[0]) < num_seed
          ce = optax.softmax_cross_entropy(
              logits, jax.nn.one_hot(y, num_classes))
          return jnp.where(seed_mask, ce, 0.0).sum() / \
              jnp.maximum(seed_mask.sum(), 1)

        loss, g = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

      step = jax.jit(_step)
      steps = probes.epoch_steps(seeds.shape[0], cfg['batch_size'],
                                 cfg['drop_last'])
      k = int(cand.chunk_k or chunk_k)
      if probe_steps is None:
        probe_steps = min(steps, 2 * k)
      probe_steps = max(1, min(steps, probe_steps))

      def run_epoch(params, opt_state):
        loss = None
        for n, b in enumerate(make_loader()):
          if n >= probe_steps:
            break
          params, opt_state, loss = step(
              params, opt_state, b.x, b.edge_index, b.edge_mask,
              b.y[seed_t], b.num_sampled_nodes[seed_t][0])
        if loss is not None:
          jax.block_until_ready(loss)
        return params, opt_state

      params, opt_state = run_epoch(params, opt_state)  # compile epoch
      after_compile = traces['n']
      t0 = time.perf_counter()
      params, opt_state = run_epoch(params, opt_state)  # steady epoch
      wall = time.perf_counter() - t0
      steady = traces['n'] - after_compile
      rec['probe_steps'] = int(probe_steps)
      rec['compile_epoch_compiles'] = dict(hetero_step=after_compile)
      rec['steady_epoch_compiles'] = dict(hetero_step=steady)
      rec['wall_s'] = round(wall, 6)
      rec['qualified'] = steady == 0
      if steady:
        rec['rejected'] = (
            f'steady-state epoch traced {steady} program(s) — a tuned '
            'typed config must dispatch a CLOSED executable set')
        metrics.inc('tune.rejected')
  except Exception as e:  # a broken candidate is evidence, not a crash
    rec['qualified'] = False
    rec['rejected'] = f'{type(e).__name__}: {e}'[:300]
    metrics.inc('tune.rejected')
  metrics.observe('tune.probe_ms',
                  (time.perf_counter() - t_start) * 1e3)
  return rec


def _per_step_wall(rec: dict) -> float:
  # candidates with different chunk_k run different probe_steps (each
  # epoch rounds to its own chunk boundary) — raw wall_s would compare
  # apples to oranges, so ranking normalizes to wall per step
  return rec['wall_s'] / max(1, rec.get('probe_steps', 1))


def _pick_winner(records: List[dict]) -> dict:
  ok = [r for r in records if r.get('qualified')]
  if not ok:
    raise RuntimeError(
        'tune(): every candidate was disqualified — see the evidence '
        'log on the raised artifact draft for per-candidate reasons '
        f'({[r.get("rejected") for r in records]})')
  ok.sort(key=_per_step_wall)
  best = ok[0]
  if len(ok) > 1 and programs.cost_enabled():
    # near-tie on per-step wall: break on flops, then peak HBM (the
    # CPU-replica rule — wall there is dispatch noise at this margin)
    near = [r for r in ok
            if _per_step_wall(r) <=
            _per_step_wall(ok[0]) * (1 + COST_TIE_MARGIN)
            and r.get('cost')]
    if len(near) > 1:
      near.sort(key=lambda r: (r['cost'].get('flops') or float('inf'),
                               r['cost'].get('peak_hbm_bytes')
                               or float('inf')))
      best = near[0]
      best['tie_break'] = 'cost (flops, peak_hbm)'
  return best


def tune(dataset, loader_cfg: Dict, *, topology: str = 'local',
         exact: bool = False,
         candidates: Optional[Sequence[Candidate]] = None,
         probe_steps: Optional[int] = None, model=None, tx=None,
         num_probes: int = 8, seed: int = 0,
         budget_s: Optional[float] = None,
         out_path: Optional[str] = None) -> TuneArtifact:
  """One call from a dataset + loader shape to a validated config
  artifact (module docstring; docs/tuning.md has the quickstart).

  Args:
    dataset: a homogeneous ``data.Dataset`` with features + labels
      (for distributed topologies: the scenario's dataset — used for
      the artifact fingerprint; the scenarios themselves come from
      ``loader_cfg['make_scenario']``).
    loader_cfg: dict with ``fanouts``, ``input_nodes``, ``batch_size``
      (+ optional shuffle / drop_last / seed / num_classes). For
      ``topology != 'local'`` see :func:`tune.topology.tune_topology`
      (``make_scenario``, analytics inputs, quotas).
    topology: which trainer scenario to field candidates for —
      ``'local'`` (homo ScanTrainer, the default), ``'dist'``
      (DistScanTrainer), ``'remote'`` (RemoteScanTrainer), or
      ``'tiered_dist'`` (TieredDistScanTrainer). One artifact per
      topology; the matching trainer's ``config=`` accepts it and a
      mismatched one refuses (docs/tuning.md 'Topology candidates').
    exact: pin the exact-semantics set (calibrated exact dedup, f32
      wire); default also fields the accuracy-matrix-certified
      relaxations (tree dedup, bf16 wire).
    candidates: explicit candidate list (default:
      :func:`default_candidates`; append
      :func:`retrace_probe_candidate` to live-fire the rejection
      path).
    probe_steps: optimizer steps per A/B epoch (default ``2 x K``,
      rounded to a chunk boundary — one executable per site).
    model / tx: the model/optimizer to probe with (default: a small
      GraphSAGE + adam — candidate RANKING is program-shape-driven,
      so a proxy model suffices; pass the real one to rank on its
      true wall).
    num_probes / seed: calibration probe controls (calibrate.py).
    budget_s: explicit wall-clock budget for the candidate A/Bs —
      after the first candidate is scored, the remaining ladder is
      truncated to what the budget affords at that measured
      per-candidate wall, with a ``kind='budget'`` evidence record
      naming what was dropped (docs/tuning.md 'Budgeted tuning').
    out_path: also save the artifact JSON there.
  """
  if topology != 'local':
    from .topology import tune_topology
    return tune_topology(topology, dataset, loader_cfg, exact=exact,
                         candidates=candidates,
                         probe_steps=probe_steps, budget_s=budget_s,
                         out_path=out_path)
  cfg = _norm_cfg(loader_cfg)
  if _is_hetero_dataset(dataset):
    # typed datasets field the per-etype fanout candidates and sign a
    # TYPED fingerprint — one artifact, validated on load by every
    # config= acceptor exactly like a homo one (docs/capacity_plans.md)
    return _tune_hetero_local(dataset, cfg, exact=exact,
                              candidates=candidates,
                              probe_steps=probe_steps, model=model,
                              tx=tx, budget_s=budget_s,
                              out_path=out_path)
  num_classes = _num_classes(dataset, cfg)
  evidence: List[dict] = []
  with spans.span('tune.run', exact=exact):
    caps, ev = probes.probe_frontier_caps(
        dataset.graph, cfg['fanouts'], cfg['batch_size'],
        input_nodes=cfg['input_nodes'], num_probes=num_probes,
        seed=seed)
    evidence.append(ev)
    n = dataset.graph.topo.indptr.shape[0] - 1 \
        if hasattr(dataset.graph, 'topo') else \
        np.asarray(dataset.graph.indptr).shape[0] - 1
    split, bucket_frac, ev = probes.probe_cache_split(dataset.graph, n)
    evidence.append(ev)
    steps = probes.epoch_steps(cfg['input_nodes'].shape[0],
                               cfg['batch_size'], cfg['drop_last'])
    chunk_k, ev = probes.probe_chunk_k(steps)
    evidence.append(ev)
    slab_cap, ev = probes.probe_slab_cap(chunk_k, caps,
                                         cfg['batch_size'], split)
    evidence.append(ev)
    buckets, ev = probes.probe_serving_buckets(cfg['batch_size'])
    evidence.append(ev)
    wire, ev = probes.wire_dtype_choice(exact)
    evidence.append(ev)

    cands = list(candidates) if candidates is not None \
        else default_candidates(caps, exact)
    _refuse_padded_candidates(cands)
    if exact:
      dropped = [c.name for c in cands if not c.exact_semantics]
      cands = [c for c in cands if c.exact_semantics]
      if dropped:
        evidence.append(dict(
            kind='exact_pin', dropped_candidates=dropped,
            note='exact=True pins the accuracy-matrix exact set'))
    records = []
    pending = list(cands)
    while pending:
      cand = pending.pop(0)
      records.append(score_candidate(cand, dataset, cfg, num_classes,
                                     chunk_k, probe_steps, model=model,
                                     tx=tx))
      if budget_s is not None and len(records) == 1 and pending:
        # tune-the-tuner: the first candidate's measured wall prices
        # the ladder; keep what the explicit budget affords and say
        # out loud what was never fielded (topology.py._budget_ladder)
        from .topology import _budget_ladder
        pending, ev = _budget_ladder(records, pending, budget_s,
                                     records[0].get('wall_s') or 0.0)
        evidence.append(ev)
    evidence.extend(records)
    best = _pick_winner(records)
    kern = dict(KERNEL_CHOICE_DEFAULTS)
    kern.update(best.get('kernel') or {})
    evidence.append(dict(kind='winner', name=best['name'],
                         wall_s=best['wall_s'],
                         tie_break=best.get('tie_break', 'wall'),
                         kernel=dict(kern)))
    # leave the dataset routed the way the winner ran (score_candidate
    # stamped the LAST candidate's routing, not necessarily the best's)
    apply_kernel_routing(dataset, kern)

    choices = dict(
        mode=best['loader_kwargs'].get('dedup', 'map'),
        frontier_caps=list(caps),
        padded_window=best['loader_kwargs'].get('padded_window'),
        wire_dtype=wire,
        chunk_k=int(best['chunk_k']),
        split_ratio=split,
        bucket_frac=bucket_frac,
        slab_cap=int(slab_cap),
        serving_buckets=list(buckets),
        batch_size=int(cfg['batch_size']),
        fanouts=list(cfg['fanouts']),
        exact=bool(exact))
    choices.update(kern)
    fp = dataset_fingerprint(dataset)
    if fp is None:
      # structured fingerprint-gap record: a dataset with no
      # computable identity is a recorded fact in the artifact, not a
      # silent one — config= acceptors will warn instead of validating
      evidence.append(dict(
          kind='fingerprint_gap', topology='local',
          dataset_type=type(dataset).__name__,
          note='dataset has no computable fingerprint — config= '
               'acceptors will warn instead of validating '
               '(docs/tuning.md "Fingerprints")'))
    art = TuneArtifact(choices, fp, evidence)
  metrics.inc('tune.artifacts')
  if out_path is not None:
    art.save(out_path)
  return art


def _tune_hetero_local(dataset, cfg: Dict, *, exact: bool,
                       candidates: Optional[Sequence[Candidate]],
                       probe_steps: Optional[int], model, tx,
                       budget_s: Optional[float],
                       out_path: Optional[str]) -> TuneArtifact:
  """tune() over a typed dataset: field the per-etype fanout candidate
  ladder (hetero_fanout_candidates), score each by compile + steady
  per-batch epochs with the RGNN proxy, and sign the winner into a v3
  artifact with the TYPED dataset fingerprint — per-etype CSR records
  the config= acceptors validate on load (docs/capacity_plans.md,
  docs/tuning.md 'Hetero datasets')."""
  if not isinstance(cfg['fanouts'], dict):
    raise ValueError(
        "tune() on a typed dataset needs loader_cfg['fanouts'] as an "
        '{edge_type: [per-hop counts]} dict — the per-etype closed '
        'shapes are the thing being tuned (docs/capacity_plans.md)')
  if not isinstance(cfg['input_nodes'], tuple):
    raise ValueError(
        "tune() on a typed dataset needs loader_cfg['input_nodes'] as "
        "('ntype', ids) — the seed type picks the label store and the "
        'proxy head (docs/tuning.md "Hetero datasets")')
  num_classes = _num_classes(dataset, cfg)
  evidence: List[dict] = []
  with spans.span('tune.run', exact=exact, hetero=True):
    seed_t, seeds = cfg['input_nodes']
    steps = probes.epoch_steps(seeds.shape[0], cfg['batch_size'],
                               cfg['drop_last'])
    chunk_k, ev = probes.probe_chunk_k(steps)
    evidence.append(ev)
    buckets, ev = probes.probe_serving_buckets(cfg['batch_size'])
    evidence.append(ev)
    wire, ev = probes.wire_dtype_choice(exact)
    evidence.append(ev)

    cands = list(candidates) if candidates is not None \
        else hetero_fanout_candidates(cfg['fanouts'])
    records: List[dict] = []
    pending = list(cands)
    while pending:
      cand = pending.pop(0)
      records.append(score_hetero_candidate(
          cand, dataset, cfg, num_classes, chunk_k, probe_steps,
          model=model, tx=tx))
      if budget_s is not None and len(records) == 1 and pending:
        from .topology import _budget_ladder
        pending, ev = _budget_ladder(records, pending, budget_s,
                                     records[0].get('wall_s') or 0.0)
        evidence.append(ev)
    evidence.extend(records)
    best = _pick_winner(records)
    evidence.append(dict(kind='winner', name=best['name'],
                         wall_s=best['wall_s'],
                         tie_break=best.get('tie_break', 'wall'),
                         fanouts=dict(best['fanouts'])))
    choices = dict(
        mode='map',  # the hetero engine runs the exact-dedup path
        frontier_caps=None,  # typed caps live in the CapacityPlan
        padded_window=None,
        wire_dtype=wire,
        chunk_k=int(chunk_k),
        serving_buckets=list(buckets),
        batch_size=int(cfg['batch_size']),
        fanouts={k: list(v) for k, v in best['fanouts'].items()},
        exact=bool(exact))
    fp = dataset_fingerprint(dataset)
    art = TuneArtifact(choices, fp, evidence)
  metrics.inc('tune.artifacts')
  if out_path is not None:
    art.save(out_path)
  return art
