"""Rule donation-safety: a donated buffer must not be read afterwards.

``jax.jit(fn, donate_argnums=...)`` invalidates the donated operand at
DISPATCH time — the caller's array becomes garbage whether or not the
call completes. This repo leans on donation everywhere the update loop
is hot (the serving store's scatter, the scanned-epoch chunk programs,
the demand-paged gather), always in the rebind idiom::

    self._emb = self._scatter(self._emb, idx, vals)   # donate (0,)

which is safe because the donated name is rebound by the very statement
that donates it. PR 7 fixed the same bug twice: a path (the empty-batch
early return, the failed-refresh re-mark) that read ``_embeddings``
after a donating dispatch without the rebind in between. This rule
makes that a lint error: after a call through a donating handle, the
names passed in donated positions are DEAD on every path until rebound;
any read of a dead name is a finding. Exception edges stay dead even
through the rebind statement — if the donating statement raised, the
buffer was still donated but the rebind never happened, which is
exactly the failed-refresh shape.

Handles are found the same way dispatch-instrumentation finds them:
``jax.jit``/donating-factory results propagating through local names,
``self.attr`` stores, container stores and returns, seen through
``programs.instrument(...)``/``wrap_dispatch(...)`` wrappers, plus
``@functools.partial(jax.jit, donate_argnums=...)`` decorated defs.
Only HOST (untraced) functions are checked — inside a traced body a
nested donating call composes into the outer program.
"""
import ast
from typing import Dict, List, Optional, Tuple

from . import astutil, flow
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'donation-safety'

_WRAPPERS = ('instrument', 'wrap_dispatch')


def check_package(modules: List[ParsedModule], config: Config):
  findings = []
  for mod in modules:
    if not in_scope(mod.relpath, config.donation_modules):
      continue
    try:
      findings.extend(_check_module(mod, config))
    except RecursionError:   # pathological nesting: err quiet
      pass
  return findings


class _ModuleState:
  def __init__(self, mod: ParsedModule, config: Config):
    self.mod = mod
    self.index = astutil.FuncIndex(mod.tree)
    self.aliases = astutil.import_aliases(mod.tree)
    self.traced = astutil.traced_functions(self.index, mod.tree,
                                           self.aliases)
    self.parents = astutil.parent_map(mod.tree)
    # handle identity -> donated positional indices
    self.attr_don: Dict[str, Tuple[int, ...]] = {}
    self.local_don: Dict[Tuple[str, str], Tuple[int, ...]] = {}
    self.container_don: Dict[str, Tuple[int, ...]] = {}
    self.factory_don: Dict[str, Tuple[int, ...]] = {}

  def scope_of(self, node) -> str:
    fi = astutil.enclosing_function(self.index, node, self.parents)
    return fi.qualname if fi else '<module>'


def _jit_donation(st: _ModuleState,
                  call: ast.Call) -> Optional[Tuple[int, ...]]:
  """Donated positions of a jax.jit(...) call, or None."""
  pos = set()
  argnames = []
  for kw in call.keywords:
    if kw.arg == 'donate_argnums':
      vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
          else [kw.value]
      for e in vals:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
          pos.add(e.value)
    elif kw.arg == 'donate_argnames':
      vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
          else [kw.value]
      for e in vals:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
          argnames.append(e.value)
  if argnames and call.args and isinstance(call.args[0], ast.Name):
    for fi in st.index.by_name.get(call.args[0].id, []):
      a = fi.node.args
      params = [x.arg for x in a.posonlyargs + a.args]
      for name in argnames:
        if name in params:
          pos.add(params.index(name))
      break
  return tuple(sorted(pos)) or None


def _donating_expr(st: _ModuleState, node: ast.AST,
                   scope: str) -> Optional[Tuple[int, ...]]:
  """Donated positions if this expression evaluates to a donating
  jitted callable, else None."""
  if isinstance(node, ast.Call):
    seg = astutil.last_segment(astutil.call_name(node))
    if seg in _WRAPPERS and node.args:
      return _donating_expr(st, node.args[0], scope)
    if seg == 'jit':
      return _jit_donation(st, node)
    if seg in st.factory_don:
      return st.factory_don[seg]
    return None
  if isinstance(node, ast.Name):
    return st.local_don.get((scope, node.id)) or \
        st.local_don.get(('<module>', node.id))
  if isinstance(node, ast.Attribute):
    return st.attr_don.get(node.attr)
  if isinstance(node, ast.Subscript):
    base = node.value
    if isinstance(base, ast.Attribute):
      return st.container_don.get(base.attr)
    if isinstance(base, ast.Name):
      return st.local_don.get((scope, base.id))
  return None


def _bind_target(st: _ModuleState, t: ast.AST, scope: str,
                 pos: Tuple[int, ...]) -> bool:
  if isinstance(t, ast.Name):
    key = (scope, t.id)
    if st.local_don.get(key) != pos:
      st.local_don[key] = pos
      return True
  elif isinstance(t, ast.Attribute):
    if st.attr_don.get(t.attr) != pos:
      st.attr_don[t.attr] = pos
      return True
  elif isinstance(t, ast.Subscript):
    base = t.value
    if isinstance(base, ast.Attribute) and \
        st.container_don.get(base.attr) != pos:
      st.container_don[base.attr] = pos
      return True
  return False


def _seed_handles(st: _ModuleState):
  """Fixpoint: donating jit results into names/attrs/containers, defs
  returning them into factories, decorated defs into handles."""
  for fi in st.index.by_qual.values():
    for dec in fi.node.decorator_list:
      if isinstance(dec, ast.Call) and \
          astutil.matches(astutil.canonical(astutil.call_name(dec),
                                            st.aliases),
                          {'functools.partial', 'partial'}) and dec.args:
        inner = astutil.canonical(astutil.dotted_name(dec.args[0]),
                                  st.aliases)
        if astutil.last_segment(inner) == 'jit':
          pos = _jit_donation(st, dec)
          if pos:
            name = fi.node.name
            st.attr_don.setdefault(name, pos)
            st.local_don.setdefault(('<module>', name), pos)
  changed = True
  while changed:
    changed = False
    for node in ast.walk(st.mod.tree):
      if isinstance(node, ast.Assign):
        scope = st.scope_of(node)
        pos = _donating_expr(st, node.value, scope)
        if pos:
          for t in node.targets:
            changed |= _bind_target(st, t, scope, pos)
      elif isinstance(node, ast.Return) and node.value is not None:
        scope = st.scope_of(node)
        if scope != '<module>':
          pos = _donating_expr(st, node.value, scope)
          fn_name = scope.rsplit('.', 1)[-1]
          if pos and st.factory_don.get(fn_name) != pos:
            st.factory_don[fn_name] = pos
            changed = True


def _check_module(mod: ParsedModule, config: Config) -> List[Finding]:
  st = _ModuleState(mod, config)
  _seed_handles(st)
  if not (st.attr_don or st.local_don or st.container_don or
          st.factory_don):
    return []
  out: List[Finding] = []
  for fi in st.index.by_qual.values():
    if fi.qualname in st.traced:
      continue
    out.extend(_check_function(st, fi))
  return out


def _donated_names(st: _ModuleState, fi: astutil.FuncInfo,
                   stmt: ast.stmt):
  """[(name, line)] donated by calls in this statement."""
  killed = []
  for call in flow.stmt_calls(stmt):
    pos = _donating_expr(st, call.func, fi.qualname)
    if not pos:
      continue
    for p in pos:
      if p < len(call.args):
        d = flow.dotted(call.args[p])
        if d:
          killed.append((d, call.lineno))
  return killed


def _check_function(st: _ModuleState,
                    fi: astutil.FuncInfo) -> List[Finding]:
  # cheap pre-pass: skip functions with no donating call at all
  gen: Dict[int, List[Tuple[str, int]]] = {}
  any_don = False
  for node in st.index.own_nodes(fi):
    if isinstance(node, ast.stmt):
      killed = _donated_names(st, fi, node)
      if killed:
        gen[id(node)] = killed
        any_don = True
  if not any_don:
    return []

  cfg = flow.build_cfg(fi.node)

  # state elements are 'name|donate_line' so the finding can say where
  # the donation happened
  def transfer(n, stmt, state):
    if stmt is None:
      return state
    # donation happens at dispatch, the rebind only after the call
    # returns — so gen precedes the write-kill, and the rebind idiom
    # (self._emb = self._scatter(self._emb, ...)) comes out clean
    for name, line in gen.get(id(stmt), ()):
      state = state | {f'{name}|{line}'}
    writes = flow.stmt_writes(stmt)
    return frozenset(e for e in state
                     if e.split('|', 1)[0] not in writes)

  def exc_transfer(n, stmt, state):
    # if the statement raised, its rebind never happened but any
    # donation in it already did (donation invalidates at dispatch)
    if stmt is None:
      return state
    for name, line in gen.get(id(stmt), ()):
      state = state | {f'{name}|{line}'}
    return state

  in_s = flow.forward(cfg, frozenset(), transfer, exc_transfer)
  out: List[Finding] = []
  seen = set()
  for n in cfg.nodes():
    stmt = cfg.stmt_of.get(n)
    if stmt is None or not in_s[n]:
      continue
    reads = flow.stmt_reads(stmt)
    for e in sorted(in_s[n]):
      name, don_line = e.split('|', 1)
      if name in reads:
        key = (name, stmt.lineno)
        if key in seen:
          continue
        seen.add(key)
        out.append(Finding(
            RULE, st.mod.path, st.mod.relpath, stmt.lineno,
            stmt.col_offset + 1,
            f"'{name}' may be read here after being donated to the "
            f'jitted call at line {don_line} — a donated buffer is '
            'invalidated at dispatch; rebind the name before reading '
            'it (or drop it from donate_argnums)',
            symbol=fi.qualname))
  return out
