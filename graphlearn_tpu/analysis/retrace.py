"""Rule retrace-hazard: dynamic sizes must not feed static jit args raw.

The one-executable-per-shape contract (PAPER.md L0, docs/
capacity_plans.md) holds because every static argument a jitted program
sees is drawn from a CLOSED set: pow2 ladders, calibrated caps,
CapacityPlan fields, the chunk-K ladder. The moment a host value
derived from runtime data — ``len(batch)``, ``table.shape[0]``, a dict
size — reaches a ``static_argnames``/``static_argnums`` slot directly,
every distinct value mints a fresh trace and the epoch dissolves into a
retrace storm. ``retrace_budget`` catches that at RUN time, per
executable, after the damage; this rule is its lint-time twin: it
flags the flow at the call site, before it ships.

Per host function, a forward taint analysis over the CFG: ``len(...)``
and ``.shape``/``.size``/``.nbytes`` reads are sources; assignment
propagates; a call to a registered closure function
(``Config.retrace_closure_fns`` — the pow2/capacity ladder) SANITIZES
its result. A sink is a static slot of (a) any package-wide function
decorated ``@functools.partial(jax.jit, static_argnames=...)`` (the
ops/ surface), or (b) a module-local handle built with
``jax.jit(fn, static_argnums=...)``, matched by the same name-based
binding the other rules use. A static argument that still carries raw
taint at the sink is a finding.

Traced functions are skipped — inside a trace, shapes are static per
executable by construction; the hazard is purely a host-side flow.
"""
import ast
from typing import Dict, List, Optional, Set, Tuple

from . import astutil, flow
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'retrace-hazard'

_SOURCE_ATTRS = ('shape', 'size', 'nbytes')
_WRAPPERS = ('instrument', 'wrap_dispatch')


def check_package(modules: List[ParsedModule], config: Config):
  registry = _static_registry(modules)
  findings = []
  for mod in modules:
    if not in_scope(mod.relpath, config.retrace_modules):
      continue
    try:
      findings.extend(_check_module(mod, config, registry))
    except RecursionError:
      pass
  return findings


# ------------------------------------------------- package-wide static sinks

def _decorated_static(fi: astutil.FuncInfo,
                      aliases) -> Optional[Tuple[Tuple[str, ...],
                                                 Tuple[int, ...]]]:
  """(static names, static positions) for a def decorated
  ``@functools.partial(jax.jit, static_argnames=...)`` (or plain
  ``@jax.jit`` with the kwarg), else None."""
  for dec in fi.node.decorator_list:
    if not isinstance(dec, ast.Call):
      continue
    name = astutil.canonical(astutil.call_name(dec), aliases)
    if astutil.matches(name, {'functools.partial', 'partial'}) and \
        dec.args:
      inner = astutil.canonical(astutil.dotted_name(dec.args[0]), aliases)
      if astutil.last_segment(inner) != 'jit':
        continue
    elif astutil.last_segment(name) != 'jit':
      continue
    names = _str_tuple_kw(dec, 'static_argnames')
    nums = _int_tuple_kw(dec, 'static_argnums')
    if not names and not nums:
      continue
    a = fi.node.args
    params = [x.arg for x in a.posonlyargs + a.args]
    pos = set(nums)
    for s in names:
      if s in params:
        pos.add(params.index(s))
    return tuple(names), tuple(sorted(pos))
  return None


def _str_tuple_kw(call: ast.Call, kwname: str) -> Tuple[str, ...]:
  for kw in call.keywords:
    if kw.arg == kwname:
      vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
          else [kw.value]
      return tuple(e.value for e in vals
                   if isinstance(e, ast.Constant) and
                   isinstance(e.value, str))
  return ()


def _int_tuple_kw(call: ast.Call, kwname: str) -> Tuple[int, ...]:
  for kw in call.keywords:
    if kw.arg == kwname:
      vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
          else [kw.value]
      return tuple(e.value for e in vals
                   if isinstance(e, ast.Constant) and
                   isinstance(e.value, int))
  return ()


def _static_registry(modules: List[ParsedModule]):
  """fn name -> (static names, static positions) across the package.
  Name collisions keep the first entry — the ops/ surface this exists
  for has unique public names."""
  reg: Dict[str, Tuple[Tuple[str, ...], Tuple[int, ...]]] = {}
  for mod in modules:
    index = astutil.FuncIndex(mod.tree)
    aliases = astutil.import_aliases(mod.tree)
    for fi in index.by_qual.values():
      info = _decorated_static(fi, aliases)
      if info is not None:
        reg.setdefault(fi.node.name, info)
  return reg


# --------------------------------------------------- module-local jit handles

class _ModuleState:
  def __init__(self, mod: ParsedModule, config: Config, registry):
    self.mod = mod
    self.config = config
    self.registry = registry
    self.index = astutil.FuncIndex(mod.tree)
    self.aliases = astutil.import_aliases(mod.tree)
    self.traced = astutil.traced_functions(self.index, mod.tree,
                                           self.aliases)
    self.parents = astutil.parent_map(mod.tree)
    # handle identity -> (static names, static positions)
    self.attr_h: Dict[str, Tuple] = {}
    self.local_h: Dict[Tuple[str, str], Tuple] = {}
    self.container_h: Dict[str, Tuple] = {}
    self.factory_h: Dict[str, Tuple] = {}

  def scope_of(self, node) -> str:
    fi = astutil.enclosing_function(self.index, node, self.parents)
    return fi.qualname if fi else '<module>'


def _static_of_jit(st: _ModuleState, call: ast.Call) -> Optional[Tuple]:
  names = _str_tuple_kw(call, 'static_argnames')
  nums = set(_int_tuple_kw(call, 'static_argnums'))
  if names and call.args and isinstance(call.args[0], ast.Name):
    for fi in st.index.by_name.get(call.args[0].id, []):
      a = fi.node.args
      params = [x.arg for x in a.posonlyargs + a.args]
      for s in names:
        if s in params:
          nums.add(params.index(s))
      break
  if not names and not nums:
    return None
  return (tuple(names), tuple(sorted(nums)))


def _static_expr(st: _ModuleState, node: ast.AST,
                 scope: str) -> Optional[Tuple]:
  if isinstance(node, ast.Call):
    seg = astutil.last_segment(astutil.call_name(node))
    if seg in _WRAPPERS and node.args:
      return _static_expr(st, node.args[0], scope)
    if seg == 'jit':
      return _static_of_jit(st, node)
    if seg in st.factory_h:
      return st.factory_h[seg]
    return None
  if isinstance(node, ast.Name):
    return st.local_h.get((scope, node.id)) or \
        st.local_h.get(('<module>', node.id))
  if isinstance(node, ast.Attribute):
    return st.attr_h.get(node.attr)
  if isinstance(node, ast.Subscript):
    base = node.value
    if isinstance(base, ast.Attribute):
      return st.container_h.get(base.attr)
    if isinstance(base, ast.Name):
      return st.local_h.get((scope, base.id))
  return None


def _seed_handles(st: _ModuleState):
  changed = True
  while changed:
    changed = False
    for node in ast.walk(st.mod.tree):
      if isinstance(node, ast.Assign):
        scope = st.scope_of(node)
        info = _static_expr(st, node.value, scope)
        if info:
          for t in node.targets:
            if isinstance(t, ast.Name):
              key = (scope, t.id)
              if st.local_h.get(key) != info:
                st.local_h[key] = info
                changed = True
            elif isinstance(t, ast.Attribute):
              if st.attr_h.get(t.attr) != info:
                st.attr_h[t.attr] = info
                changed = True
            elif isinstance(t, ast.Subscript) and \
                isinstance(t.value, ast.Attribute):
              if st.container_h.get(t.value.attr) != info:
                st.container_h[t.value.attr] = info
                changed = True
      elif isinstance(node, ast.Return) and node.value is not None:
        scope = st.scope_of(node)
        if scope != '<module>':
          info = _static_expr(st, node.value, scope)
          fn_name = scope.rsplit('.', 1)[-1]
          if info and st.factory_h.get(fn_name) != info:
            st.factory_h[fn_name] = info
            changed = True


# ----------------------------------------------------------------- taint

def _strip_sanitized(expr: ast.AST, sanitizers) -> List[ast.AST]:
  """Subtrees of ``expr`` minus anything under a sanitizing call."""
  out = []
  stack = [expr]
  while stack:
    node = stack.pop()
    if isinstance(node, ast.Call) and \
        astutil.last_segment(astutil.call_name(node)) in sanitizers:
      continue
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
      continue
    out.append(node)
    stack.extend(ast.iter_child_nodes(node))
  return out


def _raw_sources(nodes) -> List[int]:
  """Lines of len()/.shape/.size reads among ``nodes``."""
  lines = []
  for node in nodes:
    if isinstance(node, ast.Call) and \
        isinstance(node.func, ast.Name) and node.func.id == 'len':
      lines.append(node.lineno)
    elif isinstance(node, ast.Attribute) and \
        node.attr in _SOURCE_ATTRS and isinstance(node.ctx, ast.Load):
      lines.append(node.lineno)
  return lines


def _raw_reads(nodes) -> Set[str]:
  out: Set[str] = set()
  for node in nodes:
    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
      out.add(node.id)
    elif isinstance(node, ast.Attribute) and \
        isinstance(node.ctx, ast.Load):
      d = flow.dotted(node)
      if d:
        out.add(d)
  return out


def _check_module(mod: ParsedModule, config: Config,
                  registry) -> List[Finding]:
  st = _ModuleState(mod, config, registry)
  _seed_handles(st)
  sanitizers = set(config.retrace_closure_fns)
  out: List[Finding] = []
  for fi in st.index.by_qual.values():
    if fi.qualname in st.traced:
      continue
    out.extend(_check_function(st, fi, sanitizers))
  return out


def _sink_args(st: _ModuleState, call: ast.Call, scope: str):
  """Static-slot argument expressions of ``call``, or []."""
  info = _static_expr(st, call.func, scope)
  if info is None:
    seg = astutil.last_segment(astutil.call_name(call))
    info = st.registry.get(seg) if seg else None
  if info is None:
    return []
  names, pos = info
  args = [call.args[p] for p in pos if p < len(call.args)]
  args += [kw.value for kw in call.keywords if kw.arg in names]
  return args


def _check_function(st: _ModuleState, fi: astutil.FuncInfo,
                    sanitizers) -> List[Finding]:
  scope = fi.qualname
  # cheap pre-pass: any sink call at all?
  sinks = []
  for node in st.index.own_nodes(fi):
    if isinstance(node, ast.Call) and _sink_args(st, node, scope):
      sinks.append(node)
  if not sinks:
    return []

  cfg = flow.build_cfg(fi.node)

  def transfer(n, stmt, state):
    if stmt is None or not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                             ast.AnnAssign)):
      return state
    if stmt.value is None:
      return state
    kept = _strip_sanitized(stmt.value, sanitizers)
    src_lines = _raw_sources(kept)
    tainted_names = {e.split('|', 1)[0] for e in state}
    reads = _raw_reads(kept) & tainted_names
    writes = flow.stmt_writes(stmt)
    state = frozenset(e for e in state
                      if e.split('|', 1)[0] not in writes)
    if src_lines or reads:
      line = src_lines[0] if src_lines else stmt.lineno
      state |= frozenset(f'{w}|{line}' for w in writes)
    return state

  in_s = flow.forward(cfg, frozenset(), transfer)

  out: List[Finding] = []
  seen = set()
  for n in cfg.nodes():
    stmt = cfg.stmt_of.get(n)
    if stmt is None:
      continue
    tainted_names = {e.split('|', 1)[0] for e in in_s[n]}
    for call in flow.stmt_calls(stmt):
      for arg in _sink_args(st, call, scope):
        kept = _strip_sanitized(arg, sanitizers)
        hit = bool(_raw_sources(kept)) or \
            bool(_raw_reads(kept) & tainted_names)
        if hit and (call.lineno, call.col_offset) not in seen:
          seen.add((call.lineno, call.col_offset))
          fn_name = astutil.call_name(call) or '<handle>'
          out.append(Finding(
              RULE, st.mod.path, st.mod.relpath, call.lineno,
              call.col_offset + 1,
              f'dynamic size flows into a static argument of '
              f'{fn_name}(...) without passing a registered closure '
              'function (pow2_cap / capacity ladder) — every distinct '
              'value mints a fresh executable; clamp it to the closed '
              'set first (docs/capacity_plans.md)',
              symbol=fi.qualname))
  return out
