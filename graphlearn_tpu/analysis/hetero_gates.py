"""Rule hetero-gate: hetero capability refusals go through CapacityPlan.

The typed fast paths (hetero block streams, per-ntype exchange slabs,
typed tune artifacts) closed the era of `if x.is_hetero: raise
ValueError('homogeneous-only')` scattered through the marquee paths.
A capability gap on a typed dataset must now either

  1. raise :class:`~graphlearn_tpu.sampler.capacity.CapacityPlanError`
     — the typed error that names the consumer, the missing plan
     input, and the doc anchor (docs/capacity_plans.md), or
  2. carry a ``# graftlint: allow[hetero-gate] <reason>`` pragma
     explaining why the gate is a real semantic boundary and not an
     unmigrated fast path.

The rule flags a ``raise`` of anything else — or a ``warnings.warn``
— appearing as a DIRECT statement of an ``if`` branch whose test
mentions ``is_hetero`` (attribute, name, or ``getattr(...,
'is_hetero', ...)``). Direct statements only: the canonical gate shape
is a one-line refusal, and deeper hetero branches legitimately raise
for non-typed reasons.
"""
import ast
from typing import List

from .core import Config, Finding, ParsedModule

RULE = 'hetero-gate'

_MSG = ('{what} gated on is_hetero — hetero capability refusals must '
        'raise CapacityPlanError naming the consumer and the missing '
        'plan input (sampler/capacity.py, docs/capacity_plans.md), or '
        'carry a `# graftlint: allow[hetero-gate] <reason>` pragma for '
        'a real semantic boundary')

#: the module that OWNS the typed-error contract — its own internal
#: gates are the contract, not a violation of it
_EXEMPT = ('sampler/capacity.py',)


def _mentions_is_hetero(test: ast.AST) -> bool:
  for node in ast.walk(test):
    if isinstance(node, ast.Attribute) and node.attr == 'is_hetero':
      return True
    if isinstance(node, ast.Name) and node.id == 'is_hetero':
      return True
    if isinstance(node, ast.Call) and \
        isinstance(node.func, ast.Name) and node.func.id == 'getattr' and \
        any(isinstance(a, ast.Constant) and a.value == 'is_hetero'
            for a in node.args):
      return True
  return False


def _exc_name(node: ast.Raise) -> str:
  exc = node.exc
  if isinstance(exc, ast.Call):
    exc = exc.func
  if isinstance(exc, ast.Attribute):
    return exc.attr
  if isinstance(exc, ast.Name):
    return exc.id
  return ''


def _is_warn_call(stmt: ast.stmt) -> bool:
  if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
    return False
  f = stmt.value.func
  name = f.attr if isinstance(f, ast.Attribute) else \
      f.id if isinstance(f, ast.Name) else ''
  return name == 'warn'


def check_package(modules: List[ParsedModule], config: Config):
  out: List[Finding] = []
  for mod in modules:
    if mod.relpath in _EXEMPT:
      continue
    for node in ast.walk(mod.tree):
      if not isinstance(node, ast.If) or \
          not _mentions_is_hetero(node.test):
        continue
      for stmt in list(node.body) + list(node.orelse):
        what = None
        if isinstance(stmt, ast.Raise) and stmt.exc is not None:
          name = _exc_name(stmt)
          if name != 'CapacityPlanError':
            what = f'`raise {name or "..."}`'
        elif _is_warn_call(stmt):
          what = '`warnings.warn`'
        if what:
          out.append(Finding(RULE, mod.path, mod.relpath, stmt.lineno,
                             stmt.col_offset + 1,
                             _MSG.format(what=what)))
  return out
