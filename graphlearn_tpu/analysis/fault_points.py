"""Rule fault-point-coverage: fault sites are literal, unique,
registered, and documented.

The chaos suite's guarantees (docs/failure_model.md) are only as good
as the fault-site inventory: a ``fault_point`` whose name is computed
at runtime can't be armed deliberately, a duplicated name arms two
sites at once (a chaos test then *thinks* it killed one code path), an
unregistered name is invisible to the failure-model review, and an
undocumented one rots out of the operator-facing table. This rule
cross-checks three sources:

  * ``fault_point('<name>')`` call sites across the package,
  * the ``REGISTERED_SITES`` frozenset in ``utils/faults.py``
    (parsed from source — the linter never imports the package),
  * the fault-site table in ``docs/failure_model.md`` (a name counts as
    documented when it appears in backticks).
"""
import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import Config, Finding, ParsedModule

RULE = 'fault-point-coverage'


def check_package(modules: List[ParsedModule], config: Config):
  out: List[Finding] = []
  registry_mod = None
  sites: Dict[str, List[Tuple[ParsedModule, ast.Call]]] = {}

  for mod in modules:
    if mod.relpath == config.fault_registry_module:
      registry_mod = mod
    for node in ast.walk(mod.tree):
      if not isinstance(node, ast.Call):
        continue
      seg = astutil.last_segment(astutil.call_name(node))
      if seg != 'fault_point':
        continue
      if not node.args or not isinstance(node.args[0], ast.Constant) \
          or not isinstance(node.args[0].value, str):
        out.append(Finding(
            RULE, mod.path, mod.relpath, node.lineno,
            node.col_offset + 1,
            'fault_point name must be a string literal — a computed '
            'name cannot be armed deliberately from GLT_FAULTS or '
            'reviewed against docs/failure_model.md'))
        continue
      sites.setdefault(node.args[0].value, []).append((mod, node))

  if not sites:
    return out

  registered, reg_line, reg_mod = _parse_registry(registry_mod)
  documented = _documented_names(config)

  for name, occ in sorted(sites.items()):
    if len(occ) > 1:
      for mod, node in occ[1:]:
        first = occ[0][1].lineno
        out.append(Finding(
            RULE, mod.path, mod.relpath, node.lineno,
            node.col_offset + 1,
            f'duplicate fault site {name!r} (first at '
            f'{occ[0][0].relpath}:{first}) — arming it would fire two '
            'code paths at once; fault-site names are one-per-site'))
    mod, node = occ[0]
    if registered is not None and name not in registered:
      out.append(Finding(
          RULE, mod.path, mod.relpath, node.lineno, node.col_offset + 1,
          f'fault site {name!r} is not in utils/faults.py '
          'REGISTERED_SITES — add it to the registry (and to the '
          'docs/failure_model.md fault-site table)'))
    if documented is not None and name not in documented:
      out.append(Finding(
          RULE, mod.path, mod.relpath, node.lineno, node.col_offset + 1,
          f'fault site {name!r} is not documented in '
          f'{config.failure_doc} — add it to the fault-site table '
          '(what it injects, where, typical arming)'))

  if registered is not None:
    for name in sorted(registered - set(sites)):
      out.append(Finding(
          RULE, reg_mod.path, reg_mod.relpath, reg_line, 1,
          f'REGISTERED_SITES entry {name!r} has no fault_point call '
          'site — stale registration; remove it or restore the site'))
  elif registry_mod is not None:
    out.append(Finding(
        RULE, registry_mod.path, registry_mod.relpath, 1, 1,
        'utils/faults.py defines no REGISTERED_SITES frozenset — the '
        'fault-site registry is the anchor this rule checks against'))
  return out


def _parse_registry(registry_mod: Optional[ParsedModule]):
  """(names, lineno, module) from `REGISTERED_SITES = frozenset({...})`,
  or (None, 0, None) when unavailable."""
  if registry_mod is None:
    return None, 0, None
  for node in ast.walk(registry_mod.tree):
    if isinstance(node, ast.Assign):
      names = [t.id for t in node.targets if isinstance(t, ast.Name)]
      if 'REGISTERED_SITES' not in names:
        continue
      try:
        value = ast.literal_eval(node.value)
      except ValueError:
        # frozenset({...}) is a Call — evaluate its literal argument
        if isinstance(node.value, ast.Call) and node.value.args:
          try:
            value = ast.literal_eval(node.value.args[0])
          except ValueError:
            return None, 0, None
        else:
          return None, 0, None
      return set(value), node.lineno, registry_mod
  return None, 0, None


def _documented_names(config: Config) -> Optional[Set[str]]:
  if not config.repo_root:
    return None
  path = os.path.join(config.repo_root, config.failure_doc)
  if not os.path.exists(path):
    return None
  import re
  with open(path, encoding='utf-8') as fh:
    text = fh.read()
  return set(re.findall(r'`([a-z0-9_.]+)`', text))
