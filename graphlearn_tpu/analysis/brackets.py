"""Rule bracket-discipline: opened brackets must close on EVERY path.

The observability and fault layers are bracket APIs: ``spans.begin``
returns a token ``spans.end`` must consume, ``flight.epoch_begin``
returns a record ``flight.epoch_end``/``flight.end_for`` must complete,
``faults.arm`` must be met by ``faults.disarm``. A bracket left open on
ONE path is worse than no bracket at all — PR 8 fixed the same shape
three times: a prologue raise before the try block leaked the epoch
span onto the thread-context stack and mis-parented every later span;
an overflow-policy resolve inside the bracket turned a config error
into a permanently-open flight record.

This rule runs the bracket as a dataflow problem on the function CFG:
a token bound from an opener call is OPEN; a closer call naming it (or
a rebind) closes it; if an open token reaches function EXIT along any
edge — normal fall-through, early return, or an exception edge out of
any statement in between — the opener is a finding. The fix is always
the same and the message says so: move the opener's work into
``try/finally`` (or the ``with``-form, which closes structurally).

Escapes are quiet: a token that is returned, stored on ``self``,
packed into a container, or handed to a helper call leaves this
function's responsibility and stops being tracked. A bare opener call
whose token is DISCARDED (an expression statement) can never be closed
and is flagged immediately, as is calling a with-only context manager
(``strict_guards``, ``spans.span``) as a plain statement.
"""
import ast
from typing import Dict, List, Tuple

from . import astutil, flow
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'bracket-discipline'

# (opener names, closer names, what the token is)
_SPECS: Tuple[Tuple[Tuple[str, ...], Tuple[str, ...], str], ...] = (
    (('spans.begin',), ('spans.end',), 'span'),
    (('flight.epoch_begin',), ('flight.epoch_end', 'flight.end_for'),
     'flight record'),
    (('faults.arm',), ('faults.disarm',), 'armed fault region'),
)
# context managers with no token form: a bare call does nothing
_WITH_ONLY = ('strict_guards', 'spans.span')
# every bracket-API entry point: statements that are nothing but these
# calls are assumed exception-safe (closers MUST be — they run inside
# finally blocks by design), so no exception edge leaves them
_BRACKET_API = tuple(n for op, cl, _ in _SPECS for n in op + cl)


def check_package(modules: List[ParsedModule], config: Config):
  findings = []
  for mod in modules:
    if not in_scope(mod.relpath, config.bracket_modules):
      continue
    try:
      findings.extend(_check_module(mod, config))
    except RecursionError:
      pass
  return findings


def _check_module(mod: ParsedModule, config: Config) -> List[Finding]:
  index = astutil.FuncIndex(mod.tree)
  aliases = astutil.import_aliases(mod.tree)
  parents = astutil.parent_map(mod.tree)
  out: List[Finding] = []
  for fi in index.by_qual.values():
    out.extend(_check_function(mod, index, aliases, parents, fi))
  return out


def _call_matches(call: ast.Call, aliases, targets) -> bool:
  name = astutil.canonical(astutil.call_name(call), aliases)
  return astutil.matches(name, targets)


def _spec_of(call: ast.Call, aliases):
  for i, (openers, _closers, _label) in enumerate(_SPECS):
    if _call_matches(call, aliases, openers):
      return i
  return None


def _stmt_of(parents, node):
  while node is not None and not isinstance(node, ast.stmt):
    node = parents.get(node)
  return node


def _bracket_only_stmt(stmt: ast.stmt, aliases) -> bool:
  """True if the statement is a plain call (or tuple-assign of calls)
  whose every call is a bracket-API entry point — such statements are
  treated as non-raising."""
  if isinstance(stmt, (ast.Expr, ast.Assign)):
    val = stmt.value
  else:
    return False
  exprs = val.elts if isinstance(val, ast.Tuple) else [val]
  if not exprs:
    return False
  for e in exprs:
    if not (isinstance(e, ast.Call) and
            _call_matches(e, aliases, _BRACKET_API)):
      return False
  return True


def _check_function(mod, index, aliases, parents,
                    fi: astutil.FuncInfo) -> List[Finding]:
  # ---- collect opener sites in this function (own nodes only)
  tracked: Dict[str, Tuple[ast.Call, int]] = {}   # name -> (call, spec)
  findings: List[Finding] = []
  opener_calls = []
  for node in index.own_nodes(fi):
    if not isinstance(node, ast.Call):
      continue
    spec = _spec_of(node, aliases)
    if spec is not None:
      opener_calls.append((node, spec))
    elif isinstance(node.func, (ast.Name, ast.Attribute)) and \
        _call_matches(node, aliases, _WITH_ONLY):
      stmt = _stmt_of(parents, node)
      if isinstance(stmt, ast.Expr) and stmt.value is node:
        findings.append(Finding(
            RULE, mod.path, mod.relpath, node.lineno,
            node.col_offset + 1,
            f'{astutil.call_name(node)}(...) called as a bare statement '
            'does nothing — it is a context manager; use the with-form',
            symbol=fi.qualname))

  if not opener_calls:
    return findings

  for call, spec in opener_calls:
    stmt = _stmt_of(parents, call)
    if stmt is None:
      continue
    label = _SPECS[spec][2]
    if isinstance(stmt, (ast.With, ast.AsyncWith)) and \
        any(i.context_expr is call for i in stmt.items):
      continue   # structurally closed
    if isinstance(stmt, ast.Expr) and stmt.value is call:
      findings.append(Finding(
          RULE, mod.path, mod.relpath, call.lineno, call.col_offset + 1,
          f'{label} token discarded — bind the result of '
          f'{astutil.call_name(call)}(...) and close it in a finally',
          symbol=fi.qualname))
      continue
    name = None
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
      t = stmt.targets[0]
      if stmt.value is call and isinstance(t, ast.Name):
        name = t.id
      elif isinstance(stmt.value, ast.Tuple) and \
          isinstance(t, ast.Tuple) and \
          len(stmt.value.elts) == len(t.elts):
        for v, tt in zip(stmt.value.elts, t.elts):
          if v is call and isinstance(tt, ast.Name):
            name = tt.id
    if name is None:
      continue   # returned / stored / passed on: escapes, err quiet
    # two openers into one name: track the last only (quiet)
    tracked[name] = (call, spec)

  if not tracked:
    return findings

  # ---- dataflow: which tokens may still be open at EXIT
  closers = {name: _SPECS[spec][1] for name, (_c, spec) in tracked.items()}

  def closed_or_escaped(stmt) -> set:
    """Token names this statement closes (closer call argument) or
    hands off (argument to any other call / returned / yielded)."""
    gone = set()
    for call in flow.stmt_calls(stmt):
      arg_names = {a.id for a in call.args if isinstance(a, ast.Name)}
      arg_names |= {k.value.id for k in call.keywords
                    if isinstance(k.value, ast.Name)}
      for name in arg_names & set(closers):
        gone.add(name)   # closer closes it; anything else takes it over
    if isinstance(stmt, ast.Return) and stmt.value is not None:
      for n in ast.walk(stmt.value):
        if isinstance(n, ast.Name) and n.id in closers:
          gone.add(n.id)
    return gone

  gen: Dict[int, str] = {}
  for name, (call, _spec) in tracked.items():
    stmt = _stmt_of(parents, call)
    gen[id(stmt)] = name

  def transfer(n, stmt, state):
    if stmt is None:
      return state
    gone = closed_or_escaped(stmt)
    state = frozenset(e for e in state if e not in gone)
    writes = flow.stmt_writes(stmt)
    state = frozenset(e for e in state
                      if e not in writes or gen.get(id(stmt)) == e)
    name = gen.get(id(stmt))
    if name is not None:
      state = state | {name}
    return state

  def exc_transfer(n, stmt, state):
    # an opener that raised never bound its token; a closer that raised
    # is treated as having closed (quiet side). Statements that are
    # nothing but bracket-API calls are assumed not to raise at all —
    # the merge is a union, so contributing the empty set makes that
    # impossible edge vacuous.
    if stmt is None:
      return state
    if _bracket_only_stmt(stmt, aliases):
      return frozenset()
    gone = closed_or_escaped(stmt)
    return frozenset(e for e in state if e not in gone)

  cfg = flow.build_cfg(fi.node)
  in_s = flow.forward(cfg, frozenset(), transfer, exc_transfer)
  for name in sorted(in_s[flow.EXIT]):
    call, spec = tracked[name]
    label = _SPECS[spec][2]
    closer_names = ' / '.join(_SPECS[spec][1])
    findings.append(Finding(
        RULE, mod.path, mod.relpath, call.lineno, call.col_offset + 1,
        f"{label} '{name}' opened here may not be closed on every "
        f'path (exception or early return) — close it with '
        f'{closer_names} in a try/finally, or use the with-form',
        symbol=fi.qualname))
  findings.sort(key=lambda f: (f.line, f.col))
  return findings
