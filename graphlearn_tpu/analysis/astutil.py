"""Shared AST machinery for graftlint checkers.

Everything here is best-effort, per-module, name-based dataflow — the
goal is catching the regressions this codebase's conventions make
likely, not soundness. Where resolution fails we err on the quiet side
(a missed edge), and the conventions themselves (nested defs are traced
program bodies; builders return their jitted programs) close most of
the gap. docs/static_analysis.md spells out the approximations.
"""
import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

# jax transforms whose callable argument is traced
TRACING_CALLS = {
    'jax.jit', 'jit',
    'jax.vmap', 'vmap', 'jax.pmap', 'pmap',
    'jax.grad', 'grad', 'jax.value_and_grad', 'value_and_grad',
    'jax.checkpoint', 'jax.remat',
    'lax.scan', 'jax.lax.scan', 'lax.cond', 'jax.lax.cond',
    'lax.while_loop', 'jax.lax.while_loop',
    'lax.fori_loop', 'jax.lax.fori_loop', 'lax.switch', 'jax.lax.switch',
    'lax.map', 'jax.lax.map', 'lax.associative_scan',
    'shard_map',   # the compat wrapper (direct jax use is its own rule)
}


def dotted_name(node: ast.AST) -> Optional[str]:
  """'jax.random.split' for Attribute/Name chains, else None."""
  parts = []
  while isinstance(node, ast.Attribute):
    parts.append(node.attr)
    node = node.value
  if isinstance(node, ast.Name):
    parts.append(node.id)
    return '.'.join(reversed(parts))
  return None


def call_name(call: ast.Call) -> Optional[str]:
  return dotted_name(call.func)


def last_segment(name: Optional[str]) -> Optional[str]:
  return name.rsplit('.', 1)[-1] if name else None


def matches(name: Optional[str], targets) -> bool:
  """Dotted-name match, exact or by trailing segments ('random.split'
  matches 'jax.random.split'). A BARE name only matches exactly —
  otherwise the builtin ``map`` (or a local ``cond``/``scan`` helper)
  would match 'lax.map' and mint false tracing roots; bare forms that
  should match are listed explicitly in TRACING_CALLS."""
  if not name:
    return False
  for t in targets:
    if name == t or name.endswith('.' + t) or \
        ('.' in name and t.endswith('.' + name)):
      return True
  return False


def import_aliases(tree: ast.AST) -> Dict[str, str]:
  """name -> canonical dotted path, from this module's imports.
  Relative imports keep their trailing module path ('..utils.compat'
  -> 'utils.compat'), enough for suffix matching."""
  out: Dict[str, str] = {}
  for node in ast.walk(tree):
    if isinstance(node, ast.Import):
      for a in node.names:
        if a.asname:
          out[a.asname] = a.name
    elif isinstance(node, ast.ImportFrom):
      base = (node.module or '').lstrip('.')
      for a in node.names:
        full = f'{base}.{a.name}' if base else a.name
        out[a.asname or a.name] = full
  return out


def canonical(name: Optional[str], aliases: Dict[str, str]) -> Optional[str]:
  """Expand the leading alias segment: 'np.asarray' -> 'numpy.asarray'."""
  if not name:
    return None
  head, _, rest = name.partition('.')
  base = aliases.get(head, head)
  return f'{base}.{rest}' if rest else base


# ------------------------------------------------------------ function index

class FuncInfo:
  __slots__ = ('node', 'qualname', 'parent', 'nested', 'returned_defs',
               'is_nested')

  def __init__(self, node, qualname, parent):
    self.node = node
    self.qualname = qualname
    self.parent = parent          # enclosing FuncInfo or None
    self.nested: List['FuncInfo'] = []
    self.returned_defs: Set[str] = set()   # qualnames this fn may return
    self.is_nested = parent is not None


class FuncIndex:
  """All function defs in a module, with name->defs lookup and which
  nested defs each def may return (builders returning program bodies)."""

  def __init__(self, tree: ast.AST):
    self.by_qual: Dict[str, FuncInfo] = {}
    self.by_name: Dict[str, List[FuncInfo]] = {}
    self._walk(tree, None, '')
    for fi in self.by_qual.values():
      fi.returned_defs = self._returned_defs(fi)

  def _walk(self, node, parent: Optional[FuncInfo], prefix: str):
    for child in ast.iter_child_nodes(node):
      if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
        qual = f'{prefix}{child.name}'
        # a def whose immediate container is a class is a method, not a
        # traced closure of `parent`
        method = isinstance(node, ast.ClassDef)
        fi = FuncInfo(child, qual, None if method else parent)
        self.by_qual[qual] = fi
        self.by_name.setdefault(child.name, []).append(fi)
        if fi.parent is not None:
          fi.parent.nested.append(fi)
        self._walk(child, fi, qual + '.')
      elif isinstance(child, ast.ClassDef):
        self._walk(child, None, f'{prefix}{child.name}.')
      else:
        # defs under if/try/with keep the same enclosing function
        self._walk(child, parent, prefix)

  def _returned_defs(self, fi: FuncInfo) -> Set[str]:
    local_defs = {n.node.name: n.qualname for n in fi.nested}
    var_defs: Dict[str, str] = {}
    for node in self.own_nodes(fi):
      if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name):
        if node.value.id in local_defs:
          for t in node.targets:
            if isinstance(t, ast.Name):
              var_defs[t.id] = local_defs[node.value.id]
    out: Set[str] = set()

    def resolve(expr):
      if isinstance(expr, ast.Name):
        q = local_defs.get(expr.id) or var_defs.get(expr.id)
        if q:
          out.add(q)
      elif isinstance(expr, ast.Tuple):
        for e in expr.elts:
          resolve(e)

    for node in self.own_nodes(fi):
      if isinstance(node, ast.Return) and node.value is not None:
        resolve(node.value)
    return out

  def own_nodes(self, fi: FuncInfo) -> Iterator[ast.AST]:
    """Nodes of ``fi`` excluding nested function bodies."""
    stack = list(ast.iter_child_nodes(fi.node))
    while stack:
      n = stack.pop()
      if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef)):
        continue
      yield n
      stack.extend(ast.iter_child_nodes(n))

  def lookup(self, node: ast.AST) -> Optional[FuncInfo]:
    for fi in self.by_name.get(getattr(node, 'name', ''), []):
      if fi.node is node:
        return fi
    return None


# --------------------------------------------------------------- bindings

def local_bindings(index: FuncIndex,
                   fi: FuncInfo) -> Dict[str, Tuple[str, str]]:
  """name -> (kind, target) for assignments visible in ``fi``'s scope
  chain. kind 'ref': `x = self._foo` / `x = foo` — calling x calls
  target. kind 'result': `x = self._foo(...)` — calling x calls what
  target RETURNS. Inner scopes shadow outer ones."""
  out: Dict[str, Tuple[str, str]] = {}
  chain = []
  f = fi
  while f is not None:
    chain.append(f)
    f = f.parent
  for f in reversed(chain):
    for node in index.own_nodes(f):
      if not isinstance(node, ast.Assign):
        continue
      src = node.value
      entry = None
      if isinstance(src, ast.Call):
        seg = last_segment(call_name(src))
        if seg:
          entry = ('result', seg)
      elif isinstance(src, (ast.Attribute, ast.Name)):
        seg = last_segment(dotted_name(src))
        if seg:
          entry = ('ref', seg)
      if entry is None:
        continue
      for t in node.targets:
        if isinstance(t, ast.Name):
          out[t.id] = entry
  return out


# --------------------------------------------------------------- traced set

def traced_functions(index: FuncIndex, tree: ast.AST,
                     aliases: Dict[str, str]) -> Set[str]:
  """Qualnames of functions whose bodies run under tracing.

  Seeds: callables handed to jax transforms (jit/scan/shard_map/...,
  call or decorator form) plus NESTED defs — in this codebase a closure
  inside a program builder is, by convention, a traced program body.
  Host-side closures are excluded when recognizable: a nested def that
  records dispatches or calls through a name bound to a jax.jit result
  is a host dispatch wrapper, not a traced body.

  Closure: a def referenced inside a traced function is traced, and a
  call through a 'result' binding traces the bound builder's RETURNED
  defs (`core = self._shard_body(b)` => _shard_body's nested `body`)."""
  traced: Set[str] = set()
  pending: List[FuncInfo] = []

  def mark(fi: Optional[FuncInfo]):
    if fi is not None and fi.qualname not in traced:
      traced.add(fi.qualname)
      pending.append(fi)

  def mark_name(name: Optional[str]):
    for fi in index.by_name.get(name or '', []):
      mark(fi)

  # decorator roots: @jax.jit / @functools.partial(jax.jit, ...)
  for fi in index.by_qual.values():
    for dec in fi.node.decorator_list:
      if isinstance(dec, ast.Call):
        name = canonical(call_name(dec), aliases)
        if matches(name, {'functools.partial', 'partial'}) and dec.args:
          name = canonical(dotted_name(dec.args[0]), aliases)
      else:
        name = canonical(dotted_name(dec), aliases)
      if matches(name, TRACING_CALLS):
        mark(fi)

  # call-argument roots: jax.jit(f) / lax.scan(body, ...) / partial forms
  for node in ast.walk(tree):
    if not isinstance(node, ast.Call):
      continue
    name = canonical(call_name(node), aliases)
    target = None
    if matches(name, TRACING_CALLS) and node.args:
      target = node.args[0]
    elif matches(name, {'functools.partial', 'partial'}) and \
        len(node.args) > 1:
      inner = canonical(dotted_name(node.args[0]), aliases)
      if matches(inner, TRACING_CALLS):
        target = node.args[1]
    if target is not None:
      mark_name(last_segment(dotted_name(target)))

  # nested-def convention, minus host dispatch wrappers
  for fi in index.by_qual.values():
    if fi.is_nested and not _is_host_wrapper(index, fi):
      mark(fi)

  while pending:
    fi = pending.pop()
    bindings = local_bindings(index, fi)
    shadowed = _locally_bound_names(index, fi)
    for node in index.own_nodes(fi):
      name = None
      is_bare = False
      if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
        name, is_bare = node.id, True
      elif isinstance(node, ast.Attribute):
        name = node.attr
      if not name:
        continue
      kind_target = bindings.get(name)
      if kind_target is not None:
        kind, seg = kind_target
        if kind == 'ref':
          mark_name(seg)
        else:   # result-of-call: the builder's returned bodies run traced
          for builder in index.by_name.get(seg, []):
            for q in builder.returned_defs:
              mark(index.by_qual.get(q))
        continue
      if is_bare and name in shadowed:
        # a parameter / local variable shadows any same-named module
        # function (e.g. a scan body's `stats` arg vs. a host-side
        # `stats()` method) — loading it is not a function reference
        continue
      mark_name(name)
  return traced


def _locally_bound_names(index: FuncIndex, fi: FuncInfo) -> Set[str]:
  """Names bound as data (params, assignment targets, loop/with/except
  targets) anywhere in ``fi``'s enclosing-def chain. Nested function
  defs are deliberately NOT included — referencing one by name IS a
  traced-callable reference."""
  out: Set[str] = set()
  f = fi
  while f is not None:
    a = f.node.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs +
                [a.vararg, a.kwarg]):
      if arg is not None:
        out.add(arg.arg)
    for node in index.own_nodes(f):
      if isinstance(node, ast.Name) and \
          isinstance(node.ctx, (ast.Store, ast.Del)):
        out.add(node.id)
    f = f.parent
  return out


def _is_host_wrapper(index: FuncIndex, fi: FuncInfo) -> bool:
  """A nested def that performs host-side dispatch bookkeeping."""
  for node in index.own_nodes(fi):
    if isinstance(node, ast.Call):
      seg = last_segment(call_name(node))
      if seg in ('record_dispatch', 'wrap_dispatch'):
        return True
      if seg and _binds_jit(index, fi, seg):
        return True
  return False


def _binds_jit(index: FuncIndex, fi: FuncInfo, name: str) -> bool:
  """True if ``name`` is bound to a jax.jit(...) result in fi's
  enclosing def chain (the `jfn = jax.jit(fn)` ... `jfn(...)` shape)."""
  f = fi.parent
  while f is not None:
    for node in index.own_nodes(f):
      if isinstance(node, ast.Assign) and \
          isinstance(node.value, ast.Call) and \
          last_segment(call_name(node.value)) == 'jit':
        for t in node.targets:
          if isinstance(t, ast.Name) and t.id == name:
            return True
    f = f.parent
  return False


# ------------------------------------------------------------------ parents

def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
  parents: Dict[ast.AST, ast.AST] = {}
  for node in ast.walk(tree):
    for child in ast.iter_child_nodes(node):
      parents[child] = node
  return parents


def enclosing_function(index: FuncIndex, node: ast.AST,
                       parents: Dict[ast.AST, ast.AST]) -> Optional[FuncInfo]:
  n = parents.get(node)
  while n is not None:
    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
      return index.lookup(n)
    n = parents.get(n)
  return None
