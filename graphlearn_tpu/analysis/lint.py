"""graftlint CLI.

Usage::

    python -m graphlearn_tpu.analysis.lint graphlearn_tpu/
    python -m graphlearn_tpu.analysis.lint --write-baseline graphlearn_tpu/
    python -m graphlearn_tpu.analysis.lint --list-rules

Exit codes: 0 clean (after pragmas + baseline), 1 findings, 2 usage /
internal error. The default baseline is ``graftlint.baseline.json``
next to the linted package (kept EMPTY in this repo — the tier-1 suite
enforces it; see docs/static_analysis.md for the debt workflow).
"""
import argparse
import os
import sys

from .core import (PRAGMA_RULES, Config, load_baseline, run_lint,
                   write_baseline)

_RULE_DOCS = {
    'host-sync':
        'device->host sync calls (.item/.tolist/int()/float()/bool()/'
        'np.asarray/jax.device_get/block_until_ready) reachable from '
        'jitted scan/shard_map bodies in hot modules',
    'prng-discipline':
        'split-and-carry keys, constant keys in loops, and key reuse in '
        'sampler/loader modules — the fold_in counter pattern is the '
        'contract scan replay depends on',
    'dispatch-instrumentation':
        'jax.jit / jitted shard_map entrypoints dispatched without '
        'record_dispatch/wrap_dispatch in hot modules',
    'compat-shard-map':
        'shard_map imported from jax directly instead of utils/compat.py',
    'fault-point-coverage':
        'fault_point sites must be literal, unique, in '
        'utils/faults.py REGISTERED_SITES, and documented in '
        'docs/failure_model.md',
    'metric-registry':
        'metric names (counter_inc / metrics.inc/observe/set_gauge/'
        'counter/gauge/histogram) must be string literals registered '
        'in metrics/registry_names.py REGISTERED_METRICS and '
        'documented in docs/observability.md',
    'span-registry':
        'span names (spans.span/begin/emit) must be string literals '
        'registered in metrics/registry_names.py REGISTERED_SPANS and '
        'documented in the docs/observability.md span table',
    'hetero-gate':
        'is_hetero-gated raise/warn outside sampler/capacity.py must '
        'raise CapacityPlanError (the typed refusal naming the missing '
        'plan input, docs/capacity_plans.md) or carry an allow pragma '
        'for a real semantic boundary',
}


def _default_baseline(paths):
  for p in paths:
    p = os.path.abspath(p)
    d = p if os.path.isdir(p) else os.path.dirname(p)
    cand = os.path.join(os.path.dirname(d.rstrip(os.sep)),
                        'graftlint.baseline.json')
    if os.path.exists(cand):
      return cand
    cand = os.path.join(d, 'graftlint.baseline.json')
    if os.path.exists(cand):
      return cand
  return None


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(
      prog='python -m graphlearn_tpu.analysis.lint',
      description='graftlint: hot-path invariant checks for '
                  'graphlearn_tpu (see docs/static_analysis.md)')
  ap.add_argument('paths', nargs='*', help='files or directories to lint')
  ap.add_argument('--baseline', default=None,
                  help='baseline JSON (default: graftlint.baseline.json '
                       'next to the linted package, when present)')
  ap.add_argument('--no-baseline', action='store_true',
                  help='ignore any baseline file')
  ap.add_argument('--write-baseline', action='store_true',
                  help='accept current findings into the baseline file')
  ap.add_argument('--list-rules', action='store_true')
  ap.add_argument('-q', '--quiet', action='store_true',
                  help='summary line only')
  args = ap.parse_args(argv)

  if args.list_rules:
    for rule in PRAGMA_RULES:
      print(f'{rule}\n    {_RULE_DOCS[rule]}')
    return 0
  if not args.paths:
    ap.print_usage(sys.stderr)
    print('error: no paths given (try: graphlearn_tpu/)', file=sys.stderr)
    return 2

  baseline_path = args.baseline or _default_baseline(args.paths)
  baseline = set()
  if baseline_path and not args.no_baseline and not args.write_baseline:
    try:
      baseline = load_baseline(baseline_path)
    except (ValueError, OSError) as e:
      print(f'error: {e}', file=sys.stderr)
      return 2

  findings, n_pragma, n_base, modules = run_lint(args.paths, Config(),
                                                 baseline)

  if args.write_baseline:
    path = baseline_path or os.path.join(
        os.path.abspath(args.paths[0]), '..', 'graftlint.baseline.json')
    path = os.path.normpath(path)
    write_baseline(path, findings, modules)
    print(f'wrote {len(findings)} fingerprint(s) to {path}')
    return 0

  if not args.quiet:
    for f in findings:
      print(f.render())
  nfiles = len(modules)
  extras = []
  if n_pragma:
    extras.append(f'{n_pragma} pragma-suppressed')
  if n_base:
    extras.append(f'{n_base} baselined')
  extra = f' ({", ".join(extras)})' if extras else ''
  print(f'graftlint: {len(findings)} finding(s) in {nfiles} file(s)'
        f'{extra}')
  return 1 if findings else 0


if __name__ == '__main__':
  sys.exit(main())
