"""graftlint CLI.

Usage::

    python -m graphlearn_tpu.analysis.lint graphlearn_tpu/
    python -m graphlearn_tpu.analysis.lint --format json graphlearn_tpu/
    python -m graphlearn_tpu.analysis.lint --changed-only graphlearn_tpu/
    python -m graphlearn_tpu.analysis.lint --profile bench benchmarks/
    python -m graphlearn_tpu.analysis.lint --write-baseline graphlearn_tpu/
    python -m graphlearn_tpu.analysis.lint --list-rules

Exit codes: 0 clean (after pragmas + baseline), 1 findings, 2 usage /
internal error. The default baseline is ``graftlint.baseline.json``
next to the linted package (kept EMPTY in this repo — the tier-1 suite
enforces it; see docs/static_analysis.md for the debt workflow).

``--changed-only`` still parses and analyses every given path — the
cross-module rules (registries, lock-order cycles, retrace closure
functions) need whole-tree context to be sound — and then REPORTS only
findings in files touched vs ``--base-ref`` (default HEAD, plus
staged/unstaged/untracked). Use it in pre-commit hooks to see only
your own debt without weakening the analysis.

``--profile bench`` is the relaxed profile for benchmarks/ and
bench.py: the registry rules (metric/span/fault-point names), bracket
discipline and donation safety stay enforced — a benchmark that leaks
spans or reads donated buffers measures garbage — while the hot-path
scoping rules (host-sync, dispatch instrumentation, prng discipline,
retrace hazards, lock discipline) are exempt: benchmarks host-sync on
purpose, drive dispatch directly and probe shapes off the ladder.
"""
import argparse
import json
import os
import subprocess
import sys

from .core import (PRAGMA_RULES, Config, load_baseline, run_lint,
                   write_baseline)

_RULE_DOCS = {
    'host-sync':
        'device->host sync calls (.item/.tolist/int()/float()/bool()/'
        'np.asarray/jax.device_get/block_until_ready) reachable from '
        'jitted scan/shard_map bodies in hot modules',
    'prng-discipline':
        'split-and-carry keys, constant keys in loops, and key reuse in '
        'sampler/loader modules — the fold_in counter pattern is the '
        'contract scan replay depends on',
    'dispatch-instrumentation':
        'jax.jit / jitted shard_map entrypoints dispatched without '
        'record_dispatch/wrap_dispatch in hot modules',
    'compat-shard-map':
        'shard_map imported from jax directly instead of utils/compat.py',
    'fault-point-coverage':
        'fault_point sites must be literal, unique, in '
        'utils/faults.py REGISTERED_SITES, and documented in '
        'docs/failure_model.md',
    'metric-registry':
        'metric names (counter_inc / metrics.inc/observe/set_gauge/'
        'counter/gauge/histogram) must be string literals registered '
        'in metrics/registry_names.py REGISTERED_METRICS and '
        'documented in docs/observability.md',
    'span-registry':
        'span names (spans.span/begin/emit) must be string literals '
        'registered in metrics/registry_names.py REGISTERED_SPANS and '
        'documented in the docs/observability.md span table',
    'hetero-gate':
        'is_hetero-gated raise/warn outside sampler/capacity.py must '
        'raise CapacityPlanError (the typed refusal naming the missing '
        'plan input, docs/capacity_plans.md) or carry an allow pragma '
        'for a real semantic boundary',
    'donation-safety':
        'a buffer passed through a donate_argnums position is DEAD at '
        'dispatch; flow-aware check that no path reads it before the '
        'rebind (the PR 7 empty-path / failed-refresh bug class)',
    'bracket-discipline':
        'spans.begin / flight.epoch_begin / faults.arm tokens must '
        'provably close on EVERY outgoing path (exception edges '
        'included) — the PR 8 leaked-epoch-span bug class; fix with '
        'try/finally or the with-form',
    'retrace-hazard':
        'len()/.shape-derived values flowing into static jit arguments '
        'without passing a registered closure function (pow2_cap / '
        'capacity ladder) — the lint-time twin of the runtime '
        'retrace_budget guard',
    'lock-discipline':
        "fields annotated '# graftlint: shared[<lock>]' accessed "
        "outside a with-block holding the lock (or a '# graftlint: "
        "locked[<lock>]' method), plus cross-module lock-order cycle "
        'detection over with-nesting and call edges',
}


def _default_baseline(paths):
  for p in paths:
    p = os.path.abspath(p)
    d = p if os.path.isdir(p) else os.path.dirname(p)
    cand = os.path.join(os.path.dirname(d.rstrip(os.sep)),
                        'graftlint.baseline.json')
    if os.path.exists(cand):
      return cand
    cand = os.path.join(d, 'graftlint.baseline.json')
    if os.path.exists(cand):
      return cand
  return None


def _profile_config(profile: str) -> Config:
  if profile == 'bench':
    # see the module docstring: registries + brackets + donation stay
    # on, the hot-path scoping rules are exempt for benchmark code
    return Config(hot_sync_modules=(), dispatch_modules=(),
                  prng_modules=(), retrace_modules=(), lock_modules=())
  return Config()


def _changed_files(paths, base_ref: str):
  """Absolute paths of files changed vs ``base_ref`` (diff against the
  ref + staged + unstaged + untracked), or None when git is unusable —
  the caller then reports everything rather than hiding findings."""
  anchor = os.path.abspath(paths[0])
  cwd = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
  changed = set()
  cmds = [['git', 'diff', '--name-only', base_ref],
          ['git', 'ls-files', '--others', '--exclude-standard']]
  try:
    top = subprocess.run(['git', 'rev-parse', '--show-toplevel'],
                         cwd=cwd, capture_output=True, text=True,
                         timeout=30)
    if top.returncode != 0:
      return None
    root = top.stdout.strip()
    for cmd in cmds:
      r = subprocess.run(cmd, cwd=root, capture_output=True, text=True,
                         timeout=60)
      if r.returncode != 0:
        return None
      changed.update(os.path.abspath(os.path.join(root, line))
                     for line in r.stdout.splitlines() if line)
  except (OSError, subprocess.SubprocessError):
    return None
  return changed


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(
      prog='python -m graphlearn_tpu.analysis.lint',
      description='graftlint: hot-path invariant checks for '
                  'graphlearn_tpu (see docs/static_analysis.md)')
  ap.add_argument('paths', nargs='*', help='files or directories to lint')
  ap.add_argument('--baseline', default=None,
                  help='baseline JSON (default: graftlint.baseline.json '
                       'next to the linted package, when present)')
  ap.add_argument('--no-baseline', action='store_true',
                  help='ignore any baseline file')
  ap.add_argument('--write-baseline', action='store_true',
                  help='accept current findings into the baseline file')
  ap.add_argument('--list-rules', action='store_true')
  ap.add_argument('--format', choices=('text', 'json'), default='text',
                  help='output format; json includes per-rule timings')
  ap.add_argument('--timings', action='store_true',
                  help='print per-rule wall time after the summary')
  ap.add_argument('--changed-only', action='store_true',
                  help='analyse everything, report only findings in '
                       'files changed vs --base-ref (+ staged/untracked)')
  ap.add_argument('--base-ref', default='HEAD',
                  help='git ref --changed-only diffs against '
                       '(default: HEAD)')
  ap.add_argument('--profile', choices=('default', 'bench'),
                  default='default',
                  help="'bench': relaxed scoping for benchmarks/ and "
                       'bench.py (registries/brackets/donation still '
                       'enforced)')
  ap.add_argument('-q', '--quiet', action='store_true',
                  help='summary line only')
  args = ap.parse_args(argv)

  if args.list_rules:
    for rule in PRAGMA_RULES:
      print(f'{rule}\n    {_RULE_DOCS[rule]}')
    return 0
  if not args.paths:
    ap.print_usage(sys.stderr)
    print('error: no paths given (try: graphlearn_tpu/)', file=sys.stderr)
    return 2

  baseline_path = args.baseline or _default_baseline(args.paths)
  baseline = set()
  if baseline_path and not args.no_baseline and not args.write_baseline:
    try:
      baseline = load_baseline(baseline_path)
    except (ValueError, OSError) as e:
      print(f'error: {e}', file=sys.stderr)
      return 2

  result = run_lint(args.paths, _profile_config(args.profile), baseline)
  findings, n_pragma, n_base, modules = result

  if args.write_baseline:
    path = baseline_path or os.path.join(
        os.path.abspath(args.paths[0]), '..', 'graftlint.baseline.json')
    path = os.path.normpath(path)
    write_baseline(path, findings, modules)
    print(f'wrote {len(findings)} fingerprint(s) to {path}')
    return 0

  n_analysed = len(findings)
  if args.changed_only:
    changed = _changed_files(args.paths, args.base_ref)
    if changed is None:
      print('graftlint: --changed-only: git unavailable, reporting all '
            'findings', file=sys.stderr)
    else:
      findings = [f for f in findings
                  if os.path.abspath(f.path) in changed]

  nfiles = len(modules)
  if args.format == 'json':
    doc = {
        'findings': [{'rule': f.rule, 'path': f.path,
                      'relpath': f.relpath, 'line': f.line, 'col': f.col,
                      'message': f.message, 'symbol': f.symbol}
                     for f in findings],
        'files': nfiles,
        'pragma_suppressed': n_pragma,
        'baselined': n_base,
        'changed_only': bool(args.changed_only),
        'analysed_findings': n_analysed,
        'profile': args.profile,
        'timings_ms': {rule: round(dt * 1e3, 2)
                       for rule, dt in sorted(result.timings.items())},
    }
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 1 if findings else 0

  if not args.quiet:
    for f in findings:
      print(f.render())
  extras = []
  if n_pragma:
    extras.append(f'{n_pragma} pragma-suppressed')
  if n_base:
    extras.append(f'{n_base} baselined')
  if args.changed_only and n_analysed != len(findings):
    extras.append(f'{n_analysed - len(findings)} outside --changed-only')
  extra = f' ({", ".join(extras)})' if extras else ''
  print(f'graftlint: {len(findings)} finding(s) in {nfiles} file(s)'
        f'{extra}')
  if args.timings:
    total = sum(result.timings.values())
    for rule, dt in sorted(result.timings.items(),
                           key=lambda kv: -kv[1]):
      print(f'  {rule:28s} {dt * 1e3:9.1f} ms')
    print(f'  {"total (rules)":28s} {total * 1e3:9.1f} ms')
  return 1 if findings else 0


if __name__ == '__main__':
  sys.exit(main())
