"""graftlint core: findings, pragmas, baseline, module loading, runner.

Checker modules (host_sync, prng, dispatch, compat_import, fault_points)
each expose ``RULE`` (the rule id) and ``check_package(modules, config)``
returning findings over the whole parsed-module set — package-wide scope
is the common case (fault-point uniqueness spans files), and per-file
rules simply loop.

Suppression layers, in order:

  1. pragma — ``# graftlint: allow[<rule>] <reason>`` on the flagged
     line (or on a line of its own directly above it) suppresses that
     rule there. A reason is REQUIRED: an unexplained exception is
     itself a finding (rule ``pragma``), as is an unknown rule name.
  2. baseline — a checked-in JSON of finding fingerprints
     (``graftlint.baseline.json``) for debt accepted at introduction.
     Fingerprints hash (rule, relpath, stripped source line, occurrence
     index), not line numbers, so unrelated edits don't churn it. The
     shipped baseline is EMPTY and the tier-1 suite keeps it that way.
"""
import ast
import hashlib
import json
import os
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
  rule: str
  path: str          # absolute file path
  relpath: str       # package-relative (the scoping + fingerprint key)
  line: int
  col: int
  message: str
  symbol: str = ''   # enclosing function qualname, when known

  def location(self) -> str:
    return f'{self.relpath}:{self.line}'

  def render(self) -> str:
    sym = f' [{self.symbol}]' if self.symbol else ''
    return f'{self.relpath}:{self.line}:{self.col}: {self.rule}: ' \
           f'{self.message}{sym}'


@dataclass
class Config:
  """Scoping knobs. Defaults encode THIS repo's hot-path contracts;
  tests override them to point rules at fixture files.

  Module patterns are package-relative posix paths: a pattern ending in
  '/' is a directory prefix, '*' matches every module, anything else is
  an exact file match.
  """
  # rule host-sync: modules whose traced code must be sync-free
  # (storage/ carries the tiered scanned-chunk + plan programs;
  # recovery/ rides the chunk-boundary hooks inside the guarded epoch)
  hot_sync_modules: Tuple[str, ...] = (
      'loader/scan_epoch.py', 'loader/pipeline.py',
      'loader/run_epoch.py',
      'distributed/dist_feature.py', 'distributed/dist_neighbor_sampler.py',
      'distributed/remote_scan.py', 'distributed/block_producer.py',
      # tune/ drives candidate A/B epochs through the scanned trainers:
      # its probe loops sit on the same guarded hot path they score
      'ops/', 'serving/', 'storage/', 'recovery/', 'tune/')
  # rule dispatch-instrumentation: modules whose jit entrypoints must
  # record dispatches (the dispatch-budget tests' instrumented surface)
  dispatch_modules: Tuple[str, ...] = (
      'loader/scan_epoch.py', 'loader/pipeline.py', 'loader/node_loader.py',
      'distributed/dist_feature.py', 'distributed/dist_neighbor_sampler.py',
      'distributed/dist_loader.py', 'distributed/remote_scan.py',
      'distributed/block_producer.py', 'sampler/neighbor_sampler.py',
      'data/unified_tensor.py', 'serving/', 'storage/', 'recovery/',
      # Pallas kernel modules (ISSUE 13): their host-level routing
      # wrappers dispatch module-jitted impls and must stay budgeted
      'ops/gather_pallas.py', 'ops/sample_fused.py',
      # round 15: the run program's jit entrypoints and the tuner's
      # candidate A/B epochs carry the same dispatch-budget contract
      'loader/run_epoch.py', 'tune/')
  # cross-module jit factories the per-module dataflow can't see: calls
  # to these names yield jitted callables (models/train.py builders)
  known_jit_factories: Tuple[str, ...] = ('make_train_step',)
  # rule prng-discipline: sampler/loader surfaces with replay contracts
  prng_modules: Tuple[str, ...] = ('sampler/', 'loader/', 'distributed/')
  # rule compat-shard-map: the one module allowed to touch jax shard_map
  compat_module: str = 'utils/compat.py'
  # rule fault-point-coverage inputs (package-relative / repo-relative)
  fault_registry_module: str = 'utils/faults.py'
  failure_doc: str = 'docs/failure_model.md'
  # rule metric-registry inputs: the closed metric-name frozenset, its
  # documentation table, and the modules exempt from call-site checks
  # (the metrics package itself registers/loops over names as data)
  metrics_registry_module: str = 'metrics/registry_names.py'
  observability_doc: str = 'docs/observability.md'
  metrics_exempt_modules: Tuple[str, ...] = ('metrics/',)
  # flow-aware rules (donation-safety / bracket-discipline /
  # retrace-hazard / lock-discipline): scoped package-wide by default —
  # they key on idioms (donating handles, bracket openers, static jit
  # slots, shared[] annotations) rather than on module lists
  donation_modules: Tuple[str, ...] = ('*',)
  bracket_modules: Tuple[str, ...] = ('*',)
  retrace_modules: Tuple[str, ...] = ('*',)
  lock_modules: Tuple[str, ...] = ('*',)
  # rule retrace-hazard: the registered closure functions — a dynamic
  # size that passes through one of these lands in the closed static
  # set (docs/capacity_plans.md) and stops being a hazard
  retrace_closure_fns: Tuple[str, ...] = (
      'pow2_cap', 'pow2_slab_cap', 'round8', 'exchange_capacity',
      'miss_capacity', 'capacity_plan', 'hetero_capacity_plan',
      'probe_chunk_k', 'probe_slab_cap', 'clamp_etype_cap')
  # resolved at run time from the linted paths unless set explicitly
  repo_root: Optional[str] = None


@dataclass
class ParsedModule:
  path: str
  relpath: str
  source: str
  lines: List[str]
  tree: ast.AST
  # line -> set of rule names a pragma allows there (after same-line +
  # line-above expansion); '' entries mean a malformed pragma finding
  pragmas: Dict[int, set] = field(default_factory=dict)
  pragma_findings: List[Finding] = field(default_factory=list)
  # line -> [(kind, arg)] for the non-allow annotation forms the lock
  # rule consumes: '# graftlint: shared[<lock>]' on a field's defining
  # assignment, '# graftlint: locked[<lock>]' on a def
  annotations: Dict[int, list] = field(default_factory=dict)


def in_scope(relpath: str, patterns: Sequence[str]) -> bool:
  for p in patterns:
    if p == '*':
      return True
    if p.endswith('/') and relpath.startswith(p):
      return True
    if relpath == p:
      return True
  return False


# ------------------------------------------------------------------ pragmas

PRAGMA_RULES = ('host-sync', 'prng-discipline', 'dispatch-instrumentation',
                'compat-shard-map', 'fault-point-coverage',
                'metric-registry', 'span-registry', 'hetero-gate',
                'donation-safety', 'bracket-discipline', 'retrace-hazard',
                'lock-discipline')
_PRAGMA_MARK = 'graftlint:'


def _pragma_comments(mod: ParsedModule):
  """(lineno, comment_text, own_line) for comment TOKENS mentioning
  graftlint. Tokenizing (not line-scanning) keeps pragma lookalikes in
  docstrings — like the ones documenting the pragma itself — inert."""
  import io
  import tokenize
  try:
    tokens = tokenize.generate_tokens(io.StringIO(mod.source).readline)
    for tok in tokens:
      if tok.type == tokenize.COMMENT and _PRAGMA_MARK in tok.string:
        own_line = mod.lines[tok.start[0] - 1].strip().startswith('#')
        yield tok.start[0], tok.string, own_line
  except tokenize.TokenError:
    return


def _parse_pragmas(mod: ParsedModule):
  """Collect allow-pragmas and shared[]/locked[] annotations per line;
  malformed ones become findings."""
  import re
  rx = re.compile(r'#\s*graftlint:\s*(allow|shared|locked)'
                  r'\[([^\]]*)\]\s*(.*)$')
  for i, text, own_line in _pragma_comments(mod):
    m = rx.search(text)
    if not m:
      mod.pragma_findings.append(Finding(
          'pragma', mod.path, mod.relpath, i, 1,
          "malformed graftlint pragma — expected '# graftlint: "
          "allow[<rule>] <reason>', '# graftlint: shared[<lock>]' or "
          "'# graftlint: locked[<lock>]'"))
      continue
    kind = m.group(1)
    targets = [i]
    # a pragma on a comment-only line covers the next line
    if own_line:
      targets.append(i + 1)
    if kind in ('shared', 'locked'):
      arg = m.group(2).strip()
      if not arg or ',' in arg:
        mod.pragma_findings.append(Finding(
            'pragma', mod.path, mod.relpath, i, 1,
            f'graftlint {kind}[...] annotation needs exactly one lock '
            'name inside the brackets'))
        continue
      for t in targets:
        mod.annotations.setdefault(t, []).append((kind, arg))
      continue
    rules = {r.strip() for r in m.group(2).split(',') if r.strip()}
    reason = m.group(3).strip()
    bad = rules - set(PRAGMA_RULES)
    if bad or not rules:
      mod.pragma_findings.append(Finding(
          'pragma', mod.path, mod.relpath, i, 1,
          f'unknown rule(s) in pragma: {sorted(bad) or "(none)"} — '
          f'valid rules: {", ".join(PRAGMA_RULES)}'))
      continue
    if not reason:
      mod.pragma_findings.append(Finding(
          'pragma', mod.path, mod.relpath, i, 1,
          'graftlint pragma needs a reason after the closing bracket '
          '(unexplained exceptions rot)'))
      continue
    for t in targets:
      mod.pragmas.setdefault(t, set()).update(rules)


def suppressed(mod: ParsedModule, finding: Finding) -> bool:
  return finding.rule in mod.pragmas.get(finding.line, ())


# ----------------------------------------------------------------- baseline

BASELINE_NAME = 'graftlint.baseline.json'


def fingerprint(f: Finding, lines: List[str], occurrence: int) -> str:
  text = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ''
  h = hashlib.sha1(
      f'{f.rule}|{f.relpath}|{text}|{occurrence}'.encode()).hexdigest()
  return h[:16]


def fingerprints_for(findings: List[Finding],
                     modules: Dict[str, ParsedModule]) -> List[str]:
  """Stable fingerprints: occurrence index disambiguates identical
  (rule, file, line-text) triples so two equal violations don't share
  one baseline slot."""
  seen: Dict[Tuple[str, str, str], int] = {}
  out = []
  for f in findings:
    mod = modules.get(f.path)
    lines = mod.lines if mod else []
    text = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ''
    key = (f.rule, f.relpath, text)
    occ = seen.get(key, 0)
    seen[key] = occ + 1
    out.append(fingerprint(f, lines, occ))
  return out


def load_baseline(path: str) -> set:
  if not os.path.exists(path):
    return set()
  with open(path) as fh:
    data = json.load(fh)
  if not isinstance(data, dict) or data.get('version') != 1:
    raise ValueError(f'{path}: not a graftlint baseline (version 1)')
  return set(data.get('fingerprints', []))


def write_baseline(path: str, findings: List[Finding],
                   modules: Dict[str, ParsedModule]):
  data = {'version': 1,
          'fingerprints': sorted(fingerprints_for(findings, modules))}
  with open(path, 'w') as fh:
    json.dump(data, fh, indent=2, sort_keys=True)
    fh.write('\n')


# ------------------------------------------------------------ module loading

def _package_relpath(path: str) -> str:
  """Path relative to the file's topmost enclosing package (the highest
  ancestor directory chain that carries __init__.py). Fixture files in
  bare temp dirs fall back to their basename, which tests match with
  exact-name patterns."""
  path = os.path.abspath(path)
  root = os.path.dirname(path)
  top = None
  d = root
  while os.path.exists(os.path.join(d, '__init__.py')):
    top = d
    d = os.path.dirname(d)
    if d == top:
      break
  base = os.path.dirname(top) if top else root
  return os.path.relpath(path, base).replace(os.sep, '/').split('/', 1)[-1] \
      if top else os.path.basename(path)


def parse_module(path: str) -> Optional[ParsedModule]:
  with open(path, encoding='utf-8') as fh:
    source = fh.read()
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError as e:
    mod = ParsedModule(path, _package_relpath(path), source,
                       source.splitlines(), ast.Module(body=[],
                                                       type_ignores=[]))
    mod.pragma_findings.append(Finding(
        'syntax', mod.path, mod.relpath, e.lineno or 1, e.offset or 1,
        f'file does not parse: {e.msg}'))
    return mod
  mod = ParsedModule(path, _package_relpath(path), source,
                     source.splitlines(), tree)
  _parse_pragmas(mod)
  return mod


def collect_files(paths: Sequence[str]) -> List[str]:
  out = []
  for p in paths:
    p = os.path.abspath(p)
    if os.path.isdir(p):
      for dirpath, dirnames, filenames in os.walk(p):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ('__pycache__', '.git', 'build'))
        for fn in sorted(filenames):
          if fn.endswith('.py'):
            out.append(os.path.join(dirpath, fn))
    elif p.endswith('.py'):
      out.append(p)
  return out


# ------------------------------------------------------------------- runner

def _checkers():
  from . import (brackets, compat_import, dispatch, donation, fault_points,
                 hetero_gates, host_sync, locks, metric_names, prng,
                 retrace, span_names)
  return (host_sync, prng, dispatch, compat_import, fault_points,
          metric_names, span_names, hetero_gates, donation, brackets,
          retrace, locks)


@dataclass
class LintResult:
  """``run_lint``'s result. Unpacks as the historical 4-tuple
  ``(findings, n_pragma, n_base, modules)``; ``timings`` adds per-rule
  wall seconds for the CLI summary / JSON output."""
  findings: List[Finding]
  n_pragma: int
  n_base: int
  modules: Dict[str, ParsedModule]
  timings: Dict[str, float] = field(default_factory=dict)

  def __iter__(self):
    return iter((self.findings, self.n_pragma, self.n_base, self.modules))


def run_lint(paths: Sequence[str], config: Optional[Config] = None,
             baseline: Optional[set] = None) -> LintResult:
  """Lint ``paths`` (files/dirs). Returns a :class:`LintResult` (which
  unpacks as ``(findings, suppressed_count, baselined_count, modules)``)
  where ``findings`` are the live (neither pragma- nor baseline-
  suppressed) findings, sorted by location."""
  import time
  config = config or Config()
  files = collect_files(paths)
  modules: Dict[str, ParsedModule] = {}
  for f in files:
    mod = parse_module(f)
    if mod is not None:
      modules[mod.path] = mod
  if config.repo_root is None and files:
    # the directory holding the topmost package: doc paths resolve here
    pkg_file = files[0]
    d = os.path.dirname(pkg_file)
    while os.path.exists(os.path.join(d, '__init__.py')):
      d = os.path.dirname(d)
    config = replace(config, repo_root=d)

  mods = list(modules.values())
  raw: List[Finding] = []
  for mod in mods:
    raw.extend(mod.pragma_findings)
  timings: Dict[str, float] = {}
  for checker in _checkers():
    t0 = time.monotonic()
    raw.extend(checker.check_package(mods, config))
    rule = getattr(checker, 'RULE', checker.__name__)
    timings[rule] = timings.get(rule, 0.0) + (time.monotonic() - t0)

  live, n_pragma = [], 0
  for f in raw:
    mod = modules.get(f.path)
    if mod is not None and suppressed(mod, f):
      n_pragma += 1
    else:
      live.append(f)

  n_base = 0
  if baseline:
    fps = fingerprints_for(live, modules)
    kept = []
    for f, fp in zip(live, fps):
      if fp in baseline:
        n_base += 1
      else:
        kept.append(f)
    live = kept

  live.sort(key=lambda f: (f.relpath, f.line, f.col, f.rule))
  return LintResult(live, n_pragma, n_base, modules, timings)
