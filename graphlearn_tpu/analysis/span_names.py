"""Rule span-registry: span names are literal, registered, and
documented — the span namespace stays closed, like metric names.

Span trees are joined across processes by NAME + id (metrics/spans.py):
the postmortem tooling, the chaos-suite tree asserts and the
docs/observability.md span table all key on exact names, so a typo'd
or ad-hoc span name silently orphans its subtree from every consumer.
This rule is the span instance of the ``metric-registry`` contract and
reuses its machinery:

  * span-emitting call sites across the package — ``spans.span`` /
    ``spans.begin`` / ``spans.emit`` (resolved through import aliases;
    the name is the first positional or the ``name`` keyword);
  * the ``REGISTERED_SPANS`` frozenset in ``metrics/registry_names.py``
    (parsed from source, never imported); ``<prefix>.*`` wildcard
    entries are honored for symmetry though the shipped set is fully
    literal;
  * the span table in ``docs/observability.md`` — every registry entry
    must appear there in backticks.

The metrics package itself is exempt (it manipulates names as data),
exactly like the metric rule.
"""
import ast
from typing import List, Optional, Set

from . import astutil
from .core import Config, Finding, ParsedModule, in_scope
from .metric_names import (_documented_names, _literal_parts, _name_arg,
                           _parse_registry, _registered)

RULE = 'span-registry'

# last segment checked when the call resolves under a `spans` namespace
# (spans.span(...), metrics.spans.begin(...), or a bare name imported
# from the spans module)
_SPAN_FNS = ('span', 'begin', 'emit')


def _is_span_call(name: Optional[str]) -> Optional[str]:
  if not name:
    return None
  parts = name.split('.')
  if parts[-1] in _SPAN_FNS and len(parts) >= 2 and \
      parts[-2] == 'spans':
    return parts[-1]
  return None


def check_package(modules: List[ParsedModule], config: Config):
  out: List[Finding] = []
  registry_mod = None
  for mod in modules:
    if mod.relpath == config.metrics_registry_module:
      registry_mod = mod
  entries, reg_line = _parse_registry(registry_mod,
                                      name='REGISTERED_SPANS')
  exact: Set[str] = {e for e in entries if not e.endswith('.*')} \
      if entries is not None else set()
  wildcards: Set[str] = {e[:-1] for e in entries if e.endswith('.*')} \
      if entries is not None else set()
  documented = _documented_names(config)

  for mod in modules:
    if in_scope(mod.relpath, config.metrics_exempt_modules):
      continue
    aliases = astutil.import_aliases(mod.tree)
    for node in ast.walk(mod.tree):
      if not isinstance(node, ast.Call):
        continue
      fn = _is_span_call(
          astutil.canonical(astutil.call_name(node), aliases))
      if fn is None:
        continue
      arg = _name_arg(node)
      if arg is None:
        continue
      full, head = _literal_parts(arg)
      if full is None and head is None:
        out.append(Finding(
            RULE, mod.path, mod.relpath, arg.lineno, arg.col_offset + 1,
            f'span name passed to spans.{fn}() is not a string literal '
            '— computed names escape the closed namespace '
            '(metrics/registry_names.py REGISTERED_SPANS); use a '
            'literal, or a registered <prefix>.* wildcard f-string'))
        continue
      if entries is None:
        continue   # registry unparseable: its own finding covers it
      if full is not None:
        if not _registered(full, exact, wildcards):
          out.append(Finding(
              RULE, mod.path, mod.relpath, arg.lineno,
              arg.col_offset + 1,
              f'span name {full!r} is not in metrics/registry_names.py '
              'REGISTERED_SPANS — register it (and add it to the '
              'docs/observability.md span table) in the same change'))
        elif documented is not None and full in exact and \
            full not in documented:
          out.append(Finding(
              RULE, mod.path, mod.relpath, arg.lineno,
              arg.col_offset + 1,
              f'span name {full!r} is registered but missing from the '
              f'{config.observability_doc} span table — document it '
              '(emitter, tree position, meaning)'))
      else:   # f-string: literal head must contain a full wildcard
        if not head or not any(head.startswith(w) for w in wildcards):
          out.append(Finding(
              RULE, mod.path, mod.relpath, arg.lineno,
              arg.col_offset + 1,
              f'f-string span name with literal head {head!r} matches '
              'no <prefix>.* wildcard in REGISTERED_SPANS — register '
              'the family wildcard, or use a literal name'))

  if entries is None and registry_mod is not None:
    out.append(Finding(
        RULE, registry_mod.path, registry_mod.relpath, 1, 1,
        'metrics/registry_names.py defines no REGISTERED_SPANS '
        'frozenset — the span-name registry is the anchor this rule '
        'checks against'))
  elif entries is not None and documented is not None and registry_mod:
    for name in sorted(set(entries) - documented):
      out.append(Finding(
          RULE, registry_mod.path, registry_mod.relpath, reg_line, 1,
          f'REGISTERED_SPANS entry {name!r} is not documented in '
          f'{config.observability_doc} — add it to the span table'))
  return out
