"""Rule metric-registry: metric names are literal, registered, and
documented — the exported namespace stays closed.

The metrics layer's value is the CLOSED ``<subsystem>.<event>``
namespace (docs/observability.md): dashboards, the bench gate and the
flight-record postmortem tooling all key on exact names, so a typo'd
or ad-hoc name silently orphans its series. This rule cross-checks
three sources, mirroring the fault-point-coverage rule:

  * metric-emitting call sites across the package — the trace shim
    (``counter_inc``) and the idiomatic ``metrics.<fn>`` forms
    (``inc`` / ``observe`` / ``set_gauge`` / ``counter`` / ``gauge`` /
    ``histogram``), resolved through import aliases;
  * the ``REGISTERED_METRICS`` frozenset in
    ``metrics/registry_names.py`` (parsed from source — the linter
    never imports the package). Entries ending ``.*`` are WILDCARDS
    covering runtime-minted tails (``fault.*``); an f-string name
    whose literal head falls under a wildcard passes, any other
    non-literal name is a finding (suppress with a pragma when a
    dynamic name is genuinely required, as publish_stats' prefix
    parameter is);
  * the naming table in ``docs/observability.md`` — every registry
    entry must appear there in backticks (the same auto-check
    failure_model.md gets for fault sites).

No stale-entry check: wildcard families and prefix-parameterized
emitters mint names at runtime, so absence of a literal call site is
not evidence a name is dead.
"""
import ast
import os
from typing import List, Optional, Set, Tuple

from . import astutil
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'metric-registry'

# last segments checked when the call resolves under a `metrics`
# namespace (metrics.inc(...), glt.metrics.observe(...), or a bare
# name imported from the metrics package)
_METRIC_FNS = ('inc', 'observe', 'set_gauge', 'counter', 'gauge',
               'histogram')
# distinctive names checked regardless of namespace (the trace shim)
_ALWAYS_FNS = ('counter_inc',)


def _is_metric_call(name: Optional[str]) -> Optional[str]:
  """The checked function's last segment, or None when this call is
  not a metric-emitting form."""
  if not name:
    return None
  parts = name.split('.')
  if parts[-1] in _ALWAYS_FNS:
    return parts[-1]
  if parts[-1] in _METRIC_FNS and len(parts) >= 2 and \
      parts[-2] == 'metrics':
    return parts[-1]
  return None


def _name_arg(call: ast.Call) -> Optional[ast.AST]:
  if call.args:
    return call.args[0]
  for kw in call.keywords:
    if kw.arg == 'name':
      return kw.value
  return None


def _literal_parts(node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
  """(full_literal, literal_head): the whole name when it is a string
  constant, else the leading literal run of an f-string (empty-string
  head when the f-string starts with a substitution), else (None,
  None) for anything non-string."""
  if isinstance(node, ast.Constant) and isinstance(node.value, str):
    return node.value, None
  if isinstance(node, ast.JoinedStr):
    head = ''
    for v in node.values:
      if isinstance(v, ast.Constant) and isinstance(v.value, str):
        head += v.value
      else:
        break
    return None, head
  return None, None


def _registered(name: str, exact: Set[str], wildcards: Set[str]) -> bool:
  if name in exact:
    return True
  return any(name.startswith(w) for w in wildcards)


def check_package(modules: List[ParsedModule], config: Config):
  out: List[Finding] = []
  registry_mod = None
  for mod in modules:
    if mod.relpath == config.metrics_registry_module:
      registry_mod = mod
  entries, reg_line = _parse_registry(registry_mod)
  exact = {e for e in entries if not e.endswith('.*')} \
      if entries is not None else set()
  wildcards = {e[:-1] for e in entries if e.endswith('.*')} \
      if entries is not None else set()
  documented = _documented_names(config)

  for mod in modules:
    if in_scope(mod.relpath, config.metrics_exempt_modules):
      continue
    aliases = astutil.import_aliases(mod.tree)
    for node in ast.walk(mod.tree):
      if not isinstance(node, ast.Call):
        continue
      fn = _is_metric_call(
          astutil.canonical(astutil.call_name(node), aliases))
      if fn is None:
        continue
      arg = _name_arg(node)
      if arg is None:
        continue
      full, head = _literal_parts(arg)
      if full is None and head is None:
        out.append(Finding(
            RULE, mod.path, mod.relpath, arg.lineno, arg.col_offset + 1,
            f'metric name passed to {fn}() is not a string literal — '
            'computed names escape the closed namespace '
            '(metrics/registry_names.py); use a literal, or a '
            'registered <prefix>.* wildcard f-string'))
        continue
      if entries is None:
        continue   # registry unparseable: its own finding covers it
      if full is not None:
        if not _registered(full, exact, wildcards):
          out.append(Finding(
              RULE, mod.path, mod.relpath, arg.lineno,
              arg.col_offset + 1,
              f'metric name {full!r} is not in metrics/'
              'registry_names.py REGISTERED_METRICS — register it '
              '(and add it to the docs/observability.md naming table) '
              'in the same change'))
        elif documented is not None and full in exact and \
            full not in documented:
          out.append(Finding(
              RULE, mod.path, mod.relpath, arg.lineno,
              arg.col_offset + 1,
              f'metric name {full!r} is registered but missing from '
              f'the {config.observability_doc} naming table — '
              'document it (kind, unit, meaning)'))
      else:   # f-string: its literal head must fall under a wildcard
        # an empty head (name starts with a substitution) is fully
        # computed — never wildcard-safe. The head must CONTAIN a full
        # wildcard prefix (head.startswith(w)): only then is every
        # runtime completion guaranteed inside the family. The reverse
        # test (w.startswith(head)) would wave through f'd{x}' because
        # 'dist_feature.' happens to start with 'd'.
        if not head or not any(head.startswith(w) for w in wildcards):
          out.append(Finding(
              RULE, mod.path, mod.relpath, arg.lineno,
              arg.col_offset + 1,
              f'f-string metric name with literal head {head!r} '
              'matches no <prefix>.* wildcard in REGISTERED_METRICS — '
              'register the family wildcard, or use a literal name'))

  if entries is None and registry_mod is not None:
    out.append(Finding(
        RULE, registry_mod.path, registry_mod.relpath, 1, 1,
        'metrics/registry_names.py defines no REGISTERED_METRICS '
        'frozenset — the metric-name registry is the anchor this rule '
        'checks against'))
  elif entries is not None and documented is not None and registry_mod:
    for name in sorted(set(entries) - documented):
      out.append(Finding(
          RULE, registry_mod.path, registry_mod.relpath, reg_line, 1,
          f'REGISTERED_METRICS entry {name!r} is not documented in '
          f'{config.observability_doc} — add it to the naming table '
          '(wildcards appear literally, e.g. `fault.*`)'))
  return out


def _parse_registry(mod: Optional[ParsedModule],
                    name: str = 'REGISTERED_METRICS'):
  """(entries, lineno) from ``<name> = frozenset({...})``, or
  (None, 0) when unavailable. Shared with the span-registry rule
  (``name='REGISTERED_SPANS'``) — same file, same parse."""
  if mod is None:
    return None, 0
  for node in ast.walk(mod.tree):
    if not isinstance(node, ast.Assign):
      continue
    names = [t.id for t in node.targets if isinstance(t, ast.Name)]
    if name not in names:
      continue
    try:
      value = ast.literal_eval(node.value)
    except ValueError:
      if isinstance(node.value, ast.Call) and node.value.args:
        try:
          value = ast.literal_eval(node.value.args[0])
        except ValueError:
          return None, 0
      else:
        return None, 0
    return set(value), node.lineno
  return None, 0


def _documented_names(config: Config) -> Optional[Set[str]]:
  if not config.repo_root:
    return None
  path = os.path.join(config.repo_root, config.observability_doc)
  if not os.path.exists(path):
    return None
  import re
  with open(path, encoding='utf-8') as fh:
    text = fh.read()
  # backticked tokens, '*' allowed so wildcard entries document as-is
  return set(re.findall(r'`([a-z0-9_.*]+)`', text))
