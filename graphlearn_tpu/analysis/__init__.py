"""graftlint: AST-level static analysis for this package's hot-path
invariants.

The perf story of the scanned-epoch / distributed hot paths rests on
contracts that no runtime test can cheaply enforce — zero implicit
device->host syncs inside traced program bodies, counter-addressed
(never split-and-carry) PRNG keys so scan replay stays bit-identical,
dispatch instrumentation on every jitted entrypoint so the
``epoch_dispatches`` budgets mean anything, ``shard_map`` resolved only
through ``utils/compat.py``, and a closed registry of documented fault
points. graftlint checks them at the AST level, with line-level
``# graftlint: allow[<rule>] <reason>`` pragmas and a checked-in
baseline for intentional exceptions.

CLI::

    python -m graphlearn_tpu.analysis.lint graphlearn_tpu/

Rules (see docs/static_analysis.md):

    host-sync                 host round-trips inside traced code
    prng-discipline           split-and-carry / key reuse in samplers
    dispatch-instrumentation  un-instrumented jit dispatch sites
    compat-shard-map          shard_map imported outside utils/compat
    fault-point-coverage      unregistered / undocumented fault sites

This package deliberately imports neither jax nor the rest of
graphlearn_tpu at analysis time — everything is pure ``ast`` over
source text, so the linter runs anywhere Python runs.
"""
from .core import Config, Finding, load_baseline, run_lint, write_baseline

__all__ = ['Config', 'Finding', 'run_lint', 'load_baseline',
           'write_baseline']
