"""Flow-aware analysis core: per-function CFGs + forward dataflow.

The per-statement AST matching of the original graftlint rules answers
"does this call appear here"; the bug classes PRs 7, 8, 10 and 15 fixed
by hand review are all PATH questions — "is this donated value read on
any path after the donating call", "does every outgoing edge (including
the exception edge out of the prologue) close this span", "does this
dynamic length reach a static jit arg without passing the pow2 ladder".
This module gives the rules the machinery to ask them:

* :class:`CFG` — a lightweight statement-level control-flow graph per
  function. Compound statements are decomposed (``if``/loops/``try``/
  ``with``); every statement that can raise carries an EXCEPTION edge
  to the innermost handler/finally region (or straight to EXIT), so
  "provably closed on every outgoing edge" is a reachability question,
  not a lexical one.
* :func:`forward` — a worklist forward dataflow solver over a CFG with
  set-union merge (may-analysis). Rules supply a transfer function
  from (statement, in-state) to out-state — and optionally a separate
  exception-edge transfer, for facts a statement only establishes when
  it COMPLETES (a span token is not held if ``spans.begin`` itself
  raised).
* read/write helpers (:func:`stmt_reads`, :func:`stmt_writes`) that
  treat ``self.<attr>`` as a trackable dotted name, the idiom the
  donated-store and lock rules key on.

Same contract as astutil: pure stdlib ``ast``, imports neither jax nor
the package, best-effort and quiet-on-failure. The CFG deliberately
OVER-approximates paths (a ``finally`` region exits to both its normal
successor and the enclosing exception target; an early ``return``
routes through the innermost finally whose spurious fall-through
continues past the try) — for the may-analyses built on it, extra
paths make a rule more cautious on genuinely bracketed code, never
silently blind on unbracketed code.
"""
import ast
from typing import Callable, Dict, FrozenSet, List, Optional, Set

# synthetic node ids
EXIT = 0
ENTRY = 1


class CFG:
  """Statement-level control-flow graph of one function body.

  Nodes are integers; ``stmt_of[n]`` maps a node to its ast statement
  (ENTRY/EXIT have none; several nodes may share one compound
  statement's header). ``succ[n]`` holds normal-flow successors and
  ``exc[n]`` the exception-edge successors — kept separate so a rule
  can flow a different state along "this statement raised midway".
  """

  def __init__(self):
    self.succ: Dict[int, Set[int]] = {EXIT: set(), ENTRY: set()}
    self.exc: Dict[int, Set[int]] = {EXIT: set(), ENTRY: set()}
    self.stmt_of: Dict[int, ast.stmt] = {}
    self._next_id = 2

  def _new(self, stmt: Optional[ast.stmt]) -> int:
    n = self._next_id
    self._next_id += 1
    self.succ[n] = set()
    self.exc[n] = set()
    if stmt is not None:
      self.stmt_of[n] = stmt
    return n

  def _edge(self, a: int, b: int, exc: bool = False):
    (self.exc if exc else self.succ)[a].add(b)

  def nodes(self):
    return self.succ.keys()


def _can_raise(stmt: ast.stmt) -> bool:
  """Conservative: anything containing a call, subscript, attribute
  LOAD, raise, assert, await/yield, or binary op may raise. Plain
  ``pass``, constant/name copies and attribute STORES (``self.x = y``
  on ordinary objects) cannot."""
  if isinstance(stmt, (ast.Raise, ast.Assert)):
    return True
  for node in ast.walk(stmt):
    if isinstance(node, (ast.Call, ast.Subscript, ast.BinOp,
                         ast.Await, ast.Yield, ast.YieldFrom)):
      return True
    if isinstance(node, ast.Attribute) and \
        not isinstance(node.ctx, ast.Store):
      return True
  return False


class _Ctx:
  """Builder context: where control goes on break/continue/raise, and
  the stack of enclosing finally entries an early exit must run."""
  __slots__ = ('break_to', 'continue_to', 'exc_to', 'finally_to')

  def __init__(self, break_to, continue_to, exc_to, finally_to):
    self.break_to: Optional[int] = break_to
    self.continue_to: Optional[int] = continue_to
    self.exc_to = exc_to          # Tuple[int, ...]: exception targets
    self.finally_to = finally_to  # Tuple[int, ...]: outermost..innermost


def build_cfg(fn: ast.AST) -> CFG:
  """CFG of ``fn``'s body (FunctionDef / AsyncFunctionDef). Nested
  function and class definitions are opaque single nodes — their bodies
  do not execute at definition time."""
  cfg = CFG()
  ctx = _Ctx(None, None, (EXIT,), ())
  entry = _build_seq(cfg, fn.body, ctx, EXIT)
  cfg._edge(ENTRY, entry)
  return cfg


def _build_seq(cfg: CFG, stmts: List[ast.stmt], ctx: _Ctx, nxt: int) -> int:
  """Build ``stmts`` so the last falls through to ``nxt``; returns the
  entry node id (``nxt`` itself for an empty sequence)."""
  entry = nxt
  for stmt in reversed(stmts):
    entry = _build_stmt(cfg, stmt, ctx, entry)
  return entry


def _build_stmt(cfg: CFG, stmt: ast.stmt, ctx: _Ctx, nxt: int) -> int:
  if isinstance(stmt, ast.If):
    n = cfg._new(stmt)
    cfg._edge(n, _build_seq(cfg, stmt.body, ctx, nxt))
    cfg._edge(n, _build_seq(cfg, stmt.orelse, ctx, nxt))
    _exc_edges(cfg, n, stmt, ctx)
    return n

  if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
    n = cfg._new(stmt)           # header: test / iterator step
    after = _build_seq(cfg, stmt.orelse, ctx, nxt)
    loop_ctx = _Ctx(nxt, n, ctx.exc_to, ctx.finally_to)
    body = _build_seq(cfg, stmt.body, loop_ctx, n)  # back edge via header
    cfg._edge(n, body)
    cfg._edge(n, after)
    _exc_edges(cfg, n, stmt, ctx)
    return n

  if isinstance(stmt, (ast.With, ast.AsyncWith)):
    # the header evaluates+enters the context managers; the body runs
    # under them. __exit__ re-raises by default, so body exception
    # edges keep the enclosing targets. Rules that care about the
    # managed resources inspect the With node directly.
    n = cfg._new(stmt)
    cfg._edge(n, _build_seq(cfg, stmt.body, ctx, nxt))
    _exc_edges(cfg, n, stmt, ctx)
    return n

  if isinstance(stmt, ast.Try):
    return _build_try(cfg, stmt, ctx, nxt)

  if isinstance(stmt, ast.Return):
    n = cfg._new(stmt)
    # a return runs the innermost enclosing finally, whose own exits
    # carry on; only with no finally does it reach EXIT directly
    cfg._edge(n, ctx.finally_to[-1] if ctx.finally_to else EXIT)
    _exc_edges(cfg, n, stmt, ctx)
    return n

  if isinstance(stmt, ast.Raise):
    n = cfg._new(stmt)
    for t in ctx.exc_to:
      cfg._edge(n, t)
    return n

  if isinstance(stmt, (ast.Break, ast.Continue)):
    n = cfg._new(stmt)
    if ctx.finally_to:
      cfg._edge(n, ctx.finally_to[-1])
    else:
      target = ctx.break_to if isinstance(stmt, ast.Break) \
          else ctx.continue_to
      cfg._edge(n, target if target is not None else EXIT)
    return n

  # simple statement (incl. nested def/class as opaque nodes)
  n = cfg._new(stmt)
  cfg._edge(n, nxt)
  _exc_edges(cfg, n, stmt, ctx)
  return n


def _exc_edges(cfg: CFG, n: int, stmt: ast.stmt, ctx: _Ctx):
  if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
    return
  if _can_raise(stmt):
    for t in ctx.exc_to:
      cfg._edge(n, t, exc=True)


def _build_try(cfg: CFG, stmt: ast.Try, ctx: _Ctx, nxt: int) -> int:
  # finally region: entered on normal completion, from handlers, on
  # unmatched exceptions, and by early exits. It exits to BOTH the
  # normal successor and the enclosing exception targets (the
  # over-approximation the module docstring describes).
  f_entry: Optional[int] = None
  if stmt.finalbody:
    f_entry = _build_seq(cfg, stmt.finalbody, ctx, nxt)
    for node in list(cfg.succ):
      if node in (EXIT, ENTRY):
        continue
      if nxt in cfg.succ[node] and _in_region(cfg, node, stmt.finalbody):
        for t in ctx.exc_to:
          cfg._edge(node, t)

  after_body = f_entry if f_entry is not None else nxt
  inner_finally = ctx.finally_to + ((f_entry,) if f_entry is not None
                                    else ())

  # handler bodies: exceptions raised INSIDE a handler go to the
  # finally (if any) or the enclosing targets, never back to a sibling
  handler_ctx = _Ctx(ctx.break_to, ctx.continue_to,
                     (f_entry,) if f_entry is not None else ctx.exc_to,
                     inner_finally)
  exc_targets: List[int] = []
  for h in stmt.handlers:
    exc_targets.append(_build_seq(cfg, h.body, handler_ctx, after_body))
  if f_entry is not None:
    exc_targets.append(f_entry)   # unmatched exception: finally runs
  if not exc_targets:
    exc_targets = list(ctx.exc_to)

  body_ctx = _Ctx(ctx.break_to, ctx.continue_to, tuple(exc_targets),
                  inner_finally)
  orelse = _build_seq(cfg, stmt.orelse, body_ctx, after_body)
  return _build_seq(cfg, stmt.body, body_ctx, orelse)


def _in_region(cfg: CFG, node: int, stmts: List[ast.stmt]) -> bool:
  s = cfg.stmt_of.get(node)
  if s is None:
    return False
  for top in stmts:
    if s is top:
      return True
    for sub in ast.walk(top):
      if sub is s:
        return True
  return False


# ---------------------------------------------------------------- dataflow

State = FrozenSet[str]
Transfer = Callable[[int, Optional[ast.stmt], State], State]


def forward(cfg: CFG, init: State, transfer: Transfer,
            exc_transfer: Optional[Transfer] = None) -> Dict[int, State]:
  """Worklist forward may-analysis: returns the IN-state of every node
  (union over predecessors' out-states). ``transfer(node_id, stmt,
  in_state)`` produces a node's normal out-state; ``exc_transfer``
  (default: same as ``transfer``) produces the state flowing along its
  exception edges. ENTRY's in-state is ``init``."""
  flow_preds: Dict[int, List[int]] = {n: [] for n in cfg.nodes()}
  exc_preds: Dict[int, List[int]] = {n: [] for n in cfg.nodes()}
  for a in cfg.nodes():
    for b in cfg.succ[a]:
      flow_preds[b].append(a)
    for b in cfg.exc[a]:
      exc_preds[b].append(a)

  in_s: Dict[int, State] = {n: frozenset() for n in cfg.nodes()}
  out_s: Dict[int, State] = dict(in_s)
  exc_out_s: Dict[int, State] = dict(in_s)

  def apply(n: int, state: State):
    stmt = cfg.stmt_of.get(n)
    out = transfer(n, stmt, state)
    exc_out = exc_transfer(n, stmt, state) if exc_transfer else out
    return out, exc_out

  in_s[ENTRY] = init
  out_s[ENTRY], exc_out_s[ENTRY] = apply(ENTRY, init)
  work = sorted(n for n in cfg.nodes() if n != ENTRY)
  # gen/kill transfers over a finite name lattice are monotone; the cap
  # is a parse-bomb guard, not a correctness device
  cap = 200 * (len(in_s) + 2)
  while work and cap > 0:
    cap -= 1
    n = work.pop(0)
    pieces = [out_s[p] for p in flow_preds[n]] + \
        [exc_out_s[p] for p in exc_preds[n]]
    new_in = frozenset().union(*pieces) if pieces else frozenset()
    if n == ENTRY:
      new_in |= init
    new_out, new_exc = apply(n, new_in)
    if new_in == in_s[n] and new_out == out_s[n] and \
        new_exc == exc_out_s[n]:
      continue
    in_s[n], out_s[n], exc_out_s[n] = new_in, new_out, new_exc
    for b in cfg.succ[n] | cfg.exc[n]:
      if b not in work:
        work.append(b)
  return in_s


# ----------------------------------------------------------- reads / writes

def dotted(node: ast.AST) -> Optional[str]:
  """'self._emb' for a one-level attribute, 'x' for a bare name. Deeper
  chains (a.b.c) return None — the rules track locals and self-fields,
  nothing fancier."""
  if isinstance(node, ast.Name):
    return node.id
  if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
    return f'{node.value.id}.{node.attr}'
  return None


def expr_reads(expr: ast.AST) -> Set[str]:
  """Trackable names loaded anywhere inside ``expr``: bare locals plus
  one-level dotted reads (``self._emb``, ``obj.attr``). An attribute
  read also reports its base — reading ``state.params`` reads
  ``state``."""
  out: Set[str] = set()
  for node in ast.walk(expr):
    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
      out.add(node.id)
    elif isinstance(node, ast.Attribute) and \
        isinstance(node.ctx, ast.Load):
      d = dotted(node)
      if d:
        out.add(d)
  return out


def stmt_reads(stmt: ast.stmt) -> Set[str]:
  """Names the statement reads. For assignments, the RHS plus any
  subscript indices/containers on the LHS; for compound headers, the
  test/iterator/items expression only (bodies are separate nodes)."""
  if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
    out = expr_reads(stmt.value) if stmt.value is not None else set()
    if isinstance(stmt, ast.AugAssign):
      d = dotted(stmt.target)
      if d:
        out.add(d)
    targets = stmt.targets if isinstance(stmt, ast.Assign) \
        else [stmt.target]
    for t in targets:
      for sub in ast.walk(t):
        if isinstance(sub, ast.Subscript):
          out |= expr_reads(sub.slice)
          d = dotted(sub.value)
          if d:
            out.add(d)   # x[i] = v reads (the container identity of) x
    return out
  if isinstance(stmt, (ast.If, ast.While)):
    return expr_reads(stmt.test)
  if isinstance(stmt, (ast.For, ast.AsyncFor)):
    return expr_reads(stmt.iter)
  if isinstance(stmt, (ast.With, ast.AsyncWith)):
    out = set()
    for item in stmt.items:
      out |= expr_reads(item.context_expr)
    return out
  if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
    return set()
  out = set()
  for child in ast.iter_child_nodes(stmt):
    out |= expr_reads(child)
  return out


def stmt_writes(stmt: ast.stmt) -> Set[str]:
  """Trackable names the statement (re)binds: assignment targets and
  loop/with targets — bare names and ``self.<attr>``. Subscript stores
  (``x[i] = v``) mutate, they do not rebind, so they are excluded."""
  out: Set[str] = set()

  def targets_of(t):
    if isinstance(t, (ast.Tuple, ast.List)):
      for e in t.elts:
        targets_of(e)
    elif not isinstance(t, (ast.Subscript, ast.Starred)):
      d = dotted(t)
      if d:
        out.add(d)

  if isinstance(stmt, ast.Assign):
    for t in stmt.targets:
      targets_of(t)
  elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
    targets_of(stmt.target)
  elif isinstance(stmt, (ast.For, ast.AsyncFor)):
    targets_of(stmt.target)
  elif isinstance(stmt, (ast.With, ast.AsyncWith)):
    for item in stmt.items:
      if item.optional_vars is not None:
        targets_of(item.optional_vars)
  return out


def stmt_calls(stmt: ast.stmt) -> List[ast.Call]:
  """Call nodes appearing in this statement (header expressions only
  for compounds; lambdas and nested defs are opaque)."""
  if isinstance(stmt, (ast.If, ast.While)):
    roots: List[ast.AST] = [stmt.test]
  elif isinstance(stmt, (ast.For, ast.AsyncFor)):
    roots = [stmt.iter]
  elif isinstance(stmt, (ast.With, ast.AsyncWith)):
    roots = [i.context_expr for i in stmt.items]
  elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
    return []
  else:
    roots = [stmt]
  out: List[ast.Call] = []
  stack: List[ast.AST] = list(roots)
  while stack:
    node = stack.pop()
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
      continue
    if isinstance(node, ast.Call):
      out.append(node)
    stack.extend(ast.iter_child_nodes(node))
  return out
