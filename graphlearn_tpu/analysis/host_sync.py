"""Rule host-sync: no implicit device->host round-trips in traced code.

The scanned-epoch programs (PR 1/4) exist to keep an entire epoch on
device; ONE stray ``.item()`` / ``int(traced)`` / ``np.asarray(traced)``
inside a function reachable from the jitted scan bodies either fails at
trace time (the lucky case) or — via a concretization fallback or a
forgotten eager path — silently reintroduces the per-step host sync the
whole architecture removed (PERF.md: wall clock scales with dispatches
and fetches, not device ms; PyTorch-Direct, arxiv 2101.07956, builds the
same argument for GPU-centric access). This rule flags the sync surface
inside traced functions of the hot modules.

What counts as a sync call:

  ``x.item()`` / ``x.tolist()`` / ``x.block_until_ready()``
  ``int(x)`` / ``float(x)`` / ``bool(x)`` on a non-constant argument
  ``jax.device_get(x)`` / ``np.asarray(x)`` / ``np.array(x)``

Traced scope is computed per astutil.traced_functions (jit/scan/
shard_map roots + the nested-def convention). Static host-side shape
arithmetic on real constants is legitimate at trace time — suppress
those with ``# graftlint: allow[host-sync] <why>``.
"""
import ast
from typing import List

from . import astutil
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'host-sync'

_ATTR_SYNCS = {'item', 'tolist', 'block_until_ready'}
_CAST_SYNCS = {'int', 'float', 'bool'}
_FUNC_SYNCS = {'jax.device_get', 'numpy.asarray', 'numpy.array'}


def _is_const(node: ast.AST) -> bool:
  return isinstance(node, ast.Constant)


def check_package(modules: List[ParsedModule], config: Config):
  findings = []
  for mod in modules:
    if not in_scope(mod.relpath, config.hot_sync_modules):
      continue
    findings.extend(_check_module(mod))
  return findings


def _check_module(mod: ParsedModule) -> List[Finding]:
  index = astutil.FuncIndex(mod.tree)
  aliases = astutil.import_aliases(mod.tree)
  traced = astutil.traced_functions(index, mod.tree, aliases)
  out: List[Finding] = []
  for qual in sorted(traced):
    fi = index.by_qual.get(qual)
    if fi is None:
      continue
    for node in index.own_nodes(fi):
      if not isinstance(node, ast.Call):
        continue
      msg = _sync_message(node, aliases)
      if msg:
        out.append(Finding(
            RULE, mod.path, mod.relpath, node.lineno, node.col_offset + 1,
            f'{msg} inside traced code — this forces a device->host '
            'sync (or a per-call retrace) in a scanned/fused hot path; '
            'keep the value on device, or hoist the host step out of '
            'the program', symbol=qual))
  return out


def _sync_message(call: ast.Call, aliases) -> str:
  func = call.func
  if isinstance(func, ast.Attribute) and func.attr in _ATTR_SYNCS:
    return f'.{func.attr}() call'
  name = astutil.call_name(call)
  if isinstance(func, ast.Name) and func.id in _CAST_SYNCS:
    if call.args and not all(_is_const(a) for a in call.args):
      return f'{func.id}() cast'
    return ''
  # EXACT canonical match only: 'jnp.asarray' canonicalizes to
  # 'jax.numpy.asarray' (device-side, fine) and must not suffix-match
  # 'numpy.asarray'
  cname = astutil.canonical(name, aliases)
  if cname in _FUNC_SYNCS:
    return f'{name}() call'
  return ''
