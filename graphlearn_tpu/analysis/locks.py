"""Rule lock-discipline: shared state stays under its lock; lock order
is acyclic.

The host-thread population (ChunkStager, the checkpoint writer,
RotationScheduler, RetuneScheduler, the serving dispatcher, admission
control) shares state through a handful of known fields, each guarded
by one lock. PR 8's compile-watermark race and PR 15's rotate_now
force-flag were both the same bug: a field the comments SAID was
lock-guarded, touched on one path without the lock. This rule turns
the comment into a checked annotation:

* ``# graftlint: shared[<lock>]`` on the field's defining assignment
  (``self._plan = ...`` in ``__init__``, a class attribute, or a
  module-level global) registers it: every later read/write of that
  field must sit inside ``with self.<lock>:`` (or ``with <lock>:`` for
  globals), inside a method annotated ``# graftlint: locked[<lock>]``
  (callee assumes the caller holds it — and every intra-class call
  site of such a method is checked to actually hold it), or in
  ``__init__`` before the object escapes. A ``threading.Condition``
  built over the lock counts as the lock.

* The lock-order graph: every ``with``-acquisition nested inside
  another — directly, or transitively through same-class method calls
  and same/imported-module function calls — adds an ordering edge.
  A cycle across the package is a finding (the classic ABBA deadlock),
  reported once per strongly-connected component.

Annotation-driven by design: the rule is silent on unannotated state,
so adopting it is incremental and false positives are opt-in. Lock
identity is name-based (``self._lock`` in class C of module M), the
same approximation every other graftlint rule makes.
"""
import ast
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'lock-discipline'

_LOCK_CTORS = ('threading.Lock', 'threading.RLock', 'threading.Condition',
               'Lock', 'RLock', 'Condition')


def check_package(modules: List[ParsedModule], config: Config):
  findings: List[Finding] = []
  states = []
  for mod in modules:
    if not in_scope(mod.relpath, config.lock_modules):
      continue
    try:
      st = _ModState(mod)
      states.append(st)
      findings.extend(_check_shared(st))
    except RecursionError:
      pass
  findings.extend(_check_lock_order(states))
  return findings


def _norm_lock(arg: str) -> str:
  arg = arg.strip()
  return arg[5:] if arg.startswith('self.') else arg


class _ModState:
  def __init__(self, mod: ParsedModule):
    self.mod = mod
    self.index = astutil.FuncIndex(mod.tree)
    self.aliases = astutil.import_aliases(mod.tree)
    self.parents = astutil.parent_map(mod.tree)
    # registered shared fields: (class or None, field) -> lock name
    self.shared: Dict[Tuple[Optional[str], str], str] = {}
    # methods annotated locked[lock]: qualname -> lock name
    self.locked: Dict[str, str] = {}
    # declared lock objects: class -> {attr}, plus module-level names
    self.class_locks: Dict[str, Set[str]] = {}
    self.module_locks: Set[str] = set()
    # Condition-over-lock aliases: (class, attr) -> guarded attr
    self.cond_alias: Dict[Tuple[Optional[str], str], str] = {}
    self._scan_locks()
    self._scan_annotations()

  # -- structure helpers

  def class_of(self, node) -> Optional[str]:
    n = self.parents.get(node)
    while n is not None:
      if isinstance(n, ast.ClassDef):
        return n.name
      n = self.parents.get(n)
    return None

  def _scan_locks(self):
    for node in ast.walk(self.mod.tree):
      if not isinstance(node, ast.Assign) or \
          not isinstance(node.value, ast.Call):
        continue
      name = astutil.canonical(astutil.call_name(node.value),
                               self.aliases)
      if not astutil.matches(name, _LOCK_CTORS):
        continue
      is_cond = astutil.last_segment(name) == 'Condition'
      wraps = None
      if is_cond and node.value.args:
        a0 = node.value.args[0]
        if isinstance(a0, ast.Attribute) and \
            isinstance(a0.value, ast.Name) and a0.value.id == 'self':
          wraps = a0.attr
        elif isinstance(a0, ast.Name):
          wraps = a0.id
      for t in node.targets:
        if isinstance(t, ast.Attribute) and \
            isinstance(t.value, ast.Name) and t.value.id == 'self':
          cls = self.class_of(node)
          if cls:
            self.class_locks.setdefault(cls, set()).add(t.attr)
            if wraps:
              self.cond_alias[(cls, t.attr)] = wraps
        elif isinstance(t, ast.Name):
          cls = self.class_of(node)
          if cls is None:
            self.module_locks.add(t.id)
            if wraps:
              self.cond_alias[(None, t.id)] = wraps

  def _stmt_at(self, line: int) -> Optional[ast.stmt]:
    best = None
    for node in ast.walk(self.mod.tree):
      if isinstance(node, ast.stmt) and \
          node.lineno <= line <= (node.end_lineno or node.lineno):
        if best is None or node.lineno >= best.lineno:
          best = node
    return best

  def _scan_annotations(self):
    for line, entries in self.mod.annotations.items():
      for kind, arg in entries:
        if kind == 'locked':
          stmt = self._stmt_at(line)
          if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = self.index.lookup(stmt)
            if fi is not None:
              self.locked[fi.qualname] = _norm_lock(arg)
        elif kind == 'shared':
          stmt = self._stmt_at(line)
          if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
          targets = stmt.targets if isinstance(stmt, ast.Assign) \
              else [stmt.target]
          for t in targets:
            if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == 'self':
              cls = self.class_of(stmt)
              if cls:
                self.shared[(cls, t.attr)] = _norm_lock(arg)
            elif isinstance(t, ast.Name):
              cls = self.class_of(stmt)
              # a bare-name target inside a class body is a class
              # attribute; at module level it is a global
              self.shared[(cls, t.id)] = _norm_lock(arg)

  # -- lock-holding queries

  def _holds(self, cls: Optional[str], attr_or_name: str,
             lock: str) -> bool:
    """Does acquiring ``attr_or_name`` (in class ``cls``) hold
    ``lock``? Identity or a Condition built over it."""
    if attr_or_name == lock:
      return True
    return self.cond_alias.get((cls, attr_or_name)) == lock

  def with_held(self, node, fi: astutil.FuncInfo, cls: Optional[str],
                lock: str) -> bool:
    """Is ``node`` structurally inside a with-statement acquiring
    ``lock`` (within the same function)?"""
    n = self.parents.get(node)
    while n is not None and n is not fi.node:
      if isinstance(n, (ast.With, ast.AsyncWith)):
        for item in n.items:
          ce = item.context_expr
          if isinstance(ce, ast.Attribute) and \
              isinstance(ce.value, ast.Name) and ce.value.id == 'self':
            if self._holds(cls, ce.attr, lock):
              return True
          elif isinstance(ce, ast.Name):
            if self._holds(None, ce.id, lock):
              return True
      n = self.parents.get(n)
    return False

  def method_assumes(self, fi: astutil.FuncInfo, lock: str) -> bool:
    f = fi
    while f is not None:   # nested defs inherit the method's assumption
      if self.locked.get(f.qualname) == lock:
        return True
      f = f.parent
    return False


# ---------------------------------------------------------- shared access

def _check_shared(st: _ModState) -> List[Finding]:
  out: List[Finding] = []
  if not st.shared:
    return out
  by_class: Dict[Optional[str], Dict[str, str]] = {}
  for (cls, field), lock in st.shared.items():
    by_class.setdefault(cls, {})[field] = lock

  for fi in st.index.by_qual.values():
    cls = st.class_of(fi.node)
    # the (class-level) method this def belongs to, for the __init__
    # exemption — nested defs inherit their method's status
    parts = fi.qualname.split('.')
    top_method = parts[1] if cls is not None and len(parts) > 1 \
        else parts[0]
    fields = by_class.get(cls, {}) if cls is not None else {}
    globals_ = by_class.get(None, {})
    for node in st.index.own_nodes(fi):
      hit = None   # (display, lock, cls-context)
      if isinstance(node, ast.Attribute) and \
          isinstance(node.value, ast.Name) and node.value.id == 'self' \
          and node.attr in fields:
        hit = (f'self.{node.attr}', fields[node.attr], cls)
      elif isinstance(node, ast.Name) and node.id in globals_:
        hit = (node.id, globals_[node.id], None)
      if hit is None:
        continue
      display, lock, hit_cls = hit
      if top_method == '__init__':
        continue   # construction precedes sharing
      if st.method_assumes(fi, lock):
        continue
      if st.with_held(node, fi, hit_cls, lock):
        continue
      prefix = 'self.' if hit_cls is not None else ''
      out.append(Finding(
          RULE, st.mod.path, st.mod.relpath, node.lineno,
          node.col_offset + 1,
          f"'{display}' is registered shared[{lock}] but is accessed "
          f"outside 'with {prefix}{lock}:' — hold the lock, or mark "
          f"the enclosing method '# graftlint: locked[{lock}]' if "
          'every caller already holds it',
          symbol=fi.qualname))

  # locked[] methods: every intra-class call site must hold the lock
  for qual, lock in st.locked.items():
    if '.' not in qual:
      continue
    cls, mname = qual.split('.', 1)[0], qual.rsplit('.', 1)[-1]
    for fi in st.index.by_qual.values():
      if st.class_of(fi.node) != cls or fi.qualname == qual:
        continue
      if fi.qualname.split('.', 1)[-1].split('.')[0] == '__init__':
        continue
      for node in st.index.own_nodes(fi):
        if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            isinstance(node.func.value, ast.Name) and \
            node.func.value.id == 'self' and node.func.attr == mname:
          if st.method_assumes(fi, lock) or \
              st.with_held(node, fi, cls, lock):
            continue
          out.append(Finding(
              RULE, st.mod.path, st.mod.relpath, node.lineno,
              node.col_offset + 1,
              f"'self.{mname}()' assumes {lock} is held "
              f'(locked[{lock}]) but this call site does not hold it',
              symbol=fi.qualname))
  return out


# ------------------------------------------------------------- lock order

def _lock_id(st: _ModState, cls: Optional[str], name: str) -> Optional[str]:
  """Canonical id of the lock acquired by ``with self.<name>:`` (cls
  set) or ``with <name>:`` (module level); Conditions resolve to the
  lock they wrap."""
  wrapped = st.cond_alias.get((cls, name))
  if wrapped is not None:
    name = wrapped
  if cls is not None and name in st.class_locks.get(cls, set()):
    return f'{st.mod.relpath}:{cls}.{name}'
  if name in st.module_locks:
    return f'{st.mod.relpath}:{name}'
  return None


def _with_locks(st: _ModState, node, cls) -> List[str]:
  out = []
  if isinstance(node, (ast.With, ast.AsyncWith)):
    for item in node.items:
      ce = item.context_expr
      if isinstance(ce, ast.Attribute) and \
          isinstance(ce.value, ast.Name) and ce.value.id == 'self':
        lid = _lock_id(st, cls, ce.attr)
      elif isinstance(ce, ast.Name):
        lid = _lock_id(st, None, ce.id)
      else:
        lid = None
      if lid:
        out.append(lid)
  return out


def _resolve_callee(st: _ModState, states_by_mod, call: ast.Call,
                    cls: Optional[str]) -> Optional[Tuple[str, str]]:
  """(module path, qualname) of the called function when resolvable:
  self-method, same-module function, or imported-module function."""
  f = call.func
  if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
    if f.value.id == 'self' and cls is not None:
      qual = f'{cls}.{f.attr}'
      if qual in st.index.by_qual:
        return (st.mod.path, qual)
      return None
    target_mod = st.aliases.get(f.value.id)
    if target_mod:
      suffix = target_mod.replace('.', '/') + '.py'
      for other in states_by_mod.values():
        if other.mod.relpath.endswith(suffix) and \
            f.attr in other.index.by_qual:
          return (other.mod.path, f.attr)
    return None
  if isinstance(f, ast.Name) and f.id in st.index.by_qual:
    return (st.mod.path, f.id)
  return None


def _check_lock_order(states: List[_ModState]) -> List[Finding]:
  states_by_mod = {st.mod.path: st for st in states}
  if not states:
    return []

  # direct acquisitions + resolvable call edges per function
  direct: Dict[Tuple[str, str], Set[str]] = {}
  calls: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
  for st in states:
    for fi in st.index.by_qual.values():
      key = (st.mod.path, fi.qualname)
      cls = st.class_of(fi.node)
      acq: Set[str] = set()
      cs: Set[Tuple[str, str]] = set()
      for node in st.index.own_nodes(fi):
        acq.update(_with_locks(st, node, cls))
        if isinstance(node, ast.Call):
          callee = _resolve_callee(st, states_by_mod, node, cls)
          if callee:
            cs.add(callee)
      lock = st.locked.get(fi.qualname)
      if lock:
        lid = _lock_id(st, cls, lock)
        if lid:
          acq.add(lid)
      direct[key] = acq
      calls[key] = cs

  # transitive closure: locks a call may acquire
  star = {k: set(v) for k, v in direct.items()}
  changed = True
  while changed:
    changed = False
    for k, cs in calls.items():
      for callee in cs:
        extra = star.get(callee, set()) - star[k]
        if extra:
          star[k] |= extra
          changed = True

  # ordering edges: held lock -> lock acquired under it
  edges: Dict[str, Dict[str, Tuple[str, str, int]]] = {}

  def add_edge(a: str, b: str, st: _ModState, line: int):
    if a == b:
      return   # re-entrant self-acquire (RLock) is not an order edge
    edges.setdefault(a, {}).setdefault(
        b, (st.mod.path, st.mod.relpath, line))

  for st in states:
    for fi in st.index.by_qual.values():
      cls = st.class_of(fi.node)
      held_entry: List[Tuple[ast.AST, List[str]]] = []
      lock = st.locked.get(fi.qualname)
      assumed: List[str] = []
      if lock:
        lid = _lock_id(st, cls, lock)
        if lid:
          assumed.append(lid)
      for node in st.index.own_nodes(fi):
        w = _with_locks(st, node, cls)
        if w:
          held_entry.append((node, w))
      # multi-item with: earlier items are held when later ones acquire
      for node, w in held_entry:
        for i, a in enumerate(w):
          for b in w[i + 1:]:
            add_edge(a, b, st, node.lineno)
      # nesting: anything under a with-lock region
      for node, w in held_entry:
        for sub in ast.walk(node):
          if sub is node:
            continue
          if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
          inner = _with_locks(st, sub, cls)
          for a in w:
            for b in inner:
              add_edge(a, b, st, sub.lineno)
          if isinstance(sub, ast.Call):
            callee = _resolve_callee(st, states_by_mod, sub, cls)
            if callee:
              for a in w:
                for b in star.get(callee, ()):
                  add_edge(a, b, st, sub.lineno)
      # locked[] methods: body runs with the assumed lock held
      for a in assumed:
        for node in st.index.own_nodes(fi):
          for b in _with_locks(st, node, cls):
            add_edge(a, b, st, node.lineno)
          if isinstance(node, ast.Call):
            callee = _resolve_callee(st, states_by_mod, node, cls)
            if callee:
              for b in star.get(callee, ()):
                add_edge(a, b, st, node.lineno)

  return _cycle_findings(edges)


def _cycle_findings(edges) -> List[Finding]:
  """One finding per strongly-connected component with >= 2 locks."""
  index_of: Dict[str, int] = {}
  low: Dict[str, int] = {}
  on_stack: Set[str] = set()
  stack: List[str] = []
  sccs: List[List[str]] = []
  counter = [0]

  def strongconnect(v):
    work = [(v, iter(sorted(edges.get(v, {}))))]
    index_of[v] = low[v] = counter[0]
    counter[0] += 1
    stack.append(v)
    on_stack.add(v)
    while work:
      node, it = work[-1]
      advanced = False
      for w in it:
        if w not in index_of:
          index_of[w] = low[w] = counter[0]
          counter[0] += 1
          stack.append(w)
          on_stack.add(w)
          work.append((w, iter(sorted(edges.get(w, {})))))
          advanced = True
          break
        elif w in on_stack:
          low[node] = min(low[node], index_of[w])
      if advanced:
        continue
      work.pop()
      if work:
        low[work[-1][0]] = min(low[work[-1][0]], low[node])
      if low[node] == index_of[node]:
        comp = []
        while True:
          w = stack.pop()
          on_stack.discard(w)
          comp.append(w)
          if w == node:
            break
        sccs.append(comp)

  all_nodes = set(edges)
  for tgts in edges.values():
    all_nodes.update(tgts)
  for v in sorted(all_nodes):
    if v not in index_of:
      strongconnect(v)

  out = []
  for comp in sccs:
    if len(comp) < 2:
      continue
    comp_set = set(comp)
    sites = []
    for a in comp:
      for b, (path, relpath, line) in edges.get(a, {}).items():
        if b in comp_set:
          sites.append((relpath, line, path, a, b))
    sites.sort()
    if not sites:
      continue
    relpath, line, path, _a, _b = sites[0]
    names = ' -> '.join(sorted(c.rsplit(':', 1)[-1] for c in comp))
    out.append(Finding(
        RULE, path, relpath, line, 1,
        f'lock-order cycle between {{{names}}} — these locks are '
        'acquired in conflicting orders on different paths (ABBA '
        'deadlock); pick one global order and hold to it',
        symbol=''))
  return out
