"""Rule prng-discipline: counter-addressed keys, never split-and-carry.

The scanned-epoch replay contracts (PR 2 worker restart, PR 4 scanned
chunks) depend on every sampler/loader PRNG stream being COUNTER
ADDRESSED: step g's key is ``fold_in(base_key, count0 + g)`` (sharded:
``split(fold_in(base, count), P)`` — DistNeighborSampler._keys_for), so
any position in the stream is reachable from (base_key, integer) alone.
Split-and-carry (``key, sub = split(key)``) makes position N reachable
only by replaying N splits — a restarted worker or a scanned chunk
cannot jump to its offset, and the bit-identical-replay guarantees in
docs/failure_model.md silently break.

Flags, in sampler/loader-scoped modules:

  * split-and-carry: a ``jax.random.split`` result assigned back over
    its own key argument (``key, sub = split(key)``,
    ``self._key, s = split(self._key)``).
  * constant-key loops: ``jax.random.PRNGKey(...)`` created inside a
    ``for``/``while`` body — every iteration draws the same stream.
  * key reuse: the same key name consumed by two jax.random draws with
    no intervening reassignment — two identical draws where the author
    almost certainly wanted two streams.
"""
import ast
from typing import List

from . import astutil
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'prng-discipline'

# draws that CONSUME a key (same key twice == same randomness twice);
# fold_in is exempt — fold_in(key, a) / fold_in(key, b) IS the pattern
_CONSUMERS = {
    'split', 'bits', 'uniform', 'normal', 'randint', 'bernoulli',
    'categorical', 'choice', 'permutation', 'gumbel', 'exponential',
    'truncated_normal', 'shuffle',
}


def check_package(modules: List[ParsedModule], config: Config):
  findings = []
  for mod in modules:
    if not in_scope(mod.relpath, config.prng_modules):
      continue
    findings.extend(_check_module(mod))
  return findings


def _key_expr(node: ast.AST) -> str:
  """Comparable identity for a key expression: bare name or self.attr."""
  if isinstance(node, ast.Name):
    return node.id
  name = astutil.dotted_name(node)
  return name or ''


def _is_random_call(call: ast.Call, attr: str) -> bool:
  name = astutil.call_name(call)
  seg = astutil.last_segment(name)
  if seg != attr:
    return False
  # 'jax.random.split' / 'random.split' / bare 'split' (from-import)
  return name in (attr, f'random.{attr}', f'jax.random.{attr}',
                  f'jrandom.{attr}', f'jr.{attr}')


def _check_module(mod: ParsedModule) -> List[Finding]:
  out: List[Finding] = []
  index = astutil.FuncIndex(mod.tree)
  aliases = astutil.import_aliases(mod.tree)

  # ---- split-and-carry ------------------------------------------------
  for node in ast.walk(mod.tree):
    if not isinstance(node, ast.Assign):
      continue
    call = node.value
    if not (isinstance(call, ast.Call) and _is_random_call(call, 'split')
            and call.args):
      continue
    key_id = _key_expr(call.args[0])
    if not key_id:
      continue
    targets = []
    for t in node.targets:
      targets.extend(t.elts if isinstance(t, ast.Tuple) else [t])
    for t in targets:
      if _key_expr(t) == key_id:
        out.append(Finding(
            RULE, mod.path, mod.relpath, node.lineno, node.col_offset + 1,
            f'split-and-carry: jax.random.split({key_id}) assigned back '
            f'over {key_id} — stream position N is then only reachable '
            'by N sequential splits, which breaks scan replay and '
            'worker-restart fast-forward (docs/failure_model.md). Use '
            'the counter pattern: fold_in(base_key, count) per call '
            '(sharded: split(fold_in(base, count), P))'))
        break

  # ---- PRNGKey inside a loop ------------------------------------------
  for node in ast.walk(mod.tree):
    if not isinstance(node, (ast.For, ast.While)):
      continue
    for sub in ast.walk(node):
      if isinstance(sub, ast.Call) and _is_random_call(sub, 'PRNGKey'):
        out.append(Finding(
            RULE, mod.path, mod.relpath, sub.lineno, sub.col_offset + 1,
            'jax.random.PRNGKey(...) constructed inside a loop — unless '
            'the seed varies per iteration this redraws one stream; '
            'hoist the base key and fold_in the loop counter'))

  # ---- key reuse (per function, lexical) -------------------------------
  for fi in index.by_qual.values():
    uses = {}      # key name -> [linenos of consuming draws]
    assigns = {}   # key name -> [linenos of reassignment]
    for node in index.own_nodes(fi):
      if isinstance(node, ast.Call):
        seg = astutil.last_segment(astutil.call_name(node))
        if seg in _CONSUMERS and node.args and \
            isinstance(node.args[0], ast.Name) and \
            _looks_like_random(node, aliases):
          uses.setdefault(node.args[0].id, []).append(node.lineno)
      for tgt in _assigned_names(node):
        assigns.setdefault(tgt, []).append(node.lineno)
    for key, lines in uses.items():
      lines.sort()
      re_lines = sorted(assigns.get(key, []))
      for a, b in zip(lines, lines[1:]):
        if not any(a < r <= b for r in re_lines):
          out.append(Finding(
              RULE, mod.path, mod.relpath, b, 1,
              f'key reuse: {key!r} is consumed by two jax.random draws '
              f'(lines {a} and {b}) with no reassignment between them — '
              'identical randomness twice; derive one key per draw '
              '(fold_in or split)', symbol=fi.qualname))
  return out


def _looks_like_random(call: ast.Call, aliases) -> bool:
  """Only count draws that resolve to jax.random — NOT numpy's host RNG
  (np.random.permutation twice on one array is the established loader
  idiom, not key reuse) and not stdlib random."""
  name = astutil.call_name(call) or ''
  cname = astutil.canonical(name, aliases) or ''
  if cname.startswith('jax.random.'):
    return True
  # unresolvable conventional jax.random aliases (jr/jrandom)
  return name.split('.', 1)[0] in ('jr', 'jrandom')


def _assigned_names(node: ast.AST):
  if isinstance(node, ast.Assign):
    for t in node.targets:
      for e in (t.elts if isinstance(t, ast.Tuple) else [t]):
        if isinstance(e, ast.Name):
          yield e.id
  elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
    if isinstance(node.target, ast.Name):
      yield node.target.id
  elif isinstance(node, ast.For):
    t = node.target
    for e in (t.elts if isinstance(t, ast.Tuple) else [t]):
      if isinstance(e, ast.Name):
        yield e.id
