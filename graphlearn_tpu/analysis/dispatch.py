"""Rule dispatch-instrumentation: every jitted entrypoint dispatch in a
hot module must be counted.

The dispatch-budget asserts (tests/test_scan_epoch.py,
tests/test_dist_scan_epoch.py, bench.py ``epoch_dispatches``) are only
meaningful if EVERY hot-path program launch calls
``utils.trace.record_dispatch`` at its dispatch site (or is wrapped in
``wrap_dispatch``). An un-instrumented ``jax.jit`` entrypoint silently
deflates the counted budget — the budget test keeps passing while the
epoch quietly pays more dispatches than it asserts (exactly the
regression PERF.md's wall-clock-scales-with-dispatches finding makes
expensive).

Model (per module, name-based dataflow):

  * ``jax.jit(...)`` / ``shard_map(...)`` call results are HANDLES.
  * Handles propagate through local names, ``self.attr`` stores,
    container stores (``self._fns[k] = jfn``), returns (making the
    enclosing def a FACTORY), and calls of factories — plus the
    cross-module factories named in ``Config.known_jit_factories``.
  * A CALL of a handle is a dispatch site. It is fine when (a) the
    enclosing function is traced (jit-of-jit composes into the outer
    program — instrumenting there would count per trace, not per call),
    (b) ``record_dispatch``/``wrap_dispatch`` appears lexically before
    it in the same function, or (c) the enclosing function itself
    becomes a handle (a dispatch wrapper like DistFeature._build_fn's
    ``run``) whose OWN call sites are then checked — the fixpoint walks
    the wrapping chain up to wherever instrumentation must live.
  * Anything left is a finding at the original call site.
"""
import ast
from typing import Dict, List, Optional, Set

from . import astutil
from .core import Config, Finding, ParsedModule, in_scope

RULE = 'dispatch-instrumentation'

_INSTRUMENT_CALLS = ('record_dispatch', 'wrap_dispatch')


def check_package(modules: List[ParsedModule], config: Config):
  findings = []
  for mod in modules:
    if not in_scope(mod.relpath, config.dispatch_modules):
      continue
    findings.extend(_check_module(mod, config))
  return findings


class _ModuleState:
  def __init__(self, mod: ParsedModule, config: Config):
    self.mod = mod
    self.index = astutil.FuncIndex(mod.tree)
    self.aliases = astutil.import_aliases(mod.tree)
    self.traced = astutil.traced_functions(self.index, mod.tree,
                                           self.aliases)
    self.parents = astutil.parent_map(mod.tree)
    # handle identities: local names are scoped per function qualname
    self.attr_handles: Set[str] = set()        # self.<attr> is a handle
    self.container_attrs: Set[str] = set()     # self.<attr>[...] handles
    self.factories: Set[str] = set(config.known_jit_factories)
    self.local_handles: Dict[str, Set[str]] = {}  # fn qual -> names
    self.wrapped: Set[str] = set()             # wrap_dispatch products

  def scope_of(self, node) -> str:
    fi = astutil.enclosing_function(self.index, node, self.parents)
    return fi.qualname if fi else '<module>'


def _check_module(mod: ParsedModule, config: Config) -> List[Finding]:
  st = _ModuleState(mod, config)
  _seed_handles(st)
  sites = _propagate(st)
  out = []
  for call, qual in sites:
    out.append(Finding(
        RULE, mod.path, mod.relpath, call.lineno, call.col_offset + 1,
        'jitted program dispatched without instrumentation — call '
        'utils.trace.record_dispatch(<site>) immediately before the '
        'dispatch (or build the callable with wrap_dispatch) so the '
        'epoch dispatch budgets stay exact', symbol=qual))
  return out


def _is_handle_expr(st: _ModuleState, node: ast.AST, scope: str) -> bool:
  """Does this expression evaluate to a jitted callable?"""
  if isinstance(node, ast.Call):
    name = astutil.call_name(node)
    seg = astutil.last_segment(name)
    if seg == 'jit' or seg == 'shard_map':
      return True
    if seg == 'wrap_dispatch':
      return False    # instrumented at build — never a violation
    if seg in st.factories:
      return True
    return False
  if isinstance(node, ast.Name):
    return node.id in st.local_handles.get(scope, set()) or \
        node.id in st.local_handles.get('<module>', set())
  if isinstance(node, ast.Attribute):
    return node.attr in st.attr_handles
  if isinstance(node, ast.Subscript):
    base = node.value
    if isinstance(base, ast.Attribute) and base.attr in st.container_attrs:
      return True
    if isinstance(base, ast.Name):
      return base.id in st.local_handles.get(scope, set())
    return False
  if isinstance(node, ast.Tuple):
    return any(_is_handle_expr(st, e, scope) for e in node.elts)
  return False


def _seed_handles(st: _ModuleState):
  """First pass: direct jit/shard_map/factory results into names."""
  changed = True
  while changed:
    changed = False
    for node in ast.walk(st.mod.tree):
      if isinstance(node, ast.Assign):
        scope = st.scope_of(node)
        if _is_handle_expr(st, node.value, scope):
          for t in node.targets:
            changed |= _bind_target(st, t, scope)
      elif isinstance(node, ast.Return) and node.value is not None:
        scope = st.scope_of(node)
        if scope != '<module>' and \
            _is_handle_expr(st, node.value, scope):
          fn_name = scope.rsplit('.', 1)[-1]
          if fn_name not in st.factories:
            st.factories.add(fn_name)
            changed = True


def _bind_target(st: _ModuleState, t: ast.AST, scope: str) -> bool:
  if isinstance(t, ast.Name):
    s = st.local_handles.setdefault(scope, set())
    if t.id not in s:
      s.add(t.id)
      return True
  elif isinstance(t, ast.Attribute):
    if t.attr not in st.attr_handles:
      st.attr_handles.add(t.attr)
      return True
  elif isinstance(t, ast.Subscript):
    base = t.value
    if isinstance(base, ast.Attribute) and \
        base.attr not in st.container_attrs:
      st.container_attrs.add(base.attr)
      return True
  elif isinstance(t, ast.Tuple):
    return any(_bind_target(st, e, scope) for e in t.elts)
  return False


def _propagate(st: _ModuleState):
  """Fixpoint: find uninstrumented handle-call sites; a plain function
  containing one becomes a handle itself (its callers must instrument),
  until no new handles appear. Returns surviving violation sites."""
  for _round in range(20):
    sites = _dispatch_sites(st)
    new_handle = False
    for call, qual in sites:
      if qual == '<module>':
        continue
      fn_name = qual.rsplit('.', 1)[-1]
      fi = st.index.by_qual.get(qual)
      referenced = fi is not None and _is_referenced(st, fi)
      if referenced and fn_name not in st.factories and \
          not _name_is_handle(st, fn_name):
        # the wrapper itself dispatches: its call sites take over
        if fi.is_nested or fi.parent is not None:
          st.local_handles.setdefault(
              _parent_scope(fi), set()).add(fn_name)
        else:
          st.attr_handles.add(fn_name)
        new_handle = True
    if not new_handle:
      return [s for s in sites if not _excused(st, s)]
    _seed_handles(st)   # re-run: new handles may flow into factories
  return [s for s in _dispatch_sites(st) if not _excused(st, s)]


def _parent_scope(fi: astutil.FuncInfo) -> str:
  return fi.parent.qualname if fi.parent is not None else '<module>'


def _name_is_handle(st: _ModuleState, name: str) -> bool:
  if name in st.attr_handles:
    return True
  return any(name in s for s in st.local_handles.values())


def _is_referenced(st: _ModuleState, fi: astutil.FuncInfo) -> bool:
  """Is this def stored/returned/called anywhere else in the module?"""
  name = fi.node.name
  for node in ast.walk(st.mod.tree):
    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) and \
        node.id == name:
      f = astutil.enclosing_function(st.index, node, st.parents)
      if f is None or f.qualname != fi.qualname:
        return True
    if isinstance(node, ast.Attribute) and node.attr == name and \
        not isinstance(node.ctx, ast.Store):
      return True
  return False


def _excused(st: _ModuleState, site) -> bool:
  call, qual = site
  fn_name = qual.rsplit('.', 1)[-1] if qual != '<module>' else ''
  # the enclosing fn became a handle/factory: checking moved to callers
  if fn_name and (fn_name in st.factories or _name_is_handle(st, fn_name)):
    fi = st.index.by_qual.get(qual)
    return fi is not None and _is_referenced(st, fi)
  return False


def _dispatch_sites(st: _ModuleState):
  """(call, enclosing-qualname) of uninstrumented handle calls."""
  sites = []
  for node in ast.walk(st.mod.tree):
    if not isinstance(node, ast.Call):
      continue
    if not _is_handle_expr(st, node.func, st.scope_of(node)):
      continue
    # a handle mentioned as a factory call's FUNC of form
    # self._chunk_fn_for(k)(...): func is a Call -> dispatch of its result
    fi = astutil.enclosing_function(st.index, node, st.parents)
    qual = fi.qualname if fi else '<module>'
    if fi is not None and fi.qualname in st.traced:
      continue                      # jit-of-jit: composes, not dispatches
    if _instrumented_before(st, fi, node):
      continue
    sites.append((node, qual))
  return sites


def _instrumented_before(st: _ModuleState, fi: Optional[astutil.FuncInfo],
                         call: ast.Call) -> bool:
  if fi is None:
    return False
  for node in st.index.own_nodes(fi):
    if isinstance(node, ast.Call) and \
        astutil.last_segment(astutil.call_name(node)) in \
        _INSTRUMENT_CALLS and node.lineno <= call.lineno:
      return True
  return False
