"""Rule compat-shard-map: shard_map resolves ONLY through utils/compat.

``jax.shard_map`` is a moving target across the jax versions this
package must run on (top-level export on the TPU rig's jax, the
``jax.experimental.shard_map`` module on the 0.4.x CI images, and the
``check_rep``/``check_vma`` keyword rename between them).
``utils/compat.py`` owns that resolution; a direct import anywhere else
reintroduces exactly the ~40-collection-failure class of breakage PR 3
fixed, invisible until the code runs on the other jax.
"""
import ast
from typing import List

from . import astutil
from .core import Config, Finding, ParsedModule

RULE = 'compat-shard-map'

_MSG = ('direct {what} — shard_map must resolve through '
        'utils/compat.py (version shim: top-level vs experimental home, '
        'check_rep/check_vma rename); import '
        '`from ..utils.compat import shard_map` instead')


def check_package(modules: List[ParsedModule], config: Config):
  out: List[Finding] = []
  for mod in modules:
    if mod.relpath == config.compat_module:
      continue
    for node in ast.walk(mod.tree):
      what = None
      if isinstance(node, ast.Import):
        for a in node.names:
          if a.name.startswith('jax.experimental.shard_map'):
            what = f'`import {a.name}`'
      elif isinstance(node, ast.ImportFrom):
        m = (node.module or '')
        if m.startswith('jax.experimental.shard_map'):
          what = f'`from {m} import ...`'
        elif m == 'jax' and any(a.name == 'shard_map'
                                for a in node.names):
          what = '`from jax import shard_map`'
        elif m == 'jax.experimental' and any(a.name == 'shard_map'
                                             for a in node.names):
          what = '`from jax.experimental import shard_map`'
      elif isinstance(node, ast.Attribute):
        dn = astutil.dotted_name(node)
        if dn in ('jax.shard_map', 'jax.experimental.shard_map',
                  'jax.experimental.shard_map.shard_map'):
          what = f'use of `{dn}`'
      if what:
        out.append(Finding(RULE, mod.path, mod.relpath, node.lineno,
                           node.col_offset + 1, _MSG.format(what=what)))
  return out
