// Shared-memory ring buffer of variable-size blocks (host-side runtime).
//
// TPU-native counterpart of the reference's ShmQueue
// (/root/reference/graphlearn_torch/csrc/shm_queue.cc + include/shm_queue.h):
// a cross-process queue feeding sampled batches from producer processes to
// the training process. The reference uses per-block read/write semaphores
// over POSIX shm and pins the ring for CUDA H2D; on TPU the consumer is the
// single host process driving the chips, so the design is a SysV-shm byte
// ring with process-shared mutex/condvars (simpler, same contract:
// blocking enqueue on full, timeout dequeue, picklable-by-shmid attach —
// reference py_export.cc:137-154).
//
// C ABI so Python binds via ctypes (pybind11 is not in the image).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <pthread.h>
#include <sys/ipc.h>
#include <sys/shm.h>

namespace {

struct QueueMeta {
  pthread_mutex_t mutex;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint64_t capacity;     // ring payload bytes
  uint64_t head;         // read offset (monotonic)
  uint64_t tail;         // write offset (monotonic)
  uint64_t count;        // blocks currently queued
  uint32_t finished;     // producer-done flag (end-of-epoch protocol)
  uint32_t _pad;
};

// Each block: 8-byte little-endian size header, then payload, 8-byte aligned.
constexpr uint64_t kAlign = 8;

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

struct Queue {
  QueueMeta* meta;
  uint8_t* data;
  int shmid;
};

uint64_t used(const QueueMeta* m) { return m->tail - m->head; }

void write_ring(Queue* q, uint64_t pos, const void* src, uint64_t n) {
  uint64_t off = pos % q->meta->capacity;
  uint64_t first = q->meta->capacity - off;
  if (n <= first) {
    memcpy(q->data + off, src, n);
  } else {
    memcpy(q->data + off, src, first);
    memcpy(q->data, static_cast<const uint8_t*>(src) + first, n - first);
  }
}

void read_ring(Queue* q, uint64_t pos, void* dst, uint64_t n) {
  uint64_t off = pos % q->meta->capacity;
  uint64_t first = q->meta->capacity - off;
  if (n <= first) {
    memcpy(dst, q->data + off, n);
  } else {
    memcpy(dst, q->data + off, first);
    memcpy(static_cast<uint8_t*>(dst) + first, q->data, n - first);
  }
}

timespec deadline_ms(long ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += ms / 1000;
  ts.tv_nsec += (ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

}  // namespace

extern "C" {

// Create a queue with `capacity` payload bytes. Returns an opaque handle
// (0 on failure).
void* shmq_create(uint64_t capacity) {
  uint64_t total = sizeof(QueueMeta) + capacity;
  int shmid = shmget(IPC_PRIVATE, total, IPC_CREAT | 0600);
  if (shmid < 0) return nullptr;
  void* addr = shmat(shmid, nullptr, 0);
  if (addr == reinterpret_cast<void*>(-1)) return nullptr;
  // destroy-on-last-detach (reference ShmQueue marks IPC_RMID the same way)
  shmctl(shmid, IPC_RMID, nullptr);
  auto* meta = static_cast<QueueMeta*>(addr);
  memset(meta, 0, sizeof(QueueMeta));
  meta->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutex_init(&meta->mutex, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&meta->not_full, &ca);
  pthread_cond_init(&meta->not_empty, &ca);

  auto* q = new Queue;
  q->meta = meta;
  q->data = static_cast<uint8_t*>(addr) + sizeof(QueueMeta);
  q->shmid = shmid;
  return q;
}

// Attach to an existing queue by shmid (consumer side after fork/spawn).
void* shmq_attach(int shmid) {
  void* addr = shmat(shmid, nullptr, 0);
  if (addr == reinterpret_cast<void*>(-1)) return nullptr;
  auto* q = new Queue;
  q->meta = static_cast<QueueMeta*>(addr);
  q->data = static_cast<uint8_t*>(addr) + sizeof(QueueMeta);
  q->shmid = shmid;
  return q;
}

int shmq_id(void* handle) { return static_cast<Queue*>(handle)->shmid; }

// Blocking enqueue of one block. Returns 0 ok, -1 if block can never fit.
int shmq_enqueue(void* handle, const void* buf, uint64_t size) {
  auto* q = static_cast<Queue*>(handle);
  QueueMeta* m = q->meta;
  uint64_t need = align_up(size + 8);
  if (need > m->capacity) return -1;
  pthread_mutex_lock(&m->mutex);
  while (m->capacity - used(m) < need) {
    pthread_cond_wait(&m->not_full, &m->mutex);
  }
  uint64_t hdr = size;
  write_ring(q, m->tail, &hdr, 8);
  write_ring(q, m->tail + 8, buf, size);
  m->tail += need;
  m->count += 1;
  pthread_cond_signal(&m->not_empty);
  pthread_mutex_unlock(&m->mutex);
  return 0;
}

// Peek next block's size, waiting up to timeout_ms. Returns size, or
// -1 on timeout (reference QueueTimeoutError), or -2 if finished+empty.
int64_t shmq_next_size(void* handle, long timeout_ms) {
  auto* q = static_cast<Queue*>(handle);
  QueueMeta* m = q->meta;
  timespec ts = deadline_ms(timeout_ms);
  pthread_mutex_lock(&m->mutex);
  while (m->count == 0) {
    if (m->finished) {
      pthread_mutex_unlock(&m->mutex);
      return -2;
    }
    if (timeout_ms < 0) {
      pthread_cond_wait(&m->not_empty, &m->mutex);
    } else if (pthread_cond_timedwait(&m->not_empty, &m->mutex, &ts) ==
               ETIMEDOUT) {
      pthread_mutex_unlock(&m->mutex);
      return -1;
    }
  }
  uint64_t hdr;
  read_ring(q, m->head, &hdr, 8);
  pthread_mutex_unlock(&m->mutex);
  return static_cast<int64_t>(hdr);
}

// Dequeue one block into buf (must be >= its size; call shmq_next_size
// first). Returns block size, -1 on timeout, -2 finished, -3 buf too small.
int64_t shmq_dequeue(void* handle, void* buf, uint64_t bufsize,
                     long timeout_ms) {
  auto* q = static_cast<Queue*>(handle);
  QueueMeta* m = q->meta;
  timespec ts = deadline_ms(timeout_ms);
  pthread_mutex_lock(&m->mutex);
  while (m->count == 0) {
    if (m->finished) {
      pthread_mutex_unlock(&m->mutex);
      return -2;
    }
    if (timeout_ms < 0) {
      pthread_cond_wait(&m->not_empty, &m->mutex);
    } else if (pthread_cond_timedwait(&m->not_empty, &m->mutex, &ts) ==
               ETIMEDOUT) {
      pthread_mutex_unlock(&m->mutex);
      return -1;
    }
  }
  uint64_t hdr;
  read_ring(q, m->head, &hdr, 8);
  if (hdr > bufsize) {
    pthread_mutex_unlock(&m->mutex);
    return -3;
  }
  read_ring(q, m->head + 8, buf, hdr);
  m->head += align_up(hdr + 8);
  m->count -= 1;
  pthread_cond_signal(&m->not_full);
  pthread_mutex_unlock(&m->mutex);
  return static_cast<int64_t>(hdr);
}

uint64_t shmq_count(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  pthread_mutex_lock(&q->meta->mutex);
  uint64_t c = q->meta->count;
  pthread_mutex_unlock(&q->meta->mutex);
  return c;
}

// Producer-side end-of-stream mark; wakes all waiting consumers.
void shmq_finish(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  pthread_mutex_lock(&q->meta->mutex);
  q->meta->finished = 1;
  pthread_cond_broadcast(&q->meta->not_empty);
  pthread_mutex_unlock(&q->meta->mutex);
}

void shmq_reset_finished(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  pthread_mutex_lock(&q->meta->mutex);
  q->meta->finished = 0;
  pthread_mutex_unlock(&q->meta->mutex);
}

// Detach this process's mapping (shm segment dies on last detach).
void shmq_close(void* handle) {
  auto* q = static_cast<Queue*>(handle);
  shmdt(q->meta);
  delete q;
}

}  // extern "C"
