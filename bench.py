"""Benchmark: neighbor-sampling throughput (the reference's headline metric).

Mirrors /root/reference/benchmarks/api/bench_sampler.py: ogbn-products-like
config — 3-hop fanout [15, 10, 5], batch 1024 — reporting sampled edges/sec
in millions. The graph is synthetic at products scale density (avg degree
~25) because datasets aren't downloadable here; the metric definition matches
the reference's (total sampled edges / wall time, bench_sampler.py:48-54).

`vs_baseline`: the reference publishes figure-only numbers
(docs/figures/scale_up.png; SURVEY.md §6). The comparison constant below is
the GLT-CUDA A100 scale read off that figure (~40M sampled edges/s for this
config). Prints ONE JSON line.
"""
import json
import time

import numpy as np

GLT_A100_EDGES_PER_SEC_M = 40.0  # figure-scale estimate, see module docstring

NUM_NODES = 1_000_000
AVG_DEG = 25
FANOUT = [15, 10, 5]
BATCH = 1024
WARMUP = 3
ITERS = 50


def build_graph():
  import graphlearn_tpu as glt
  rng = np.random.default_rng(0)
  # power-law-ish: half the edges uniform, half into a hot head
  e = NUM_NODES * AVG_DEG
  rows = rng.integers(0, NUM_NODES, e)
  cols = np.empty(e, np.int64)
  half = e // 2
  cols[:half] = rng.integers(0, NUM_NODES, half)
  cols[half:] = rng.zipf(1.5, e - half) % NUM_NODES
  topo = glt.data.Topology(np.stack([rows, cols]), num_nodes=NUM_NODES)
  return glt.data.Graph(topo, 'HBM')


def main():
  import jax
  import graphlearn_tpu as glt
  from graphlearn_tpu.sampler import NodeSamplerInput
  glt.utils.enable_compilation_cache()

  graph = build_graph()
  # fused: one XLA program per batch (in-program dependencies are free;
  # per-op host dispatch is not). dedup='auto' picks the direct-address
  # table inducer (no sorts) at this graph size.
  sampler = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True)
  rng = np.random.default_rng(1)

  def one_batch(i):
    seeds = rng.integers(0, NUM_NODES, BATCH)
    return sampler.sample_from_nodes(NodeSamplerInput(seeds),
                                     batch_cap=BATCH)

  for i in range(WARMUP):
    out = one_batch(i)
    jax.block_until_ready(out.edge_mask)  # sync WITHOUT a host fetch:
    # on this runtime the first device->host transfer permanently switches
    # dispatch into a synchronous mode (~30x slower per call, measured);
    # the timed loop below must run before any fetch.

  # No eager ops inside the timed loop: on this runtime an eager op whose
  # input is a still-pending program output serializes the dispatch
  # pipeline (~20ms/batch measured). The fused program already computes
  # per-hop edge counts (num_sampled_edges) on device; collect those
  # handles, block once (the sync bracketing the reference also uses,
  # bench_sampler.py:48-53), and fetch the ints after the clock stops.
  glt.utils.maybe_start_trace()   # GLT_PROFILE_DIR -> jax.profiler trace
  t0 = time.perf_counter()
  counts = []
  for i in range(ITERS):
    out = one_batch(i)
    counts.append(out.num_sampled_edges)
  jax.block_until_ready(counts)
  dt = time.perf_counter() - t0
  glt.utils.stop_trace()
  total_edges = sum(int(c) for hop in counts for c in hop)

  edges_per_sec_m = total_edges / dt / 1e6
  print(json.dumps({
      'metric': 'sampled_edges_per_sec',
      'value': round(edges_per_sec_m, 3),
      'unit': 'M edges/s',
      'vs_baseline': round(edges_per_sec_m / GLT_A100_EDGES_PER_SEC_M, 3),
  }))


if __name__ == '__main__':
  main()
