"""Benchmark: neighbor-sampling throughput (the reference's headline metric).

Mirrors /root/reference/benchmarks/api/bench_sampler.py: ogbn-products-like
config — 3-hop fanout [15, 10, 5], batch 1024 — reporting sampled edges/sec
in millions. The graph is synthetic at products scale density (avg degree
~25) because datasets aren't downloadable here; the metric definition matches
the reference's (total sampled edges / time, bench_sampler.py:48-54).

TIMING IS PROFILER-BASED: on the axon-tunnel runtime `block_until_ready`
returns at dispatch, not completion, so wall clocks either under-measure
(pipelined mode: dispatch only) or over-measure (a single device->host fetch
permanently degrades every later call) — see PERF.md "Timing on the axon
tunnel". The only trustworthy clock is the device trace: this bench runs the
timed batches under `jax.profiler.trace` and reads the sampling program's
device duration out of the trace events. Wall-clock dispatch time is
reported as a secondary `dispatch_ms_per_batch` sanity field.

The headline measures the TPU-native computation-tree sampler
(dedup='tree': positional relabeling, zero random access — PERF.md); the
reference-parity exact-dedup mode ('map') is reported alongside as
`map_edges_per_sec_m`.

`vs_baseline`: the reference publishes figure-only numbers
(docs/figures/scale_up.png; SURVEY.md §6). The comparison constant below is
the GLT-CUDA A100 scale read off that figure (~40M sampled edges/s for this
config). Prints ONE JSON line.
"""
import json
import os
import shutil
import time

import numpy as np

GLT_A100_EDGES_PER_SEC_M = 40.0  # figure-scale estimate, see module docstring

NUM_NODES = 1_000_000
AVG_DEG = 25
FANOUT = [15, 10, 5]
BATCH = 1024
WARMUP = 3
ITERS = 20
TRACE_DIR = '/tmp/glt_bench_trace'

# end-to-end train-step section (products-like: SAGE h=256, 47 classes)
E2E_ITERS = 10
E2E_HIDDEN = 256
E2E_CLASSES = 47
E2E_FEAT_DIM = 100

# the north-star metric (BASELINE.json) is ogbn-products GraphSAGE EPOCH
# TIME: the real train split is 196,615 seeds -> 192 full batches at 1024
# (drop_last, the reference example's posture). epoch_time_s below =
# steps_per_epoch x the device-trace full-pipeline ms/batch.
PRODUCTS_TRAIN_SEEDS = 196_615


def build_graph():
  import graphlearn_tpu as glt
  rng = np.random.default_rng(0)
  # power-law-ish: half the edges uniform, half into a hot head
  e = NUM_NODES * AVG_DEG
  rows = rng.integers(0, NUM_NODES, e)
  cols = np.empty(e, np.int64)
  half = e // 2
  cols[:half] = rng.integers(0, NUM_NODES, half)
  cols[half:] = rng.zipf(1.5, e - half) % NUM_NODES
  topo = glt.data.Topology(np.stack([rows, cols]), num_nodes=NUM_NODES)
  return glt.data.Graph(topo, 'HBM')


def _device_program_ms(trace_dir):
  """Shared helper: graphlearn_tpu.utils.device_program_ms."""
  from graphlearn_tpu.utils import device_program_ms
  return device_program_ms(trace_dir)


def _run_mode(sampler, rng, jax):
  """Dispatch WARMUP+ITERS batches; return (edges_per_batch list,
  dispatch seconds for the ITERS loop)."""
  from graphlearn_tpu.sampler import NodeSamplerInput

  def one_batch():
    seeds = rng.integers(0, NUM_NODES, BATCH)
    return sampler.sample_from_nodes(NodeSamplerInput(seeds),
                                     batch_cap=BATCH)

  for _ in range(WARMUP):
    out = one_batch()
  jax.block_until_ready(out.edge_mask)
  t0 = time.perf_counter()
  outs = [one_batch() for _ in range(ITERS)]
  jax.block_until_ready([o.num_sampled_edges for o in outs])
  dispatch_dt = time.perf_counter() - t0
  edges = [sum(int(c) for c in o.num_sampled_edges) for o in outs]
  return edges, dispatch_dt


def _run_e2e(ds, train_idx, dtype, jax, trace_dir, variant='tree',
             cal_caps=None):
  """One full train-step pipeline (sample + collate + layered SAGE
  fwd/bwd/adam) traced for E2E_ITERS batches; returns total device ms
  per batch summed across the pipeline's programs (the same breakdown
  methodology as PERF.md 'End-to-end training step').

  variant='tree': block sampling + tree_dense layered model (the
  relaxed-semantics fast path). variant='exact': calibrated exact-dedup
  sampling + prefix-layered segment model — reference semantics."""
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import GraphSAGE
  from graphlearn_tpu.models import train as train_lib

  if variant == 'exact':
    loader = glt.loader.NeighborLoader(
        ds, FANOUT, train_idx, batch_size=BATCH, shuffle=True,
        drop_last=True, seed=0, dedup='map', frontier_caps=cal_caps,
        seed_labels_only=True)
    no, eo = train_lib.merge_hop_offsets(BATCH, FANOUT,
                                         frontier_caps=cal_caps)
    # merge_dense: per-hop k-run reshape-mean aggregation (exact,
    # equivalence-tested) — halves the train program vs segment ops
    model = GraphSAGE(hidden_dim=E2E_HIDDEN, out_dim=E2E_CLASSES,
                      num_layers=len(FANOUT), hop_node_offsets=no,
                      hop_edge_offsets=eo, dtype=dtype,
                      merge_dense=True, fanouts=tuple(FANOUT))
  else:
    loader = glt.loader.NeighborLoader(
        ds, FANOUT, train_idx, batch_size=BATCH, shuffle=True,
        drop_last=True, seed=0, dedup='tree', strategy='block',
        seed_labels_only=True)
    no, eo = train_lib.tree_hop_offsets(BATCH, FANOUT)
    # tree_dense: contiguous child blocks -> reshape aggregation (no
    # gathers/segment scatters); exact for un-budgeted tree batches and
    # 2.8x on the fwd/bwd (PERF.md)
    model = GraphSAGE(hidden_dim=E2E_HIDDEN, out_dim=E2E_CLASSES,
                      num_layers=len(FANOUT), hop_node_offsets=no,
                      hop_edge_offsets=eo, dtype=dtype, tree_dense=True,
                      fanouts=tuple(FANOUT))
  it = iter(loader)
  first = train_lib.batch_to_dict(next(it))
  state, tx = train_lib.create_train_state(model, jax.random.PRNGKey(0),
                                           first)
  step, _ = train_lib.make_train_step(model, tx, E2E_CLASSES)
  def run_step():
    nonlocal state
    state, loss, _ = step(state, train_lib.batch_to_dict(next(it)))
    return loss

  state, loss, _ = step(state, first)            # compile
  return _traced_step_ms(jax, run_step, trace_dir, 'jit_train_step')


def _traced_step_ms(jax, run_step, trace_dir, prog_prefix):
  """Shared measurement scaffold for the e2e benches: 2 warmup steps,
  then E2E_ITERS traced steps; returns (full pipeline ms/step,
  ``prog_prefix`` program ms/step). Every pipeline program (sample /
  collate / train_step / bookkeeping) runs exactly once per batch, so
  ms/step = sum of PER-CALL averages — robust to steps leaking across
  the trace window on this rig, where block_until_ready returns at
  dispatch (module docstring); a count-weighted total / E2E_ITERS
  would not be."""
  for _ in range(2):                             # warmup
    loss = run_step()
  jax.block_until_ready(loss)
  shutil.rmtree(trace_dir, ignore_errors=True)
  jax.profiler.start_trace(trace_dir)
  losses = [run_step() for _ in range(E2E_ITERS)]
  jax.block_until_ready(losses)
  jax.profiler.stop_trace()
  progs = _device_program_ms(trace_dir)
  if not progs:
    return None, None
  train_ms = None
  for n, (ms, _) in progs.items():
    if n.startswith(prog_prefix):
      train_ms = ms
  return sum(ms for ms, _ in progs.values()), train_ms


def _traced_call_ms(jax, fn, trace_dir, prog_prefix, iters=20):
  """Per-call device ms of ONE jitted program: warmup, trace ``iters``
  calls, read the ``prog_prefix`` program's average from the device
  trace (None when the lane is missing — non-TPU backends)."""
  jax.block_until_ready(fn())                     # compile + warmup
  shutil.rmtree(trace_dir, ignore_errors=True)
  jax.profiler.start_trace(trace_dir)
  outs = [fn() for _ in range(iters)]
  jax.block_until_ready(outs)
  jax.profiler.stop_trace()
  for n, (ms, _) in _device_program_ms(trace_dir).items():
    if n.startswith(prog_prefix):
      return float(ms)
  return None


def _run_hetero_e2e(jax, trace_dir, conv='sage', n_paper=100_000,
                    n_author=357_041, feat_dim=1024, hb=1024, hops=2,
                    variant='tree'):
  """IGBH-shaped hetero RGNN train step, device-traced (the reference's
  flagship hetero workload: examples/igbh/train_rgnn.py, IGB-tiny node
  counts 100k papers / 357k authors, 1024-dim features, hidden 128).

  variant='tree' (hb=1024, 2 typed hops): tree_dense typed aggregation
  over worst-case tree layouts (a static worst-case 3-hop plan would
  exceed the graph itself — kept for round-over-round continuity).
  variant='calibrated': per-(hop, etype) calibrated caps
  (estimate_hetero_frontier_caps) make the REFERENCE shape feasible —
  batch 5120 x 3 typed hops, the examples/igbh/train_rgnn.py defaults —
  on exact-dedup merge batches with the dense k-run aggregation
  (RGNN merge_dense) and the overflow guard active ('warn'; the caller
  reads loader.check_overflow() at the very end of the bench: one
  device fetch AFTER every trace is captured, per PERF.md fetch rules).

  Returns (full pipeline ms/step, train-program ms/step, loader).
  """
  import graphlearn_tpu as glt
  import jax.numpy as jnp
  from graphlearn_tpu.models import RGNN
  CITES = ('paper', 'cites', 'paper')
  WRITES = ('author', 'writes', 'paper')
  REV = ('paper', 'rev_writes', 'author')
  n_paper, n_author, feat_dim, ncls = (n_paper, n_author, feat_dim,
                                       16)
  hrng = np.random.default_rng(7)
  cites = np.stack([hrng.integers(0, n_paper, n_paper * 12),
                    hrng.integers(0, n_paper, n_paper * 12)])
  writes = np.stack([hrng.integers(0, n_author, n_author * 3),
                     hrng.integers(0, n_paper, n_author * 3)])
  ds = glt.data.Dataset(edge_dir='out')
  ds.init_graph({CITES: cites.astype(np.int32),
                 WRITES: writes.astype(np.int32),
                 REV: writes[::-1].copy().astype(np.int32)},
                graph_mode='HBM',
                num_nodes={CITES: n_paper, WRITES: n_author,
                           REV: n_paper})
  ds.init_node_features({
      'paper': hrng.standard_normal((n_paper, feat_dim),
                                    dtype=np.float32),
      'author': hrng.standard_normal((n_author, feat_dim),
                                     dtype=np.float32)})
  ds.init_node_labels(
      {'paper': hrng.integers(0, ncls, n_paper)})
  hopfan = [15, 10, 5][:hops]
  fan = {CITES: hopfan, WRITES: hopfan, REV: hopfan}
  seeds = ('paper', hrng.integers(0, n_paper, hb * (E2E_ITERS + 5)))
  if variant == 'calibrated':
    caps = glt.sampler.estimate_hetero_frontier_caps(
        ds.graph, fan, {'paper': hb}, num_probes=3, slack=1.5)
    loader = glt.loader.NeighborLoader(
        ds, fan, seeds, batch_size=hb, shuffle=True, drop_last=True,
        seed=0, dedup='merge', frontier_caps=caps,
        overflow_policy='warn')
    recs, no, eo = glt.sampler.hetero_tree_blocks(
        {'paper': hb}, tuple(fan), fan, etype_caps=caps)
    dense_kw = dict(merge_dense=True, tree_records=recs)
  else:
    loader = glt.loader.NeighborLoader(
        ds, fan, seeds, batch_size=hb, shuffle=True, drop_last=True,
        seed=0, dedup='tree')
    recs, no, eo = glt.sampler.hetero_tree_blocks({'paper': hb},
                                                  tuple(fan), fan)
    dense_kw = dict(tree_dense=True, tree_records=recs)
  etypes = tuple(glt.typing.reverse_edge_type(et) for et in fan)
  # dense typed k-run aggregation is the flagship hetero path;
  # heads=4 matches the reference igbh rgat default
  model = RGNN(etypes=etypes, hidden_dim=128, out_dim=ncls, conv=conv,
               heads=(4 if conv == 'gat' else 1),
               num_layers=len(hopfan), out_ntype='paper',
               dtype=jnp.bfloat16, hop_node_offsets=no,
               hop_edge_offsets=eo, **dense_kw)
  import optax

  def bdict(batch):
    return dict(x=batch.x, ei=batch.edge_index, em=batch.edge_mask,
                y=batch.y['paper'],
                num_seed=batch.num_sampled_nodes['paper'][0])

  it = iter(loader)
  first = bdict(next(it))
  params = model.init(jax.random.PRNGKey(0), first['x'], first['ei'],
                      first['em'])
  tx = optax.adam(1e-3)
  opt_state = tx.init(params)

  def loss_fn(params, b):
    logits = model.apply(params, b['x'], b['ei'], b['em'])
    nl = logits.shape[0]
    y = b['y'][:nl]
    sm = jnp.arange(nl) < b['num_seed']
    ce = optax.softmax_cross_entropy(logits, jax.nn.one_hot(y, ncls))
    return jnp.where(sm, ce, 0.0).sum() / jnp.maximum(sm.sum(), 1)

  @jax.jit
  def hetero_train_step(params, opt_state, b):
    loss, g = jax.value_and_grad(loss_fn)(params, b)
    updates, opt_state = tx.update(g, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss

  def run_step():
    nonlocal params, opt_state
    params, opt_state, loss = hetero_train_step(params, opt_state,
                                                bdict(next(it)))
    return loss

  params, opt_state, loss = hetero_train_step(params, opt_state, first)
  tot, tr = _traced_step_ms(jax, run_step, trace_dir,
                            'jit_hetero_train_step')
  return tot, tr, loader


# v5e peak dense matmul throughput (bf16); MFU below is matmul-FLOPs /
# device-time / this peak — the aggregation segment ops / gathers are
# memory ops and carry no model FLOPs under the standard convention
V5E_PEAK_BF16_TFLOPS = 197.0


def _sage_matmul_gflops(layer_rows, feat_dim, hidden, classes):
  """Analytic matmul FLOPs for one layered-SAGE fwd+bwd+adam step.

  Each SAGEConv layer runs TWO dense matmuls (self + aggregated
  neighbors) over its node-prefix row count; backward costs ~2x forward
  (grads w.r.t. inputs + weights). rows are the per-layer prefix widths
  (widest first), dims follow the bench model config.
  """
  dims = [feat_dim] + [hidden] * (len(layer_rows) - 1)
  outs = [hidden] * (len(layer_rows) - 1) + [classes]
  fwd = sum(2 * r * di * do * 2
            for r, di, do in zip(layer_rows, dims, outs))
  return 3 * fwd / 1e9


def _error_record(stage: str, err: str) -> dict:
  """Structured failure record: the driver must always get ONE parseable
  JSON line, never a bare traceback (BENCH_r04 died at backend init with
  rc=1 and no numbers — this makes the failure self-describing)."""
  return {
      'metric': 'sampled_edges_per_sec', 'value': None, 'unit': 'M edges/s',
      'vs_baseline': None, 'error': f'{stage}: {err}'[:400],
      'config': {'num_nodes': NUM_NODES, 'avg_deg': AVG_DEG,
                 'fanout': FANOUT, 'batch': BATCH},
      'last_good_numbers': 'PERF.md (round-4 builder-measured)',
  }


# ------------------------------------------------------------ key registry
# The declared schema of the record main() prints (and _error_record's
# failure shape). `python bench.py --validate BENCH_*.json` checks saved
# records against it — a renamed or misspelled key otherwise silently
# orphans the metric history the BENCH_r*.json trajectory exists to keep
# (tests/test_analysis.py runs this over the checked-in files as a cheap
# tier-1 gate). Add the registry entry IN THE SAME CHANGE as the
# result[...] assignment.
BENCH_KEY_REGISTRY = {
    # headline sampling throughput
    'backend': 'jax backend platform the run executed on',
    'metric': 'headline metric name (sampled_edges_per_sec)',
    'value': 'headline value, M edges/s (tree mode); null on failure',
    'unit': 'headline unit string',
    'vs_baseline': 'headline / GLT-CUDA A100 figure estimate',
    'headline_semantics': 'which dedup semantics the headline measures',
    'timing': "'device-trace' or 'dispatch-wall-fallback'",
    'device_ms_per_batch': 'tree-mode device ms per batch',
    'dispatch_ms_per_batch': 'dispatch wall ms per batch (sanity)',
    'map_edges_per_sec_m': 'exact-dedup (merge) throughput',
    'map_device_ms_per_batch': 'exact-dedup device ms per batch',
    'padded16_edges_per_sec_m': 'padded-window W=16 throughput',
    'padded16_device_ms_per_batch': 'padded-window device ms per batch',
    'block_edges_per_sec_m': 'block-strategy throughput',
    'block_device_ms_per_batch': 'block-strategy device ms per batch',
    'map_calibrated_edges_per_sec_m': 'calibrated exact-dedup throughput',
    'map_calibrated_device_ms_per_batch': 'calibrated exact device ms',
    'map_calibrated_vs_baseline': 'calibrated exact / A100 figure',
    'calibrated_caps': 'per-hop frontier caps the calibrated run used',
    'sampled_edges_per_sec_per_chip_m': 'north-star per-chip (tree)',
    'sampled_edges_per_sec_per_chip_exact_m': 'north-star per-chip (exact)',
    # end-to-end train step + epoch projection
    'train_step_ms_f32': 'e2e sample+collate+train ms, f32',
    'train_step_ms_bf16': 'e2e ms, bf16 tree path',
    'train_step_ms_exact_bf16': 'e2e ms, bf16 calibrated exact path',
    'steps_per_epoch_products': 'ogbn-products full batches at 1024',
    'epoch_time_s': 'north-star epoch seconds (reference semantics)',
    'epoch_time_s_exact': 'alias of epoch_time_s (exact path)',
    'epoch_time_s_tree': 'epoch seconds, relaxed tree path',
    'epoch_time_semantics': 'which path epoch_time_s measures',
    'epoch_time_basis': 'how the epoch figure is derived (honesty label)',
    # MFU / FLOP accounting
    'model_gflops_per_step_tree': 'analytic matmul GFLOPs/step, tree',
    'model_gflops_per_step_exact': 'analytic matmul GFLOPs/step, exact',
    'model_tflops_per_sec_bf16': 'achieved TFLOP/s, tree bf16',
    'model_tflops_per_sec_exact_bf16': 'achieved TFLOP/s, exact bf16',
    'mfu_pct_bf16': 'MFU % of v5e peak, tree bf16 (whole step)',
    'mfu_pct_exact_bf16': 'MFU %, exact bf16 (whole step)',
    'mfu_pct_train_program_bf16': 'MFU %, train program only',
    'mfu_pct_train_program_exact_bf16': 'MFU %, exact train program only',
    # scanned epoch (PR 1)
    'epoch_dispatches': 'measured dispatches for the scanned bench epoch',
    'epoch_dispatches_products_est': 'ceil(products_steps/K)+2 estimate',
    'scan_epoch_steps': 'steps in the measured scanned epoch',
    'scan_epoch_chunk': 'K (chunk size) of the measured scanned epoch',
    'scan_epoch_wall_s': 'scanned epoch wall seconds',
    'scan_epoch_device_trace_s': 'scanned epoch device-trace seconds',
    'epoch_time_s_scanned': 'products-scale scanned epoch projection',
    # program observatory (PR 8, metrics/programs.py): compile/retrace
    # accounting over the scanned-epoch section (reset at its start;
    # cost attribution captured under GLT_PROGRAM_COST)
    'compile_count': 'XLA compiles across the scanned-epoch section',
    'compile_time_s_total': 'summed compile wall s (section scope)',
    'retrace_count': 'compiles beyond the first per site — a retrace '
                     'regression multiplies epoch wall clock',
    'program_flops_total': 'cost_analysis flops summed over compiled '
                           'programs (null without GLT_PROGRAM_COST)',
    'program_peak_hbm_mb': 'max per-program peak-HBM estimate, MB '
                           '(args+out+temps-aliased; null w/o cost)',
    # one-call autotune + run-as-a-program (ISSUE 15, graphlearn_tpu/
    # tune/ + loader/run_epoch.py, docs/tuning.md): the one-call cost
    # of landing on the fast path, and the whole-run dispatch budget
    # vs per-epoch scans on the same stream (bit-identical arms)
    'tune_wall_s': 'tune() wall seconds on the bench fixture (probes + '
                   'observatory-scored candidate A/Bs + artifact)',
    'tune_chosen_config': 'the chosen knob assignment + winner + '
                          'artifact fingerprint (evidence string)',
    'run_epoch_dispatches': 'RunTrainer dispatches for the E-epoch run '
                            '(pin: ceil(E*steps/K) + 2)',
    'run_wall_s': 'RunTrainer steady-state E-epoch run wall seconds',
    'run_vs_per_epoch_ratio': 'run wall / E sequential ScanTrainer '
                              'epoch walls (< 1.0 = the folded run '
                              'wins; arms bit-identical)',
    'run_scan_config': 'E/steps/K/batch shape + both arms\' dispatch '
                       'counts behind the run_scan figures',
    # topology-wide autotune + continuous retune (ISSUE 18, tune/
    # topology.py + tune/retune.py, docs/tuning.md): the one-call cost
    # of tuning a DISTRIBUTED scenario (every candidate a freshly built
    # store), and the drift-to-published-config latency of the shadow
    # retune daemon
    'dist_tune_wall_s': "tune(topology='dist') wall seconds on the "
                        'CPU-replica mesh fixture (feasibility screen '
                        '+ per-scenario compile/steady A/Bs + artifact)',
    'topology_tune_config': "the dist tune's winning topology knob "
                            'assignment + winner + artifact '
                            'fingerprint (evidence string)',
    'retune_trigger_to_publish_s': 'RetuneScheduler latency from drift-'
                                   'trigger fire to published artifact '
                                   '(shadow tune + config= publish)',
    # scanned DISTRIBUTED epoch (PR 4)
    'dist_epoch_dispatches': 'per-step collocated dist epoch dispatches',
    'dist_epoch_wall_s': 'per-step collocated dist epoch wall seconds',
    'dist_scan_epoch_dispatches': 'DistScanTrainer epoch dispatches',
    'dist_scan_epoch_wall_s': 'DistScanTrainer epoch wall seconds',
    'dist_scan_epoch_steps': 'steps in the measured dist scanned epoch',
    'dist_scan_epoch_chunk': 'K of the measured dist scanned epoch',
    'dist_scan_mesh_size': 'mesh size the dist A/B ran on',
    'dist_scan_epoch_dispatch_reduction_x': 'per-step / scanned dispatches',
    # feature-exchange volume (PR 3, analytic)
    'feature_exchange_mb_per_batch': 'miss-only exchange MB/shard/batch',
    'feature_exchange_mb_per_batch_fullwidth': 'full-width posture MB',
    'feature_exchange_reduction_x': 'fullwidth / miss-only MB ratio',
    'feature_exchange_config': 'P/width/F/bucket/split/wire of the figure',
    # RUN_MEAN_IMPL decision pair (VERDICT r5) + the auto-landed verdict
    # (ISSUE 13: models.run_impl_decision applies the >3% margin rule so
    # the next round flips the models.RUN_MEAN_IMPL default — or pins
    # GLT_RUN_MEAN_IMPL — with a one-line, evidence-linked change)
    'run_mean_impl_reshape_ms': 'e2e step ms with RUN_MEAN_IMPL=reshape',
    'run_mean_impl_window_ms': 'e2e step ms with RUN_MEAN_IMPL=window',
    'run_mean_impl_decision': "auto-landed winner ('reshape'/'window'; "
                              'null when either leg failed)',
    'run_mean_impl_decision_config': 'evidence string behind the '
                                     'decision (both ms + margin rule)',
    # RUN_SOFTMAX_IMPL decision pair (ISSUE 14, the pending PR 13
    # copy-tax residual): the dense-GAT run-softmax chain A/B'd on the
    # RGAT e2e step, auto-decided by the same >3% margin rule
    # (override per run with GLT_RUN_SOFTMAX_IMPL)
    'run_softmax_impl_reshape_ms': 'RGAT e2e step ms with '
                                   'RUN_SOFTMAX_IMPL=reshape',
    'run_softmax_impl_window_ms': 'RGAT e2e step ms with '
                                  'RUN_SOFTMAX_IMPL=window',
    'run_softmax_impl_decision': "auto-landed winner ('reshape'/"
                                 "'window'; null when either leg "
                                 'failed)',
    'run_softmax_impl_decision_config': 'evidence string behind the '
                                        'softmax decision',
    # kernel campaign r13 (ops/gather_pallas.py v2 + ops/sample_fused.py,
    # benchmarks/prof_gather2.py): device-trace A/B of the run-segmented
    # multi-row DMA gather and the fused sample+gather hop vs their XLA
    # paths — ratios < 1.0 are the measured-win condition for flipping
    # UnifiedTensor.use_pallas_v2 / NeighborSampler(use_fused_hop=True)
    'gather2_ms': 'gather v2 kernel device ms/call (sorted-unique id '
                  'probe, default block_rows/run_span)',
    'gather2_vs_take_ratio': 'gather2_ms / XLA take ms on the same '
                             'probe (< 1.0 = kernel wins)',
    'gather2_config': 'probe + autotune config behind the gather2 keys',
    'fused_hop_ms': 'fused sample+gather hop kernel device ms/call',
    'fused_hop_vs_xla_ratio': 'fused_hop_ms / XLA uniform_sample hop ms '
                              '(< 1.0 = kernel wins)',
    'fused_hop_config': 'probe config behind the fused_hop keys',
    # kernel campaign r16 (ops/sample_fused.py sample_level_fused +
    # tune/): the fused MULTI-HOP frontier level (sample+gather+dedup
    # in one kernel pass) vs the same level through the XLA merge
    # engine, and the kernel routing the tuner actually chose
    'fused_multihop_ms': 'fused multi-hop frontier kernel device ms '
                         'per fanout level (sample+gather+dedup fused)',
    'fused_multihop_vs_xla_ratio': 'fused_multihop_ms / XLA sample + '
                                   'merge-dedup level ms (< 1.0 = '
                                   'kernel wins)',
    'fused_multihop_config': 'probe config behind the fused_multihop '
                             'keys',
    'kernel_route_config': "tune()'s chosen kernel routing — the "
                           'artifact kernel choices every config= '
                           'acceptor applies (docs/tuning.md)',
    # out-of-core tiered storage (storage/, ROADMAP item 2): a scanned
    # epoch whose feature table is >= 4x the HBM(hot)+RAM(warm) budget,
    # vs the identical all-HBM epoch — the oversubscription gate
    'oversub_epoch_wall_s': 'tiered (HBM+RAM+disk) scanned epoch wall s',
    'oversub_hbm_epoch_wall_s': 'all-HBM reference epoch wall s',
    'oversub_ratio': 'tiered / all-HBM epoch wall (gate: ~1.5x)',
    'prefetch_hit_rate': 'cold rows staged ahead / all cold-row reads',
    'staged_mb_per_chunk': 'MB staged host->ring per scanned chunk',
    'oversub_bit_identical': 'tiered epoch losses == all-HBM losses',
    'oversub_config': 'graph/tier/oversubscription shape of the figures',
    # device oversubscription THROUGH the shard exchange (storage/
    # dist_scan.py, ISSUE 14): a scanned DISTRIBUTED epoch whose shards
    # hold only hot prefixes + staged exchange slabs, vs the identical
    # all-HBM DistScanTrainer epoch
    'dist_oversub_epoch_wall_s': 'tiered dist scanned epoch wall s '
                                 '(hot prefix + staged slabs)',
    'dist_oversub_hbm_epoch_wall_s': 'all-HBM DistScanTrainer '
                                     'reference epoch wall s',
    'dist_oversub_ratio': 'tiered dist / all-HBM epoch wall '
                          '(gate: ~1.5x)',
    'dist_oversub_bit_identical': 'tiered dist epoch losses == all-HBM '
                                  'losses (exact miss-exchange program)',
    'dist_oversub_config': 'graph/mesh/prefix/oversubscription shape '
                           'of the dist_oversub figures',
    # demand-paged PER-STEP oversubscribed gather (storage/dist.py,
    # ISSUE 16): per-step TieredDistFeature.get over hot prefix +
    # per-step demand-paged slabs vs the identical all-HBM per-step
    # loop — bit-identical rows; the ratio prices the per-step host
    # round trip the scanned path amortizes at chunk boundaries
    'oversub_per_step_wall_s': 'demand-paged per-step get loop wall s',
    'oversub_per_step_hbm_wall_s': 'all-HBM per-step get loop wall s',
    'oversub_per_step_ratio': 'demand-paged / all-HBM per-step wall '
                              '(the per-step demand-paging tax)',
    'oversub_per_step_bit_identical': 'demand-paged rows == all-HBM '
                                      'rows over every step',
    'oversub_per_step_config': 'store/mesh/prefix/step shape of the '
                               'oversub_per_step figures',
    # zero-downtime sharded store rotation (serving/rotation.py): next
    # version materializes onto per-shard disk tiers while the current
    # serves, then swaps atomically under live threaded traffic
    'rotation_swap_ms_p99': 'serving.rotation_swap_ms p99 over the '
                            'bench rotations (the swap critical '
                            'section, not the build)',
    'rotation_failed_requests': 'requests failed during live rotation '
                                '(gate: 0 — zero-downtime contract)',
    'rotation_config': 'table/shards/traffic shape of the rotation '
                       'figures',
    # chunk-granular recovery (recovery/, docs/recovery.md): a scanned
    # epoch checkpointed at the default cadence vs the plain epoch,
    # plus a kill-at-chunk-N + resume measuring the lost-work bound
    'checkpoint_save_ms_p99': 'checkpoint.save_ms p99 over the '
                              'checkpointed epochs (ms)',
    'checkpoint_bytes': 'avg bytes per chunk-boundary snapshot',
    'resume_replay_chunks': 'chunks of lost work replayed after the '
                            'kill (kill boundary - checkpoint boundary)',
    'recovery_overhead_pct': 'checkpointed vs plain scanned epoch wall '
                             'overhead, % (default cadence; gate <5%)',
    'recovery_config': 'graph/cadence/kill shape of the recovery figures',
    # chunk-staged remote scan (distributed/remote_scan.py,
    # docs/remote_scan.md): a server-client epoch over K-batch blocks
    # vs the collocated DistScanTrainer epoch at the same scale — the
    # decoupled-topology-at-scanned-speed gate (CPU replica here; the
    # on-chip figures land with the TPU relay)
    'remote_scan_epoch_wall_s': 'chunk-staged remote epoch wall s',
    'remote_scan_epoch_dispatches': 'client dispatches for that epoch '
                                    '(pin: ceil(steps/K) + 2)',
    'remote_block_stage_ms_p99': 'remote.block_stage_ms p99 — block '
                                 'staging latency ahead of the scan',
    'remote_vs_collocated_ratio': 'remote / collocated scanned epoch '
                                  'wall (gate: ~1.3x)',
    'remote_scan_config': 'graph/block/server shape of the figures',
    # hetero at scanned speed (ISSUE 19, sampler/capacity.py,
    # docs/capacity_plans.md): typed CapacityPlans thread per-ntype
    # closed shapes through the marquee fast paths — the chunk-staged
    # remote epoch on TYPED block streams vs the per-batch remote
    # hetero path (bit-identical arms), and the per-ntype tiered
    # exchange vs the all-HBM hetero DistScanTrainer epoch
    'hetero_scan_epoch_wall_s': 'hetero chunk-staged remote epoch '
                                'wall s (typed block streams)',
    'hetero_scan_per_batch_wall_s': 'per-batch remote hetero epoch '
                                    'wall s (the path hetero was '
                                    'stuck on pre-CapacityPlan)',
    'hetero_scan_vs_per_batch_ratio': 'hetero scanned / per-batch '
                                      'epoch wall (gate: <= 1.0 on '
                                      'the CPU replica)',
    'hetero_scan_epoch_dispatches': 'client dispatches for the hetero '
                                    'scanned epoch (pin: '
                                    'ceil(steps/K) + 2)',
    'hetero_scan_bit_identical': 'hetero scanned losses == per-batch '
                                 'remote hetero losses',
    'hetero_scan_config': 'graph/etype/block shape of the '
                          'hetero_scan figures',
    'hetero_tiered_epoch_wall_s': 'hetero tiered dist epoch wall s '
                                  '(per-ntype hot prefixes + staged '
                                  'slabs)',
    'hetero_tiered_hbm_epoch_wall_s': 'all-HBM hetero DistScanTrainer '
                                      'reference epoch wall s',
    'hetero_tiered_ratio': 'hetero tiered / all-HBM epoch wall '
                           '(gate: ~1.5x, the dist_oversub contract '
                           'on typed stores)',
    'hetero_tiered_bit_identical': 'hetero tiered epoch losses == '
                                   'all-HBM hetero losses',
    'hetero_tiered_config': 'graph/mesh/prefix shape of the '
                            'hetero_tiered figures',
    # multi-tenant service fabric (distributed/tenancy.py,
    # docs/multi_tenancy.md): weighted-fair shares and interactive
    # latency under a contended sampling cluster, plus the visible-
    # backpressure throttle plumbing against a tight in-flight quota
    'tenant_fairness_spread': 'max per-tenant |throughput share - '
                              'weight share| / weight share under '
                              'contention (acceptance: within 0.25)',
    'tenant_p99_degradation_ms': 'interactive probe p99 under '
                                 'contention minus its solo p99 (ms)',
    'tenant_throttle_rate': 'throttle rejections per produce-ahead op '
                            'against a one-frame in-flight quota',
    'tenant_config': 'tenant/weight/load shape of the fairness figures',
    # serving tier (PR 7): offline materialization + online endpoint
    'embed_epoch_wall_s': 'full-graph layer-wise materialization wall s',
    'embed_epoch_dispatches': 'materialization dispatches, all layers',
    'serving_qps_per_chip': 'ServingEngine sustained lookups/s per chip',
    'serving_p50_ms': 'serving.total_ms p50 under the bench load',
    'serving_p99_ms': 'serving.total_ms p99 under the bench load',
    'serving_config': 'graph/bucket/load shape of the serving figures',
    # hetero train steps
    'hetero_rgnn_step_ms_bf16': 'RGNN (sage) e2e step ms',
    'hetero_rgnn_train_program_ms': 'RGNN train program device ms',
    'hetero_rgat_step_ms_bf16': 'RGAT e2e step ms',
    'hetero_rgat_train_program_ms': 'RGAT train program device ms',
    'hetero_rgnn_ref_step_ms_bf16': 'RGNN at reference shape (5120x3)',
    'hetero_rgnn_ref_train_program_ms': 'RGNN ref train program ms',
    'hetero_rgat_ref_step_ms_bf16': 'RGAT at reference shape',
    'hetero_rgat_ref_train_program_ms': 'RGAT ref train program ms',
    'hetero_ref_config': 'reference-shape run configuration',
    'hetero_ref_overflow': 'any ref-shape loader truncated (bool/null)',
    # failure shapes (_error_record + per-section catches)
    'error': 'whole-run failure: stage + message',
    'config': 'bench graph config echoed on failure records',
    'last_good_numbers': 'pointer to the last trusted figures',
}
# per-section failure keys: '<section>_error' for these section stems
# (plus '<registered key>_error' for per-key isolation, e.g.
# run_mean_impl_reshape_ms_error)
BENCH_ERROR_SECTIONS = (
    'train_step', 'scan_epoch', 'dist_scan_epoch', 'run_mean_impl',
    'run_softmax_impl', 'hetero_step', 'hetero_ref', 'feature_exchange',
    'serving', 'oversub', 'dist_oversub', 'rotation', 'recovery',
    'remote_scan', 'gather2', 'fused_hop', 'fused_multihop',
    'oversub_per_step', 'tune', 'topology_tune', 'run_scan', 'tenancy',
    'hetero_scan', 'hetero_tiered',
)

# The LOWER-IS-BETTER subset of BENCH_KEY_REGISTRY — the keys
# `bench.py --gate` regression-checks round over round (ms / seconds /
# dispatch counts / wire MB; throughput keys are higher-is-better and
# tracked in the trajectory table only). Declare a new latency/cost key
# here IN THE SAME CHANGE that registers it, or the gate never sees it.
BENCH_LOWER_IS_BETTER = frozenset({
    'device_ms_per_batch', 'map_device_ms_per_batch',
    'padded16_device_ms_per_batch', 'block_device_ms_per_batch',
    'map_calibrated_device_ms_per_batch', 'dispatch_ms_per_batch',
    'train_step_ms_f32', 'train_step_ms_bf16', 'train_step_ms_exact_bf16',
    'epoch_time_s', 'epoch_time_s_exact', 'epoch_time_s_tree',
    'epoch_time_s_scanned',
    'epoch_dispatches', 'scan_epoch_wall_s', 'scan_epoch_device_trace_s',
    # the run-as-a-program gate pair: the whole-run dispatch budget and
    # the run/per-epoch wall ratio (a ratio drifting up means the
    # folded run lost its dispatch-tax win round over round)
    'run_epoch_dispatches', 'run_vs_per_epoch_ratio',
    # retraces and compile seconds regress silently; the gate catches a
    # round-over-round jump (a new chunk length, a dtype drift)
    'retrace_count', 'compile_time_s_total',
    'dist_epoch_dispatches', 'dist_epoch_wall_s',
    'dist_scan_epoch_dispatches', 'dist_scan_epoch_wall_s',
    # the topology-tune cost pair: the one-call dist tune and the
    # drift-to-published-config latency (a retune daemon that gets
    # slower to publish is a serving-freshness regression)
    'dist_tune_wall_s', 'retune_trigger_to_publish_s',
    'feature_exchange_mb_per_batch',
    'run_mean_impl_reshape_ms', 'run_mean_impl_window_ms',
    'run_softmax_impl_reshape_ms', 'run_softmax_impl_window_ms',
    # the kernel-campaign ratio pair: a ratio drifting UP means the
    # kernels lost ground vs XLA round over round (compiler regressions
    # included) — gate it like any latency key
    'gather2_vs_take_ratio', 'fused_hop_vs_xla_ratio',
    'fused_multihop_vs_xla_ratio',
    'embed_epoch_wall_s', 'embed_epoch_dispatches',
    'oversub_epoch_wall_s', 'staged_mb_per_chunk',
    # the dist-oversubscription gate ratio (~1.5x) and the rotation
    # pair: the swap critical section's p99 and the zero-downtime
    # contract itself (any failed request is a regression from 0)
    'dist_oversub_ratio', 'oversub_per_step_ratio',
    'rotation_swap_ms_p99',
    'rotation_failed_requests',
    # a checkpoint that gets expensive (bytes) or taxing (overhead)
    # regresses silently otherwise — the issue's gate pair
    'checkpoint_bytes', 'recovery_overhead_pct',
    # the chunk-staged remote gate pair: the remote/collocated wall
    # ratio and the block staging latency ahead of the scan
    'remote_vs_collocated_ratio', 'remote_block_stage_ms_p99',
    # the typed-fast-path gate pair (ISSUE 19): hetero scanned epochs
    # must stay at-or-under the per-batch hetero wall, and the
    # per-ntype tiered exchange must hold the dist_oversub contract
    'hetero_scan_vs_per_batch_ratio', 'hetero_tiered_ratio',
    # the multi-tenant gate pair: weight-share fidelity of the fair
    # scheduler and the interactive tenant's latency cost under a
    # saturating training load (both drift silently otherwise)
    'tenant_fairness_spread', 'tenant_p99_degradation_ms',
    'serving_p50_ms', 'serving_p99_ms',
    'hetero_rgnn_step_ms_bf16', 'hetero_rgnn_train_program_ms',
    'hetero_rgat_step_ms_bf16', 'hetero_rgat_train_program_ms',
    'hetero_rgnn_ref_step_ms_bf16', 'hetero_rgnn_ref_train_program_ms',
    'hetero_rgat_ref_step_ms_bf16', 'hetero_rgat_ref_train_program_ms',
})
assert BENCH_LOWER_IS_BETTER <= set(BENCH_KEY_REGISTRY), \
    'gate keys must be registered bench keys'

#: >20% worse on a declared lower-is-better key fails the gate.
GATE_REGRESSION_THRESHOLD = 0.20


def _default_bench_paths():
  import glob as _glob
  import os
  here = os.path.dirname(os.path.abspath(__file__))
  return sorted(_glob.glob(os.path.join(here, 'BENCH_*.json')))


def _load_bench_record(path):
  """(record, error) from a BENCH_*.json file (raw bench output, or
  the driver wrapper whose 'parsed' field holds it) — the ONE unwrap
  of the driver-wrapper contract, shared by --validate and --gate so
  the two can't diverge on the same files. ``record`` is None when the
  file is unreadable (``error`` says why) or when the wrapper carries
  no parseable record (``error`` None — rc/tail tell that story)."""
  try:
    with open(path) as fh:
      data = json.load(fh)
  except (OSError, ValueError) as e:
    return None, f'unreadable: {e}'
  record = data.get('parsed', data) if isinstance(data, dict) else data
  return (record if isinstance(record, dict) else None), None


def _gate_value(record, key):
  """The gateable numeric for ``key``, or None (missing / null /
  non-numeric / bool — a failed section must read as 'no data', never
  as a 0-regression or an infinite one)."""
  v = record.get(key)
  if isinstance(v, bool) or not isinstance(v, (int, float)):
    return None
  return float(v)


def gate_bench_files(paths=(), threshold: float = GATE_REGRESSION_THRESHOLD
                     ) -> int:
  """--gate entry: regression-check the NEWEST BENCH_*.json against the
  previous round over their shared lower-is-better keys, and print the
  per-key trajectory across every round. Returns a process exit code
  (1 on any >threshold regression).

  Rounds whose record is missing/unparseable (a driver wrapper with no
  'parsed' — e.g. a relay-down round) are skipped, so the gate always
  compares the two most recent rounds WITH numbers; keys absent or
  null on either side are skipped per key. No jax, no device."""
  import os
  paths = paths or _default_bench_paths()
  rounds = []
  for path in paths:
    name = os.path.basename(path)
    record, _ = _load_bench_record(path)
    if record is None:
      print(f'bench --gate: {name}: no parsed record (skipped)')
      continue
    if not any(_gate_value(record, k) is not None
               for k in BENCH_LOWER_IS_BETTER):
      # a parseable round with ZERO gateable numbers (relay-down
      # fail-fast record) must not become the "newest round" — it
      # would make every comparison vacuous AND shield the next real
      # round from being gated against the last real numbers
      print(f'bench --gate: {name}: no gateable keys (skipped)')
      continue
    rounds.append((name, record))
  if not rounds:
    print('bench --gate: no parseable BENCH records — nothing to gate')
    return 0

  # trajectory table: every lower-is-better key any round reported
  keys = sorted(k for k in BENCH_LOWER_IS_BETTER
                if any(_gate_value(r, k) is not None for _, r in rounds))
  if keys:
    width = max(len(k) for k in keys)
    header = ' '.join(f'{name:>14}' for name, _ in rounds)
    print(f'{"key (lower is better)":<{width}} {header}')
    for k in keys:
      cells = []
      for _, r in rounds:
        v = _gate_value(r, k)
        cells.append(f'{v:>14.3f}' if v is not None else f'{"—":>14}')
      print(f'{k:<{width}} {" ".join(cells)}')

  if len(rounds) < 2:
    print('bench --gate: fewer than two rounds with numbers — pass')
    return 0
  (prev_name, prev), (new_name, new) = rounds[-2], rounds[-1]
  regressions = []
  for k in keys:
    old_v, new_v = _gate_value(prev, k), _gate_value(new, k)
    if old_v is None or new_v is None or old_v <= 0:
      continue
    ratio = new_v / old_v
    if ratio > 1.0 + threshold:
      regressions.append((k, old_v, new_v, ratio))
  for k, old_v, new_v, ratio in regressions:
    print(f'bench --gate: REGRESSION {k}: {old_v:.3f} ({prev_name}) -> '
          f'{new_v:.3f} ({new_name}) = {ratio:.2f}x '
          f'(threshold {1 + threshold:.2f}x)')
  print(f'bench --gate: {len(regressions)} regression(s) comparing '
        f'{new_name} against {prev_name} over {len(keys)} tracked '
        'key(s)')
  return 1 if regressions else 0


def _known_bench_key(key: str) -> bool:
  if key in BENCH_KEY_REGISTRY:
    return True
  if key.endswith('_error'):
    stem = key[:-len('_error')]
    return stem in BENCH_ERROR_SECTIONS or stem in BENCH_KEY_REGISTRY
  return False


def validate_bench_record(record) -> list:
  """Problems (strings) with one parsed bench record; [] when clean."""
  if not isinstance(record, dict):
    return [f'record is {type(record).__name__}, expected a JSON object']
  problems = []
  for key in ('metric', 'value', 'unit', 'vs_baseline'):
    if key not in record:
      problems.append(f"missing required key '{key}' (the driver "
                      'contract: every record carries the headline '
                      'fields, null-valued on failure)')
  for key in sorted(record):
    if not _known_bench_key(key):
      problems.append(f"unknown key '{key}' — not in BENCH_KEY_REGISTRY; "
                      'register it (bench.py) in the same change that '
                      'emits it, or fix the spelling')
  return problems


def validate_bench_files(paths) -> int:
  """--validate entry: check saved BENCH_*.json records (raw bench
  output, or the driver wrapper whose 'parsed' field holds it) against
  BENCH_KEY_REGISTRY. Prints findings; returns a process exit code."""
  paths = paths or _default_bench_paths()
  total = 0
  for path in paths:
    record, err = _load_bench_record(path)
    if err:
      print(f'{path}: {err}')
      total += 1
      continue
    if record is None:
      # a driver wrapper whose run produced no parseable line: nothing
      # to schema-check (rc/tail carry the failure story)
      print(f'{path}: no parsed record (skipped)')
      continue
    problems = validate_bench_record(record)
    for p in problems:
      print(f'{path}: {p}')
    total += len(problems)
  print(f'bench --validate: {total} problem(s) in {len(paths)} file(s)')
  return 1 if total else 0


def _relay_ports() -> tuple:
  """Probed relay ports; GLT_BENCH_RELAY_PORTS overrides (tests force
  the down path with it). Malformed tokens are ignored — a bad override
  must degrade to the defaults, never crash the failure path itself."""
  import os
  ports = tuple(
      int(tok) for tok in
      os.environ.get('GLT_BENCH_RELAY_PORTS', '8083,8082').split(',')
      if tok.strip().isdigit())
  return ports or (8083, 8082)


def _axon_relay_up(timeout: float = 2.0) -> bool:
  """Bare TCP probe of the axon loopback relay. When the TPU host driver
  dies, EVERY jax init that dials the axon plugin hangs forever (PERF.md
  'TPU-host failure mode') — so probe the socket first, never jax."""
  import socket
  for port in _relay_ports():
    try:
      with socket.create_connection(('127.0.0.1', port), timeout=timeout):
        return True
    except OSError:
      continue
  return False


def _watchdog(seconds: float, stage: str, detail: str):
  """Hard deadline: if the returned Event isn't set within ``seconds``,
  emit the structured error record and exit 0. Used twice — a tight
  init deadline (the TCP probe can pass while the tunnel is still
  wedged) and a whole-run deadline (a wedge can also manifest at the
  first transfer/compile/fetch, long after init succeeded)."""
  import os
  import threading
  done = threading.Event()

  def fire():
    if not done.wait(seconds):
      print(json.dumps(_error_record(stage, detail)), flush=True)
      os._exit(0)

  threading.Thread(target=fire, daemon=True).start()
  return done


def main():
  import jax
  import graphlearn_tpu as glt
  glt.utils.enable_compilation_cache()

  import os
  init_s = float(os.environ.get('GLT_BENCH_INIT_TIMEOUT', '180'))
  total_s = float(os.environ.get('GLT_BENCH_TOTAL_TIMEOUT', '3600'))
  init_done = _watchdog(
      init_s, 'backend-init-timeout',
      f'jax backend init did not return within {init_s:.0f}s — axon '
      'tunnel wedged (host-side TPU driver down?); recovery is '
      "host-side, see PERF.md 'TPU-host failure mode'")
  # whole-run deadline, never disarmed before the result prints: a
  # wedge at the first device put / compile / trace fetch must also
  # end as ONE parseable record, not a hung process
  _watchdog(
      total_s, 'run-timeout',
      f'bench did not complete within {total_s:.0f}s — device work '
      'wedged after successful backend init (axon tunnel / host driver '
      'failure mid-run)')
  backend = jax.devices()[0].platform
  init_done.set()

  graph = build_graph()
  s_tree = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True,
                                       dedup='tree')
  s_map = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True,
                                      dedup='map')
  # accelerated mode: dense pre-shuffled [N, 16] adjacency (rows with
  # deg > 16 sample a uniformly random 16-subset — an approximation the
  # exact modes don't make, so it's reported alongside, not as headline;
  # W=16 covers the max fanout 15 and is the fastest window, PERF.md)
  s_pad = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True,
                                      dedup='tree', padded_window=16)
  # block mode: cluster sampling over aligned 16-wide CSR blocks — raw
  # CSR, exact uniform marginals, row-gather speed (PERF.md)
  s_blk = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True,
                                      dedup='tree', strategy='block')
  # calibrated exact dedup: identical semantics to 'map' while every
  # batch stays under the calibrated per-hop frontier caps (numpy probe
  # simulation, slack 1.5x); buffers shrink from the worst-case static
  # plan to ~actual unique counts (sampler/calibrate.py)
  cal_caps = glt.sampler.estimate_frontier_caps(
      graph, FANOUT, BATCH, num_probes=5, slack=1.5)
  s_cal = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True,
                                      dedup='map', frontier_caps=cal_caps)
  rng = np.random.default_rng(1)

  # compile all programs outside the trace
  _run_mode(s_tree, rng, jax)
  _run_mode(s_map, rng, jax)
  _run_mode(s_pad, rng, jax)
  _run_mode(s_blk, rng, jax)
  _run_mode(s_cal, rng, jax)

  shutil.rmtree(TRACE_DIR, ignore_errors=True)
  jax.profiler.start_trace(TRACE_DIR)
  tree_edges, tree_dispatch = _run_mode(s_tree, rng, jax)
  map_edges, _ = _run_mode(s_map, rng, jax)
  pad_edges, _ = _run_mode(s_pad, rng, jax)
  blk_edges, _ = _run_mode(s_blk, rng, jax)
  cal_edges, _ = _run_mode(s_cal, rng, jax)
  jax.profiler.stop_trace()

  progs = _device_program_ms(TRACE_DIR)
  # the fused programs carry per-mode names (sample_tree / sample_map,
  # neighbor_sampler._fused_homo_fn) so trace events key unambiguously
  def mode_ms(mode):
    for n, (ms, cnt) in progs.items():
      # exact program match: 'sample_tree(' must not match
      # 'sample_tree_padded(...)'
      if f'sample_{mode}(' in n:
        return ms
    return None

  result = {'backend': backend}
  # dedup='map' resolves to the merge-sort exact engine (the program is
  # named sample_merge); the semantics are unchanged exact dedup
  tree_ms, map_ms = mode_ms('tree'), mode_ms('merge')
  pad_ms = mode_ms('tree_padded')
  blk_ms = mode_ms('tree_block')
  if tree_ms is None or map_ms is None:
    # trace unavailable (non-TPU backend): fall back to dispatch wall
    tree_ms = map_ms = pad_ms = blk_ms = tree_dispatch / ITERS * 1000
    result['timing'] = 'dispatch-wall-fallback'
  tree_rate = np.mean(tree_edges) / tree_ms / 1e3   # edges/ms -> M/s
  map_rate = np.mean(map_edges) / map_ms / 1e3
  result.update({
      'metric': 'sampled_edges_per_sec',
      'value': round(float(tree_rate), 3),
      'unit': 'M edges/s',
      'vs_baseline': round(float(tree_rate) / GLT_A100_EDGES_PER_SEC_M, 3),
      # headline = tree mode (accuracy-certified >= exact by the mode
      # matrix, PERF.md); the REFERENCE-SEMANTICS parity figure is
      # map_calibrated_* below (exact dedup, >= 1x baseline)
      'headline_semantics': 'computation-tree (certified >= exact)',
      'device_ms_per_batch': round(float(tree_ms), 3),
      'map_edges_per_sec_m': round(float(map_rate), 3),
      'map_device_ms_per_batch': round(float(map_ms), 3),
      'dispatch_ms_per_batch': round(tree_dispatch / ITERS * 1000, 3),
      'timing': result.get('timing', 'device-trace'),
  })
  if pad_ms:
    pad_rate = np.mean(pad_edges) / pad_ms / 1e3
    result['padded16_edges_per_sec_m'] = round(float(pad_rate), 3)
    result['padded16_device_ms_per_batch'] = round(float(pad_ms), 3)
  else:
    # measurement failure must not read as a 0-regression
    result['padded16_edges_per_sec_m'] = None
  if blk_ms:
    blk_rate = np.mean(blk_edges) / blk_ms / 1e3
    result['block_edges_per_sec_m'] = round(float(blk_rate), 3)
    result['block_device_ms_per_batch'] = round(float(blk_ms), 3)
  else:
    result['block_edges_per_sec_m'] = None
  cal_ms = mode_ms('merge_capped')
  if cal_ms:
    cal_rate = np.mean(cal_edges) / cal_ms / 1e3
    result['map_calibrated_edges_per_sec_m'] = round(float(cal_rate), 3)
    result['map_calibrated_device_ms_per_batch'] = round(float(cal_ms), 3)
    result['map_calibrated_vs_baseline'] = round(
        float(cal_rate) / GLT_A100_EDGES_PER_SEC_M, 3)
    result['calibrated_caps'] = cal_caps
  else:
    result['map_calibrated_edges_per_sec_m'] = None
    result['map_calibrated_vs_baseline'] = None

  # north-star per-chip throughput (single-chip rig: per-chip == absolute)
  result['sampled_edges_per_sec_per_chip_m'] = result['value']
  if result.get('map_calibrated_edges_per_sec_m') is not None:
    result['sampled_edges_per_sec_per_chip_exact_m'] = \
        result['map_calibrated_edges_per_sec_m']

  # ---- end-to-end train step (sample + collate + layered SAGE) ----
  try:
    import jax.numpy as jnp
    frng = np.random.default_rng(2)
    feat = frng.standard_normal((NUM_NODES, E2E_FEAT_DIM),
                                dtype=np.float32)
    labels = frng.integers(0, E2E_CLASSES, NUM_NODES)
    ds = glt.data.Dataset(graph=graph)
    ds.init_node_features(feat)
    ds.init_node_labels(labels)
    n_seeds = BATCH * (E2E_ITERS + 4)
    train_idx = frng.integers(0, NUM_NODES, n_seeds)
    e2e_f32, _ = _run_e2e(ds, train_idx, None, jax,
                          '/tmp/glt_bench_e2e_f32')
    e2e_bf16, tr_bf16 = _run_e2e(ds, train_idx, jnp.bfloat16, jax,
                                 '/tmp/glt_bench_e2e_bf16')
    result['train_step_ms_f32'] = (round(float(e2e_f32), 3)
                                   if e2e_f32 else None)
    result['train_step_ms_bf16'] = (round(float(e2e_bf16), 3)
                                    if e2e_bf16 else None)
    # reference-semantics e2e: calibrated exact dedup + prefix-layered
    # segment model (smaller buffers beat tree_dense at this scale)
    e2e_exact, tr_exact = _run_e2e(ds, train_idx, jnp.bfloat16, jax,
                                   '/tmp/glt_bench_e2e_exact',
                                   variant='exact', cal_caps=cal_caps)
    result['train_step_ms_exact_bf16'] = (round(float(e2e_exact), 3)
                                          if e2e_exact else None)

    # ---- north-star keys (BASELINE.json: epoch time +
    # sampled-edges/sec/chip). Single-chip rig: per-chip == absolute.
    steps_per_epoch = PRODUCTS_TRAIN_SEEDS // BATCH
    result['steps_per_epoch_products'] = steps_per_epoch
    if e2e_exact:
      # primary epoch_time_s is the REFERENCE-SEMANTICS path (calibrated
      # exact dedup) — the like-for-like number against the reference's
      # example config; the tree figure is the relaxed fast path
      result['epoch_time_s'] = round(steps_per_epoch * e2e_exact / 1e3, 3)
      result['epoch_time_s_exact'] = result['epoch_time_s']
      result['epoch_time_semantics'] = 'calibrated-exact (reference)'
    if e2e_bf16:
      result['epoch_time_s_tree'] = round(
          steps_per_epoch * e2e_bf16 / 1e3, 3)
    # honesty label: ms/batch is device-trace truth on THIS bench's
    # synthetic (1M nodes, avg deg 25, zipf mix), scaled by the real
    # products step count — measured-at-2.45M epoch walls come from the
    # example / accuracy-matrix runs (PERF.md)
    result['epoch_time_basis'] = (
        f'device-trace ms/batch on bench graph (N={NUM_NODES}, '
        f'avg_deg={AVG_DEG}) x {steps_per_epoch} products steps')

    # ---- MFU / FLOP accounting (driver's perf lens; PERF.md roofline)
    from graphlearn_tpu.models import train as train_lib
    no_t, _ = train_lib.tree_hop_offsets(BATCH, FANOUT)
    no_e, _ = train_lib.merge_hop_offsets(BATCH, FANOUT,
                                          frontier_caps=cal_caps)
    # EXECUTED matmul rows (round 4, out_rows): layer l produces only
    # the next layer's prefix — [o_{L-1}, o_{L-2}, o_{L-2}] for 3
    # layers (the last layer keeps its full input width). The numerator
    # is useful work actually performed; the pre-round-4 accounting
    # counted the full input prefixes, ~5x more (those rows existed
    # then, but were wasted — see PERF.md 'MFU and the roofline').
    g_tree = _sage_matmul_gflops([no_t[-2], no_t[-3], no_t[-3]],
                                 E2E_FEAT_DIM, E2E_HIDDEN, E2E_CLASSES)
    g_exact = _sage_matmul_gflops([no_e[-2], no_e[-3], no_e[-3]],
                                  E2E_FEAT_DIM, E2E_HIDDEN, E2E_CLASSES)
    result['model_gflops_per_step_tree'] = round(g_tree, 1)
    result['model_gflops_per_step_exact'] = round(g_exact, 1)
    if e2e_bf16:
      tf = g_tree / e2e_bf16  # GFLOP / ms == TFLOP/s
      result['model_tflops_per_sec_bf16'] = round(tf, 2)
      result['mfu_pct_bf16'] = round(100 * tf / V5E_PEAK_BF16_TFLOPS, 2)
      if tr_bf16:
        result['mfu_pct_train_program_bf16'] = round(
            100 * g_tree / tr_bf16 / V5E_PEAK_BF16_TFLOPS, 2)
    if e2e_exact:
      tf = g_exact / e2e_exact
      result['model_tflops_per_sec_exact_bf16'] = round(tf, 2)
      result['mfu_pct_exact_bf16'] = round(
          100 * tf / V5E_PEAK_BF16_TFLOPS, 2)
      if tr_exact:
        result['mfu_pct_train_program_exact_bf16'] = round(
            100 * g_exact / tr_exact / V5E_PEAK_BF16_TFLOPS, 2)
  except Exception as e:                        # never break the headline
    result['train_step_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- scanned epoch: epoch-as-a-program (loader/scan_epoch.py) -----
  # The dispatch tax is the wall-clock story on this rig (PERF.md), so
  # report the ScanTrainer epoch's WALL time, DEVICE-TRACE time and
  # dispatch count side by side with epoch_time_s: the subsystem's claim
  # is wall -> device-trace at ~ceil(steps/K) dispatches. Graceful on
  # CPU: the trace has no TPU lanes there, so the device keys stay null.
  try:
    from graphlearn_tpu.models import GraphSAGE
    from graphlearn_tpu.models import train as train_lib
    from graphlearn_tpu.utils import count_dispatches
    # overflow_policy='off': the guard's epoch-end flag fetch is a
    # device->host sync, and the FIRST fetch permanently degrades later
    # dispatches on the axon runtime (PERF.md fetch rules)
    scan_loader = glt.loader.NeighborLoader(
        ds, FANOUT, train_idx, batch_size=BATCH, shuffle=True,
        drop_last=True, seed=0, dedup='map', frontier_caps=cal_caps,
        seed_labels_only=True, overflow_policy='off')
    no_s, eo_s = train_lib.merge_hop_offsets(BATCH, FANOUT,
                                             frontier_caps=cal_caps)
    scan_model = GraphSAGE(hidden_dim=E2E_HIDDEN, out_dim=E2E_CLASSES,
                           num_layers=len(FANOUT), hop_node_offsets=no_s,
                           hop_edge_offsets=eo_s, dtype=jnp.bfloat16,
                           merge_dense=True, fanouts=tuple(FANOUT))
    tmpl_loader = glt.loader.NeighborLoader(
        ds, FANOUT, train_idx[:BATCH], batch_size=BATCH, seed=0,
        dedup='map', frontier_caps=cal_caps, seed_labels_only=True,
        overflow_policy='off')
    first = train_lib.batch_to_dict(next(iter(tmpl_loader)))
    sstate, stx = train_lib.create_train_state(
        scan_model, jax.random.PRNGKey(0), first)
    scan_k = 8
    # program observatory over this section: reset, then arm cost
    # attribution for the compile epoch (one extra HOST-side AOT
    # compile per new executable — never a dispatch; the measured
    # epoch below runs with it disarmed and fully steady-state)
    from graphlearn_tpu.metrics import programs as _programs
    _programs.reset()
    _prev_cost = os.environ.get('GLT_PROGRAM_COST')
    os.environ['GLT_PROGRAM_COST'] = '1'
    try:
      trainer = glt.loader.ScanTrainer(scan_loader, scan_model, stx,
                                       E2E_CLASSES, chunk_size=scan_k)
      sstate, losses, _ = trainer.run_epoch(sstate)      # compile epoch
      jax.block_until_ready(losses)
    finally:
      if _prev_cost is None:
        os.environ.pop('GLT_PROGRAM_COST', None)
      else:
        os.environ['GLT_PROGRAM_COST'] = _prev_cost
    with count_dispatches() as dc:
      t0 = time.perf_counter()
      sstate, losses, _ = trainer.run_epoch(sstate)
      jax.block_until_ready(losses)
      scan_wall = time.perf_counter() - t0
    scan_steps = int(losses.shape[0])
    steps_products = PRODUCTS_TRAIN_SEEDS // BATCH
    # epoch_dispatches is MEASURED on this bench's scan_epoch_steps-step
    # epoch; the products-scale figure at the same K is the _est key
    result['epoch_dispatches'] = dc.total
    result['epoch_dispatches_products_est'] = \
        -(-steps_products // scan_k) + 2
    result['scan_epoch_steps'] = scan_steps
    result['scan_epoch_chunk'] = scan_k
    result['scan_epoch_wall_s'] = round(scan_wall, 3)
    td = '/tmp/glt_bench_scan_epoch'
    shutil.rmtree(td, ignore_errors=True)
    jax.profiler.start_trace(td)
    sstate, losses, _ = trainer.run_epoch(sstate)
    jax.block_until_ready(losses)
    jax.profiler.stop_trace()
    sprogs = _device_program_ms(td)
    if sprogs:
      # split per-step work (the scan chunks) from per-EPOCH fixed cost
      # (seed-permutation prologue, metrics concat): only the former
      # scales with the products step count — keeps the estimate on the
      # same per-step basis as epoch_time_s
      chunk_ms = sum(ms * cnt for n_, (ms, cnt) in sprogs.items()
                     if 'scan_epoch_chunk' in n_)
      fixed_ms = sum(ms * cnt for n_, (ms, cnt) in sprogs.items()
                     if 'scan_epoch_chunk' not in n_)
      result['scan_epoch_device_trace_s'] = round(
          (chunk_ms + fixed_ms) / 1e3, 3)
      result['epoch_time_s_scanned'] = round(
          (chunk_ms / scan_steps * steps_products + fixed_ms) / 1e3, 3)
    else:
      result['scan_epoch_device_trace_s'] = None
      result['epoch_time_s_scanned'] = None
    # observatory aggregates AFTER the measured + traced epochs: a
    # steady-state section reports its compile-epoch compiles and ZERO
    # further retraces — retrace_count regressing round-over-round is
    # exactly what the gate is for (a new chunk length, a dtype drift)
    agg = _programs.aggregate()
    result['compile_count'] = agg['compile_count']
    result['compile_time_s_total'] = agg['compile_time_s_total']
    result['retrace_count'] = agg['retrace_count']
    result['program_flops_total'] = agg['program_flops_total']
    result['program_peak_hbm_mb'] = agg['program_peak_hbm_mb']
  except Exception as e:
    result['scan_epoch_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- one-call autotune (graphlearn_tpu/tune/, docs/tuning.md) -----
  # tune() on the bench fixture: calibration probes + observatory-
  # scored candidate A/Bs -> a validated config artifact. The wall is
  # the whole one-call cost (the thing an operator pays ONCE instead of
  # hand-picking ~10 knobs); the chosen-config string is the evidence
  # trail for the trajectory table.
  try:
    t0 = time.perf_counter()
    tune_art = glt.tune(
        ds, dict(fanouts=FANOUT, input_nodes=train_idx[:2048],
                 batch_size=256, num_classes=E2E_CLASSES))
    tune_wall = time.perf_counter() - t0
    result['tune_wall_s'] = round(tune_wall, 3)
    _winner = [e for e in tune_art.evidence
               if e.get('kind') == 'winner'][0]
    ch = tune_art.choices
    result['tune_chosen_config'] = (
        f"mode={ch['mode']} caps={ch['frontier_caps']} "
        f"K={ch['chunk_k']} split={ch['split_ratio']} "
        f"bucket_frac={ch['bucket_frac']} wire={ch['wire_dtype']} "
        f"slab={ch['slab_cap']} buckets={ch['serving_buckets']} "
        f"winner={_winner['name']} by {_winner['tie_break']}, "
        f"fingerprint {tune_art.fingerprint[:12]}")
    result['kernel_route_config'] = (
        f"use_pallas_v2={ch['use_pallas_v2']} "
        f"block_rows={ch['gather2_block_rows']} "
        f"run_span={ch['gather2_run_span']} "
        f"use_fused_hop={ch['use_fused_hop']} "
        f"window={ch['fused_hop_window']}")
  except Exception as e:
    result['tune_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- run-as-a-program (loader/run_epoch.py, docs/tuning.md) -------
  # RunTrainer folds an E-epoch RUN into ceil(E*steps/K)+2 dispatches
  # vs E*(ceil(steps/K)+2) for per-epoch ScanTrainer calls. Both arms
  # run a compile pass then a measured steady-state pass from FRESH
  # states (run_scan_ab's donation rule); losses must stay
  # bit-identical between arms — the ratio is a pure dispatch-tax
  # claim, not a semantics trade.
  try:
    from graphlearn_tpu.models import GraphSAGE
    from graphlearn_tpu.models import train as train_lib
    from graphlearn_tpu.utils import count_dispatches
    rs_epochs, rs_steps, rs_k, rs_batch = 3, 8, 4, 1024
    rs_seeds = train_idx[:rs_batch * rs_steps]

    def rs_loader():
      return glt.loader.NeighborLoader(
          ds, FANOUT, rs_seeds, batch_size=rs_batch, shuffle=True,
          drop_last=True, seed=0, dedup='map', frontier_caps=cal_caps,
          seed_labels_only=True, overflow_policy='off')

    rs_model = GraphSAGE(hidden_dim=64, out_dim=E2E_CLASSES,
                         num_layers=len(FANOUT))
    rs_first = train_lib.batch_to_dict(next(iter(rs_loader())))

    def rs_state(tx=None):
      if tx is None:
        return train_lib.create_train_state(
            rs_model, jax.random.PRNGKey(0), rs_first)
      return train_lib.create_train_state(
          rs_model, jax.random.PRNGKey(0), rs_first, optimizer=tx)[0]

    # per-epoch arm: compile pass (E epochs), then the measured pass
    pe_state, rs_tx = rs_state()
    pe = glt.loader.ScanTrainer(rs_loader(), rs_model, rs_tx,
                                E2E_CLASSES, chunk_size=rs_k)
    for _ in range(rs_epochs):
      pe_state, pe_losses, _ = pe.run_epoch(pe_state)
    jax.block_until_ready(pe_losses)
    pe_state = rs_state(rs_tx)
    pe_all = []
    with count_dispatches() as pe_dc:
      t0 = time.perf_counter()
      for _ in range(rs_epochs):
        pe_state, pe_losses, _ = pe.run_epoch(pe_state)
        pe_all.append(pe_losses)
      jax.block_until_ready(pe_losses)
      pe_wall = time.perf_counter() - t0
    pe_all = np.concatenate([np.asarray(x) for x in pe_all])

    # run arm: one RunTrainer over the same stream — compile run, then
    # the measured steady-state run from a fresh state. track_eval
    # OFF: the ratio is the pure dispatch-tax claim, so the run arm
    # must not pay the in-carry eval forward the per-epoch arm lacks
    run_state = rs_state(rs_tx)
    rt = glt.RunTrainer(rs_loader(), rs_model, rs_tx, E2E_CLASSES,
                        chunk_size=rs_k, epochs=rs_epochs,
                        track_eval=False)
    run_state, run_losses, _ = rt.run(run_state)
    jax.block_until_ready(run_losses)
    run_state = rs_state(rs_tx)
    with count_dispatches() as run_dc:
      t0 = time.perf_counter()
      run_state, run_losses, _ = rt.run(run_state)
      jax.block_until_ready(run_losses)
      run_wall = time.perf_counter() - t0
    bit_identical = bool(np.array_equal(np.asarray(run_losses), pe_all))
    result['run_epoch_dispatches'] = run_dc.total
    result['run_wall_s'] = round(run_wall, 3)
    result['run_vs_per_epoch_ratio'] = round(run_wall / pe_wall, 3)
    result['run_scan_config'] = (
        f'E={rs_epochs} steps/epoch={rs_steps} K={rs_k} '
        f'batch={rs_batch} run_dispatches={run_dc.total} '
        f'per_epoch_dispatches={pe_dc.total} '
        f'per_epoch_wall_s={round(pe_wall, 3)} '
        f'bit_identical={bit_identical}')
  except Exception as e:
    result['run_scan_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- scanned DISTRIBUTED epoch: dist-epoch-as-a-program ----------
  # The collocated mesh loop's counterpart of the keys above: the
  # per-step distributed loop pays >= 2 dispatches/batch (sample +
  # collate + feature/label gathers + train step) while DistScanTrainer
  # runs the epoch as ceil(steps/K) + 2 (loader/scan_epoch.py). Runs on
  # whatever devices the backend exposes (mesh size 1 on a single-chip
  # rig — the dispatch-count story is mesh-size-independent); wall
  # times are the scheduling claim, device-trace staged for the
  # multi-chip run.
  try:
    import jax.numpy as jnp
    import optax
    from benchmarks.bench_dist_loader import (make_dist_fixture,
                                              run_scan_ab)
    from graphlearn_tpu.models import GraphSAGE
    from graphlearn_tpu.models import train as train_lib
    dp_ = min(8, len(jax.devices()))
    dn, ddeg, dbatch, dsteps, dchunk = 100_000, 10, 256, 8, 4
    drng = np.random.default_rng(3)
    drows = drng.integers(0, dn, dn * ddeg)
    dcols = drng.integers(0, dn, dn * ddeg)
    _, dds, dmesh = make_dist_fixture(
        drows, dcols, dn, dp_, feat_dim=32, split_ratio=0.2,
        labels=drng.integers(0, 16, dn), feat_rng=drng)
    dseeds = drng.integers(0, dn, dp_ * dbatch * dsteps)

    def _dist_loader():
      return glt.distributed.DistNeighborLoader(
          dds, [10, 5], dseeds, batch_size=dbatch, shuffle=False,
          drop_last=True, seed=0, mesh=dmesh)

    dmodel = GraphSAGE(hidden_dim=64, out_dim=16, num_layers=2)
    dtx = optax.adam(1e-3)
    dfirst = next(iter(_dist_loader()))
    dparams = dmodel.init(jax.random.PRNGKey(0),
                          np.asarray(dfirst.x)[0],
                          np.asarray(dfirst.edge_index)[0],
                          np.asarray(dfirst.edge_mask)[0])

    def _dist_state():
      return train_lib.TrainState(dparams, dtx.init(dparams),
                                  jnp.zeros((), jnp.int32))

    ab = run_scan_ab(_dist_loader, dmodel, dtx, 16, dchunk,
                     _dist_state)
    ddc, sdc = ab['step_dispatches'], ab['scan_dispatches']
    result['dist_epoch_dispatches'] = ddc.total
    result['dist_epoch_wall_s'] = round(ab['step_wall_s'], 3)
    result['dist_scan_epoch_dispatches'] = sdc.total
    result['dist_scan_epoch_wall_s'] = round(ab['scan_wall_s'], 3)
    result['dist_scan_epoch_steps'] = int(
        np.asarray(ab['scan_losses']).shape[0])
    result['dist_scan_epoch_chunk'] = dchunk
    result['dist_scan_mesh_size'] = dp_
    result['dist_scan_epoch_dispatch_reduction_x'] = round(
        ddc.total / max(sdc.total, 1), 1)
  except Exception as e:
    result['dist_scan_epoch_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- topology-wide autotune + continuous retune (tune/topology.py +
  # tune/retune.py, docs/tuning.md 'Topology candidates' / 'Continuous
  # retuning'): one dist-scenario tune on the CPU-replica mesh — every
  # candidate is a freshly BUILT scenario because the dist knobs are
  # store-construction parameters — then a live RetuneScheduler timed
  # from drift-trigger fire to published artifact.
  try:
    import threading

    import jax.numpy as jnp
    import optax
    from graphlearn_tpu.models import GraphSAGE
    from graphlearn_tpu.models import train as train_lib
    from graphlearn_tpu.typing import GraphPartitionData
    from jax.sharding import Mesh
    tp_ = min(4, len(jax.devices()))
    tt_n, tt_deg, tt_batch, tt_steps = 4_000, 8, 8, 4
    tt_rng = np.random.default_rng(7)
    tt_rows = tt_rng.integers(0, tt_n, tt_n * tt_deg)
    tt_cols = tt_rng.integers(0, tt_n, tt_n * tt_deg)
    tt_node_pb = (np.arange(tt_n) % tp_).astype(np.int32)
    tt_epb = tt_node_pb[tt_rows]
    tt_eids = np.arange(tt_rows.shape[0])
    tt_parts, tt_feats = [], []
    for q_ in range(tp_):
      m_ = tt_epb == q_
      tt_parts.append(GraphPartitionData(
          edge_index=np.stack([tt_rows[m_], tt_cols[m_]]),
          eids=tt_eids[m_]))
      ids_ = np.nonzero(tt_node_pb == q_)[0]
      tt_feats.append((ids_.astype(np.int64),
                       tt_rng.standard_normal((ids_.shape[0], 16))
                       .astype(np.float32)))
    tt_mesh = Mesh(np.array(jax.devices()[:tp_]), ('g',))
    tt_dg = glt.distributed.DistGraph(tp_, 0, tt_parts, tt_node_pb,
                                      tt_epb)
    tt_labels = tt_rng.integers(0, 8, tt_n)
    tt_seeds = tt_rng.integers(0, tt_n, tp_ * tt_batch * tt_steps)
    tt_model = GraphSAGE(hidden_dim=32, out_dim=8, num_layers=2)
    tt_tx = optax.adam(1e-3)

    def _topo_scenario(knobs, chunk_k):
      wire = jnp.bfloat16 if knobs.get('wire_dtype') == 'bf16' else None
      df_ = glt.distributed.DistFeature(
          tp_, tt_feats, tt_node_pb, tt_mesh,
          split_ratio=knobs.get('split_ratio') or 0.0,
          wire_dtype=wire, bucket_frac=knobs.get('bucket_frac'))
      ds_ = glt.distributed.DistDataset(tp_, 0, tt_dg, df_,
                                        node_labels=tt_labels)
      loader_ = glt.distributed.DistNeighborLoader(
          ds_, [4, 2], tt_seeds, batch_size=tt_batch, shuffle=False,
          drop_last=True, seed=0, mesh=tt_mesh)
      first_ = next(iter(loader_))
      params_ = tt_model.init(jax.random.PRNGKey(0),
                              np.asarray(first_.x)[0],
                              np.asarray(first_.edge_index)[0],
                              np.asarray(first_.edge_mask)[0])
      state_ = train_lib.TrainState(params_, tt_tx.init(params_),
                                    jnp.zeros((), jnp.int32))
      trainer_ = glt.loader.DistScanTrainer(loader_, tt_model, tt_tx, 8,
                                            chunk_size=chunk_k)
      return trainer_, state_

    tt_base = glt.distributed.DistDataset(
        tp_, 0, tt_dg,
        glt.distributed.DistFeature(tp_, tt_feats, tt_node_pb, tt_mesh,
                                    split_ratio=0.2),
        node_labels=tt_labels)
    tt_cfg = dict(make_scenario=_topo_scenario, fanouts=[4, 2],
                  batch_size=tt_batch, feat_dim=16, num_partitions=tp_,
                  epoch_steps=tt_steps)
    t0 = time.perf_counter()
    topo_art = glt.tune(tt_base, tt_cfg, topology='dist',
                        probe_steps=tt_steps)
    result['dist_tune_wall_s'] = round(time.perf_counter() - t0, 3)
    _tw = [e for e in topo_art.evidence if e.get('kind') == 'winner'][0]
    tch = topo_art.choices
    result['topology_tune_config'] = (
        f"topology={tch['topology']} winner={_tw['name']} "
        f"K={tch['chunk_k']} split={tch['split_ratio']} "
        f"bucket_frac={tch['bucket_frac']} wire={tch['wire_dtype']} "
        f"by {_tw['tie_break']}, "
        f"fingerprint {topo_art.fingerprint[:12]}")
    # trigger-to-publish latency through a LIVE scheduler: a manual
    # drift probe flips, the shadow tune re-runs the same dist field,
    # and the clock stops when publish_fn lands the fresh artifact
    published = threading.Event()
    tt_trig = [False]
    sched = glt.tune.RetuneScheduler(
        shadow_tune_fn=lambda: glt.tune(tt_base, tt_cfg,
                                        topology='dist',
                                        probe_steps=tt_steps),
        publish_fn=lambda art: published.set(),
        triggers={'bench_drift': lambda: tt_trig[0]},
        initial=topo_art, poll_s=0.05)
    sched.start()
    try:
      tt_trig[0] = True
      t0 = time.perf_counter()
      if not published.wait(timeout=300):
        raise TimeoutError('retune did not publish within 300s '
                           f'(last_error={sched.last_error})')
      result['retune_trigger_to_publish_s'] = round(
          time.perf_counter() - t0, 3)
    finally:
      tt_trig[0] = False
      sched.stop()
  except Exception as e:
    result['topology_tune_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- RUN_MEAN_IMPL A/B (the prof_copytax.py decision, VERDICT r5):
  # emit both impls' e2e step ms as bench keys so the next on-chip run
  # DECIDES the models.RUN_MEAN_IMPL default instead of staying stalled
  # behind a manual probe run.
  try:
    from graphlearn_tpu.models import models as models_lib
    prev_impl = models_lib.RUN_MEAN_IMPL
    try:
      # per-impl isolation: reduce_window's vjp asserts on jax 0.4.x
      # (this container), so a 'window' failure must not take the
      # 'reshape' number down with it — the pair is the decision input
      for impl in ('reshape', 'window'):
        key = f'run_mean_impl_{impl}_ms'
        try:
          models_lib.RUN_MEAN_IMPL = impl
          tot_i, _ = _run_e2e(ds, train_idx, jnp.bfloat16, jax,
                              f'/tmp/glt_bench_copytax_{impl}',
                              variant='exact', cal_caps=cal_caps)
          result[key] = round(float(tot_i), 3) if tot_i else None
        except Exception as e:
          result[key] = None
          result[f'{key}_error'] = f'{type(e).__name__}: {e}'[:200]
    finally:
      models_lib.RUN_MEAN_IMPL = prev_impl
    # auto-land the winner (ISSUE 13): when both legs produced numbers,
    # write the decision into the record so the next round can flip the
    # models.RUN_MEAN_IMPL default (or pin GLT_RUN_MEAN_IMPL) with a
    # one-line change citing this record — no manual probe run needed
    dec, why = models_lib.run_impl_decision(
        result.get('run_mean_impl_reshape_ms'),
        result.get('run_mean_impl_window_ms'))
    result['run_mean_impl_decision'] = dec
    result['run_mean_impl_decision_config'] = (
        f'{why}; basis: exact-variant bf16 e2e step ({E2E_ITERS} traced '
        'iters); apply by editing models.RUN_MEAN_IMPL citing this '
        'record')
  except Exception as e:
    result['run_mean_impl_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- RUN_SOFTMAX_IMPL A/B (the PR 13 copy-tax residual): the
  # dense-GAT masked run-softmax chain ('window' = flat [f*k, H]
  # reduce_window, models._masked_run_softmax) measured on the RGAT e2e
  # step — the conv family that actually runs the softmax — with the
  # SAME per-leg isolation and >3% auto-decision as run_mean above.
  # Apply by editing models.RUN_SOFTMAX_IMPL or pinning
  # GLT_RUN_SOFTMAX_IMPL, citing this record.
  try:
    from graphlearn_tpu.models import models as models_lib
    prev_sm = models_lib.RUN_SOFTMAX_IMPL
    try:
      for impl in ('reshape', 'window'):
        key = f'run_softmax_impl_{impl}_ms'
        try:
          models_lib.RUN_SOFTMAX_IMPL = impl
          tot_i, _, _ = _run_hetero_e2e(
              jax, f'/tmp/glt_bench_softmax_{impl}', conv='gat')
          result[key] = round(float(tot_i), 3) if tot_i else None
        except Exception as e:
          result[key] = None
          result[f'{key}_error'] = f'{type(e).__name__}: {e}'[:200]
    finally:
      models_lib.RUN_SOFTMAX_IMPL = prev_sm
    dec, why = models_lib.run_impl_decision(
        result.get('run_softmax_impl_reshape_ms'),
        result.get('run_softmax_impl_window_ms'))
    result['run_softmax_impl_decision'] = dec
    result['run_softmax_impl_decision_config'] = (
        f'{why}; basis: RGAT bf16 e2e step; apply by editing '
        'models.RUN_SOFTMAX_IMPL (or pin GLT_RUN_SOFTMAX_IMPL) citing '
        'this record')
  except Exception as e:
    result['run_softmax_impl_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- kernel campaign r13: gather v2 + fused hop vs their XLA paths
  # (device-trace A/B; ratios < 1.0 flip the per-kernel routing flags —
  # UnifiedTensor.use_pallas_v2 / NeighborSampler(use_fused_hop=True)).
  # The full autotune grid lives in benchmarks/prof_gather2.py; bench
  # tracks one representative config per kernel round over round.
  try:
    import jax.numpy as jnp
    if backend != 'tpu':
      raise RuntimeError(
          f'backend {backend}: kernel-path device-trace claims are '
          'TPU-only (CPU interpret parity lives in tests/test_ops.py)')
    g2_table = jnp.asarray(
        np.random.default_rng(5).standard_normal((NUM_NODES, 128))
        .astype(np.float32))
    # chunk-structured sorted-unique ids: gather v2's target workload is
    # the tiered staging / slab gather, whose planned miss sets are
    # CHUNK-contiguous (rows group per disk chunk — storage/planner) —
    # 1024 random 128-row chunks = 131072 ids, sorted, every chunk a
    # stretch of consecutive rows, so the probe actually exercises the
    # multi-row run-DMA path. (A uniform sorted sample of 131k from 1M
    # has ~zero full 8-runs: P ~ 0.13^7 — it would measure only the
    # v1-equivalent single-DMA path plus plan overhead.)
    g2_starts = np.sort(np.random.default_rng(6).choice(
        NUM_NODES // 128, 1024, replace=False)) * 128
    g2_ids = jnp.asarray(
        (g2_starts[:, None] + np.arange(128)[None, :])
        .reshape(-1).astype(np.int32))
    from graphlearn_tpu.ops.gather_pallas import _gather_rows_hbm2_impl

    def _g2_take(t, i):
      return jnp.take(t, i, axis=0)
    take_fn = jax.jit(_g2_take)
    g2_ms = _traced_call_ms(
        jax, lambda: _gather_rows_hbm2_impl(g2_table, g2_ids, 256, 8,
                                            True, False),
        '/tmp/glt_bench_gather2', 'jit__gather_rows_hbm2_impl')
    take_ms = _traced_call_ms(jax, lambda: take_fn(g2_table, g2_ids),
                              '/tmp/glt_bench_g2take', 'jit__g2_take')
    result['gather2_ms'] = round(g2_ms, 3) if g2_ms else None
    result['gather2_vs_take_ratio'] = (
        round(g2_ms / take_ms, 3) if g2_ms and take_ms else None)
    result['gather2_config'] = (
        f'[{NUM_NODES}, 128] f32 table, 1024 x 128-row contiguous '
        'chunks = 131072 sorted-unique ids (presorted=True, the '
        'staging-slab shape), block_rows=256, run_span=8 vs jnp.take')
  except Exception as e:
    result['gather2_error'] = f'{type(e).__name__}: {e}'[:200]

  try:
    import jax.numpy as jnp
    if backend != 'tpu':
      raise RuntimeError(
          f'backend {backend}: kernel-path device-trace claims are '
          'TPU-only (CPU interpret parity lives in tests/test_ops.py)')
    fh_ga = s_cal._graph_arrays()
    fh_meta = s_cal._csr_meta()
    fh_blocks = glt.ops.build_indices128(fh_ga['indices'], min_rows=5)
    fh_seeds = jnp.asarray(np.random.default_rng(7).integers(
        0, NUM_NODES, BATCH * FANOUT[0]).astype(np.int32))
    fh_mask = jnp.ones((BATCH * FANOUT[0],), bool)
    fh_key = jax.random.fold_in(jax.random.PRNGKey(0), 1)
    fh_k = FANOUT[1]
    fh_ms = _traced_call_ms(
        jax, lambda: glt.ops.sample_hop_fused(
            fh_ga['indptr'], fh_ga['indices'], fh_blocks, fh_seeds,
            fh_mask, fh_k, fh_key, meta=fh_meta),
        '/tmp/glt_bench_fusedhop', 'jit_sample_hop_fused')
    xla_ms = _traced_call_ms(
        jax, lambda: glt.ops.uniform_sample(
            fh_ga['indptr'], fh_ga['indices'], fh_seeds, fh_mask, fh_k,
            fh_key, meta=fh_meta),
        '/tmp/glt_bench_xlahop', 'jit_uniform_sample')
    result['fused_hop_ms'] = round(fh_ms, 3) if fh_ms else None
    result['fused_hop_vs_xla_ratio'] = (
        round(fh_ms / xla_ms, 3) if fh_ms and xla_ms else None)
    result['fused_hop_config'] = (
        f'one hop, {BATCH * FANOUT[0]} seeds x k={fh_k}, window=512, '
        'block_seeds=128, bench CSR vs ops.uniform_sample')
  except Exception as e:
    result['fused_hop_error'] = f'{type(e).__name__}: {e}'[:200]

  # fused MULTI-HOP frontier (r16, ops/sample_fused.py): one whole
  # fanout level — sample+gather+dedup in a single kernel pass — vs the
  # identical level through the XLA merge engine (uniform draw +
  # induce_next_merge). Both arms are the SAME jitted entry; the kernel
  # arm routes through the level kernel via the blocks128 table.
  try:
    import jax.numpy as jnp
    if backend != 'tpu':
      raise RuntimeError(
          f'backend {backend}: kernel-path device-trace claims are '
          'TPU-only (CPU interpret parity lives in tests/test_ops.py)')
    fl_ga = s_cal._graph_arrays()
    fl_meta = s_cal._csr_meta()
    fl_blocks = glt.ops.build_indices128(fl_ga['indices'], min_rows=5)
    fl_seeds = jnp.asarray(np.random.default_rng(8).integers(
        0, NUM_NODES, BATCH).astype(np.int32))
    fl_k = FANOUT[0]
    fl_cap = BATCH + BATCH * fl_k
    fl_key = jax.random.fold_in(jax.random.PRNGKey(0), 2)
    fl_state, fl_uniq, fl_umask, _ = glt.ops.init_node_merge(
        fl_seeds, jnp.ones((BATCH,), bool), fl_cap)

    def _fl_call(blocks):
      return glt.ops.sample_level_fused(
          fl_ga['indptr'], fl_ga['indices'], blocks, fl_uniq, fl_umask,
          fl_k, fl_key, fl_state, jnp.arange(BATCH, dtype=jnp.int32),
          meta=fl_meta, prefix_cap=BATCH, max_new=BATCH * fl_k,
          final=True)
    fl_ms = _traced_call_ms(jax, lambda: _fl_call(fl_blocks),
                            '/tmp/glt_bench_fusedlevel',
                            'jit_sample_level_fused')
    flx_ms = _traced_call_ms(jax, lambda: _fl_call(None),
                             '/tmp/glt_bench_xlalevel',
                             'jit_sample_level_fused')
    result['fused_multihop_ms'] = round(fl_ms, 3) if fl_ms else None
    result['fused_multihop_vs_xla_ratio'] = (
        round(fl_ms / flx_ms, 3) if fl_ms and flx_ms else None)
    result['fused_multihop_config'] = (
        f'one level, {BATCH} seeds x k={fl_k}, prefix_cap={BATCH}, '
        'window=512, block_seeds=128, bench CSR vs uniform draw + '
        'induce_next_merge (same jitted entry, blocks128=None)')
  except Exception as e:
    result['fused_multihop_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- hetero (IGBH-shaped RGNN/RGAT) train step --------------------
  try:
    for conv, key in (('sage', 'hetero_rgnn'), ('gat', 'hetero_rgat')):
      tot, tr, _ = _run_hetero_e2e(jax, f'/tmp/glt_bench_hetero_{conv}',
                                   conv=conv)
      result[f'{key}_step_ms_bf16'] = (round(float(tot), 3) if tot
                                       else None)
      result[f'{key}_train_program_ms'] = (round(float(tr), 3) if tr
                                           else None)
  except Exception as e:
    result['hetero_step_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- hetero at the REFERENCE shape: batch 5120 x 3 typed hops
  # (examples/igbh/train_rgnn.py defaults) under calibrated
  # per-(hop, etype) caps — statically infeasible without them
  ref_loaders = []
  ref_convs = (('sage', 'hetero_rgnn_ref'), ('gat', 'hetero_rgat_ref'))
  try:
    for conv, key in ref_convs:
      tot, tr, ldr = _run_hetero_e2e(
          jax, f'/tmp/glt_bench_hetero_ref_{conv}', conv=conv, hb=5120,
          hops=3, variant='calibrated')
      result[f'{key}_step_ms_bf16'] = (round(float(tot), 3) if tot
                                       else None)
      result[f'{key}_train_program_ms'] = (round(float(tr), 3) if tr
                                           else None)
      ref_loaders.append(ldr)
    result['hetero_ref_config'] = ('batch 5120 x 3 hops [15,10,5], '
                                   'calibrated merge_dense, exact dedup')
  except Exception as e:
    result['hetero_ref_error'] = f'{type(e).__name__}: {e}'[:200]
  # ---- distributed feature-exchange volume (analytic, products
  # config P=8): the collate-time DistFeature all_to_all MB/shard/batch
  # under the miss-only posture (bucket_frac=2.0, split_ratio=0.2 hit
  # floor, bf16 wire) vs the full-width posture it replaced. Analytic
  # from the same static capacities the program compiles with —
  # PERF.md 'Feature path (distributed)'.
  try:
    from graphlearn_tpu.distributed.dist_feature import \
        feature_exchange_mb
    from graphlearn_tpu.sampler.neighbor_sampler import capacity_plan
    node_cap = sum(capacity_plan(BATCH, FANOUT))
    fx_p = 8
    fx_opt = feature_exchange_mb(node_cap, fx_p, E2E_FEAT_DIM,
                                 bucket_frac=2.0, wire_bytes=2,
                                 hit_rate=0.2)
    fx_full = feature_exchange_mb(node_cap, fx_p, E2E_FEAT_DIM,
                                  bucket_frac=None, wire_bytes=4)
    result['feature_exchange_mb_per_batch'] = round(fx_opt, 3)
    result['feature_exchange_mb_per_batch_fullwidth'] = round(fx_full, 3)
    result['feature_exchange_reduction_x'] = round(fx_full / fx_opt, 1)
    result['feature_exchange_config'] = (
        f'P={fx_p}, request_width={node_cap}, F={E2E_FEAT_DIM}, '
        'bucket_frac=2.0, split_ratio=0.2, bf16 wire')
  except Exception as e:
    result['feature_exchange_mb_per_batch'] = None
    result['feature_exchange_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- out-of-core oversubscription (storage/, ROADMAP item 2) ----
  # A scanned epoch over a TieredFeature whose table is >= 4x the
  # HBM(hot)+RAM(warm) budget, A/B'd against the identical all-HBM
  # ScanTrainer epoch. Fetch-bearing by design (the prologue plan fetch
  # + per-chunk slab uploads ARE the mechanism), so it sits after every
  # dispatch-sensitive section; epoch 1 compiles, epoch 2 measures.
  try:
    import tempfile
    import time as _time

    from graphlearn_tpu import metrics as glt_metrics
    from graphlearn_tpu.models import GraphSAGE as _SAGE
    from graphlearn_tpu.models import train as _train_lib
    from graphlearn_tpu.storage import TieredFeature, TieredScanTrainer
    ov_n, ov_deg, ov_f = 60_000, 4, 64
    ov_hot, ov_warm = 4096, 4096
    ov_batch, ov_seeds, ov_k = 256, 8192, 8
    ov_rng = np.random.default_rng(17)
    ov_rows = np.repeat(np.arange(ov_n), ov_deg)
    ov_cols = (ov_rows + ov_rng.integers(1, ov_n, ov_rows.shape[0])) % ov_n
    ov_feat = ov_rng.standard_normal((ov_n, ov_f)).astype(np.float32)
    ov_labels = ov_rng.integers(0, E2E_CLASSES, ov_n)
    ov_pool = ov_rng.permutation(ov_n)[:ov_seeds].astype(np.int64)
    feat_mb = ov_feat.nbytes / 1e6
    budget_mb = (ov_hot + ov_warm) * ov_f * 4 / 1e6
    assert feat_mb >= 4 * budget_mb, (feat_mb, budget_mb)

    def ov_build(store_fn):
      ds = glt.data.Dataset()
      ds.init_graph(np.stack([ov_rows, ov_cols]), graph_mode='CPU',
                    num_nodes=ov_n)
      ds.node_features = store_fn()
      ds.init_node_labels(ov_labels)
      return glt.loader.NeighborLoader(ds, [3, 2], ov_pool,
                                       batch_size=ov_batch, shuffle=False,
                                       drop_last=True, seed=5)

    ov_model = _SAGE(hidden_dim=64, out_dim=E2E_CLASSES, num_layers=2)
    ov_tmpl = _train_lib.batch_to_dict(next(iter(ov_build(
        lambda: glt.data.Feature(ov_feat, split_ratio=1.0)))))

    def ov_epoch(trainer_cls, store_fn, **kw):
      import jax as _jax
      loader = ov_build(store_fn)
      state, tx = _train_lib.create_train_state(
          ov_model, _jax.random.PRNGKey(0), ov_tmpl)
      tr = trainer_cls(loader, ov_model, tx, E2E_CLASSES,
                       chunk_size=ov_k, **kw)
      state, _, _ = tr.run_epoch(state)          # compile epoch
      t0 = _time.perf_counter()
      state, losses, _ = tr.run_epoch(state)     # measured epoch
      _jax.block_until_ready(losses)
      wall = _time.perf_counter() - t0
      return wall, np.asarray(losses), tr

    hbm_wall, hbm_losses, _ = ov_epoch(
        glt.loader.ScanTrainer,
        lambda: glt.data.Feature(ov_feat, split_ratio=1.0))
    ov_dir = tempfile.mkdtemp(prefix='glt_oversub_')
    c0 = glt_metrics.default_registry().counters()
    t_wall, t_losses, t_tr = ov_epoch(
        TieredScanTrainer,
        lambda: TieredFeature(ov_feat, hot_rows=ov_hot,
                              warm_rows=ov_warm, spill_dir=ov_dir))
    c1 = glt_metrics.default_registry().counters()
    staged = c1.get('storage.staged_rows', 0) - c0.get(
        'storage.staged_rows', 0)
    missed = c1.get('storage.prefetch_miss', 0) - c0.get(
        'storage.prefetch_miss', 0)
    staged_mb = (c1.get('storage.staged_bytes', 0)
                 - c0.get('storage.staged_bytes', 0)) / 1e6
    chunks = 2 * max(1, -(-(ov_seeds // ov_batch) // ov_k))
    t_tr.close()
    result['oversub_epoch_wall_s'] = round(t_wall, 3)
    result['oversub_hbm_epoch_wall_s'] = round(hbm_wall, 3)
    result['oversub_ratio'] = round(t_wall / hbm_wall, 3)
    result['prefetch_hit_rate'] = round(
        staged / (staged + missed), 4) if staged + missed else None
    result['staged_mb_per_chunk'] = round(staged_mb / chunks, 3)
    result['oversub_bit_identical'] = bool(
        np.array_equal(hbm_losses, t_losses))
    result['oversub_config'] = (
        f'N={ov_n}, deg={ov_deg}, F={ov_f}, feat {feat_mb:.1f} MB vs '
        f'hot+warm {budget_mb:.1f} MB ({feat_mb / budget_mb:.1f}x '
        f'oversub), batch {ov_batch} x {ov_seeds // ov_batch} steps, '
        f'K={ov_k}')
  except Exception as e:
    result['oversub_epoch_wall_s'] = None
    result['oversub_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- DIST oversubscription through the shard exchange (storage/
  # dist_scan.py, ISSUE 14): a scanned DISTRIBUTED epoch whose shards
  # hold only a hot prefix + chunk-staged exchange slabs, A/B'd against
  # the identical all-HBM DistScanTrainer epoch. Fetch-bearing by
  # design (the prologue plan fetch + per-chunk slab uploads ARE the
  # mechanism), so it sits with the other fetch-bearing sections.
  try:
    import tempfile
    import time as _time

    import jax.numpy as jnp
    import optax
    from graphlearn_tpu.models import GraphSAGE as _DSAGE
    from graphlearn_tpu.models import train as _dtrain
    from graphlearn_tpu.storage import (TieredDistFeature,
                                        TieredDistScanTrainer)
    from graphlearn_tpu.typing import GraphPartitionData
    from jax.sharding import Mesh
    do_n, do_deg, do_f = 16_384, 4, 64
    do_p = min(4, max(1, len(jax.devices())))
    do_batch, do_steps, do_k = 64, 16, 4        # per shard
    do_rng = np.random.default_rng(31)
    do_rows = np.repeat(np.arange(do_n), do_deg)
    do_cols = (do_rows + do_rng.integers(1, do_n, do_rows.shape[0])) % do_n
    do_pb = (np.arange(do_n) % do_p).astype(np.int32)
    do_epb = do_pb[do_rows]
    do_eids = np.arange(do_rows.shape[0])
    do_labels = do_rng.integers(0, E2E_CLASSES, do_n)
    do_feats = [(np.nonzero(do_pb == q)[0].astype(np.int64),
                 do_rng.standard_normal(
                     (int((do_pb == q).sum()), do_f)).astype(np.float32))
                for q in range(do_p)]
    do_parts = []
    for q in range(do_p):
      m = do_epb == q
      do_parts.append(GraphPartitionData(
          edge_index=np.stack([do_rows[m], do_cols[m]]),
          eids=do_eids[m]))
    do_seeds = do_rng.integers(0, do_n, do_p * do_batch * do_steps)
    do_mesh = Mesh(np.array(jax.devices()[:do_p]), ('g',))
    n_part = max(ids.shape[0] for ids, _ in do_feats)
    do_hot = max(1, n_part // 8)                 # 8x >= the 4x gate

    def do_loader(store):
      dg = glt.distributed.DistGraph(do_p, 0, do_parts, do_pb, do_epb)
      ds = glt.distributed.DistDataset(do_p, 0, dg, store,
                                       node_labels=do_labels)
      return glt.distributed.DistNeighborLoader(
          ds, [4, 2], do_seeds, batch_size=do_batch, shuffle=False,
          drop_last=True, seed=0, mesh=do_mesh)

    do_model = _DSAGE(hidden_dim=64, out_dim=E2E_CLASSES, num_layers=2)
    do_tx = optax.adam(1e-3)
    hbm_loader = do_loader(glt.distributed.DistFeature(
        do_p, do_feats, do_pb, do_mesh, split_ratio=0.1))
    do_first = next(iter(hbm_loader))
    do_params = do_model.init(jax.random.PRNGKey(0),
                              np.asarray(do_first.x)[0],
                              np.asarray(do_first.edge_index)[0],
                              np.asarray(do_first.edge_mask)[0])
    # host copy: run_epoch DONATES its state, and a replicated
    # device_put can alias the original buffers — each arm must start
    # from FRESH device arrays of the same values (run_scan_ab's rule)
    do_params_host = jax.tree.map(np.asarray, do_params)

    def do_state():
      p = jax.tree.map(jnp.asarray, do_params_host)
      return _dtrain.TrainState(p, do_tx.init(p),
                                jnp.zeros((), jnp.int32))

    def do_epoch(trainer):
      state, _, _ = trainer.run_epoch(do_state())     # compile epoch
      t0 = _time.perf_counter()
      state, losses, _ = trainer.run_epoch(state)     # measured epoch
      jax.block_until_ready(losses)
      return _time.perf_counter() - t0, np.asarray(losses)

    hbm_tr = glt.loader.DistScanTrainer(
        do_loader(glt.distributed.DistFeature(
            do_p, do_feats, do_pb, do_mesh, split_ratio=0.1)),
        do_model, do_tx, E2E_CLASSES, chunk_size=do_k)
    hbm_wall, hbm_losses = do_epoch(hbm_tr)
    do_dir = tempfile.mkdtemp(prefix='glt_dist_oversub_')
    t_tr = TieredDistScanTrainer(
        do_loader(TieredDistFeature(
            do_p, do_feats, do_pb, mesh=do_mesh, spill_dir=do_dir,
            hot_prefix_rows=do_hot, split_ratio=0.1)),
        do_model, do_tx, E2E_CLASSES, chunk_size=do_k)
    try:
      t_wall, t_losses = do_epoch(t_tr)
    finally:
      # also on a failed epoch: the stager worker thread (and its
      # spill-dir mmaps) must not outlive this section
      t_tr.close()
    result['dist_oversub_epoch_wall_s'] = round(t_wall, 3)
    result['dist_oversub_hbm_epoch_wall_s'] = round(hbm_wall, 3)
    result['dist_oversub_ratio'] = round(t_wall / hbm_wall, 3)
    result['dist_oversub_bit_identical'] = bool(
        np.array_equal(hbm_losses, t_losses))
    result['dist_oversub_config'] = (
        f'N={do_n}, deg={do_deg}, F={do_f}, P={do_p} mesh, hot prefix '
        f'{do_hot}/{n_part} rows/shard ({n_part / do_hot:.1f}x '
        f'oversub), batch {do_batch}/shard x {do_steps} steps, '
        f'K={do_k}')
  except Exception as e:
    result['dist_oversub_epoch_wall_s'] = None
    result['dist_oversub_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- demand-paged PER-STEP oversubscribed gather (storage/dist.py,
  # ISSUE 16): TieredDistFeature.get on an oversubscribed store (hot
  # prefix + per-step demand-paged slabs) vs the identical all-HBM
  # per-step loop. Rows must be bit-identical (the exact per-step
  # plan); the ratio prices the per-step host round trip the scanned
  # path amortizes at chunk boundaries. Fetch-bearing BY DESIGN.
  try:
    import tempfile
    import time as _time

    import jax.numpy as jnp
    from jax.sharding import Mesh

    from graphlearn_tpu.storage import TieredDistFeature
    ps_p, ps_f, ps_n = 4, 32, 20_000
    ps_batch, ps_steps = 256, 16
    ps_rng = np.random.default_rng(37)
    ps_pb = (np.arange(ps_n) % ps_p).astype(np.int32)
    ps_feats = [(np.nonzero(ps_pb == q)[0].astype(np.int64),
                 ps_rng.standard_normal(
                     (int((ps_pb == q).sum()), ps_f)).astype(np.float32))
                for q in range(ps_p)]
    ps_mesh = Mesh(np.array(jax.devices()[:ps_p]), ('g',))
    ps_npart = max(ids.shape[0] for ids, _ in ps_feats)
    ps_hot = max(1, ps_npart // 8)               # 8x oversubscription
    ps_stores = [
        TieredDistFeature(ps_p, ps_feats, ps_pb, mesh=ps_mesh,
                          spill_dir=tempfile.mkdtemp(prefix='glt_ps_'),
                          hot_prefix_rows=h, split_ratio=0.1)
        for h in (0, ps_hot)]
    ps_ids = ps_rng.integers(
        0, ps_n, (ps_steps, ps_p, ps_batch)).astype(np.int32)

    def ps_loop(store):
      # compile pass over every step (the demand-paged path keys its
      # programs by pow2 slab cap — all caps must be warm), then the
      # measured pass over the identical stream
      for s in range(ps_steps):
        jax.block_until_ready(store.get(ps_ids[s]))
      t0 = _time.perf_counter()
      outs = [store.get(ps_ids[s]) for s in range(ps_steps)]
      jax.block_until_ready(outs)
      wall = _time.perf_counter() - t0
      return wall, np.stack([np.asarray(jax.device_get(o))
                             for o in outs])
    hbm_wall, hbm_rows = ps_loop(ps_stores[0])
    ps_wall, ps_rows = ps_loop(ps_stores[1])
    result['oversub_per_step_wall_s'] = round(ps_wall, 3)
    result['oversub_per_step_hbm_wall_s'] = round(hbm_wall, 3)
    result['oversub_per_step_ratio'] = round(ps_wall / hbm_wall, 3)
    result['oversub_per_step_bit_identical'] = bool(
        np.array_equal(hbm_rows, ps_rows))
    result['oversub_per_step_config'] = (
        f'N={ps_n}, F={ps_f}, P={ps_p} mesh, hot prefix '
        f'{ps_hot}/{ps_npart} rows/shard '
        f'({ps_npart / ps_hot:.1f}x oversub), batch {ps_batch}/shard '
        f'x {ps_steps} per-step get() dispatches, split_ratio=0.1')
  except Exception as e:
    result['oversub_per_step_ratio'] = None
    result['oversub_per_step_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- chunk-granular recovery (recovery/, docs/recovery.md) ----
  # Three measurements on one scanned fixture: (1) plain epoch wall,
  # (2) the SAME epoch with a ChunkCheckpointer at the default cadence
  # (overhead gate: <5%), (3) a kill at chunk N + resume, reporting
  # the lost-work bound (replayed chunks) and asserting the resumed
  # epoch's losses bit-match the uninterrupted stream. Fetch-bearing
  # by design (boundary device_gets ARE the mechanism), so it sits
  # with the other fetch-bearing sections, after everything
  # dispatch-sensitive.
  try:
    import tempfile
    import time as _time

    from graphlearn_tpu import metrics as glt_metrics
    from graphlearn_tpu.models import GraphSAGE as _SAGE
    from graphlearn_tpu.models import train as _train_lib
    from graphlearn_tpu.recovery import ChunkCheckpointer
    rc_n, rc_deg, rc_f = 20_000, 4, 32
    rc_batch, rc_seeds, rc_k, rc_every = 128, 4096, 4, 4
    rc_rng = np.random.default_rng(23)
    rc_rows = np.repeat(np.arange(rc_n), rc_deg)
    rc_cols = (rc_rows + rc_rng.integers(1, rc_n, rc_rows.shape[0])) % rc_n
    rc_feat = rc_rng.standard_normal((rc_n, rc_f)).astype(np.float32)
    rc_labels = rc_rng.integers(0, E2E_CLASSES, rc_n)
    rc_pool = rc_rng.permutation(rc_n)[:rc_seeds].astype(np.int64)
    rc_steps = rc_seeds // rc_batch          # 32 steps, 8 chunks of K=4

    def rc_build():
      ds = glt.data.Dataset()
      ds.init_graph(np.stack([rc_rows, rc_cols]), graph_mode='CPU',
                    num_nodes=rc_n)
      ds.init_node_features(rc_feat)
      ds.init_node_labels(rc_labels)
      return glt.loader.NeighborLoader(ds, [3, 2], rc_pool,
                                       batch_size=rc_batch,
                                       shuffle=False, drop_last=True,
                                       seed=7)

    rc_model = _SAGE(hidden_dim=64, out_dim=E2E_CLASSES, num_layers=2)
    rc_tmpl = _train_lib.batch_to_dict(next(iter(rc_build())))

    def rc_epoch(ckpt_dir=None, kill_chunk=None):
      """(wall of the 2nd epoch or None, losses of the 1st epoch,
      trainer, checkpointer) — epoch 1 compiles, epoch 2 measures;
      kill_chunk raises out of epoch 1 at that chunk's boundary."""
      import jax as _jax
      state, tx = _train_lib.create_train_state(
          rc_model, _jax.random.PRNGKey(0), rc_tmpl)
      tr = glt.loader.ScanTrainer(rc_build(), rc_model, tx,
                                  E2E_CLASSES, chunk_size=rc_k)
      ck = None
      if ckpt_dir is not None:
        ck = ChunkCheckpointer(ckpt_dir, every=rc_every).attach(tr)
      if kill_chunk is not None:
        def rc_killer(c, start, k):
          if c == kill_chunk:
            raise RuntimeError('bench kill')
        tr.stage_hook = rc_killer
        try:
          tr.run_epoch(state)
          raise AssertionError('bench kill did not fire')
        except RuntimeError:
          pass
        ck.close()
        return None, None, tr, ck
      state, losses1, _ = tr.run_epoch(state)     # compile epoch
      t0 = _time.perf_counter()
      state, losses2, _ = tr.run_epoch(state)     # measured epoch
      _jax.block_until_ready(losses2)
      wall = _time.perf_counter() - t0
      if ck is not None:
        ck.flush()
      return wall, np.asarray(losses1), tr, ck

    base_wall, rc_losses1, _, _ = rc_epoch()
    c0 = glt_metrics.default_registry().counters()
    rc_dir = tempfile.mkdtemp(prefix='glt_ckpt_')
    ck_wall, _, _, rc_ck = rc_epoch(ckpt_dir=rc_dir)
    rc_ck.close()
    c1 = glt_metrics.default_registry().counters()
    saves = c1.get('checkpoint.saves', 0) - c0.get('checkpoint.saves', 0)
    sbytes = c1.get('checkpoint.bytes', 0) - c0.get(
        'checkpoint.bytes', 0)
    # kill at the chunk after the first cadence write, then resume in
    # a FRESH trainer: bit-identity vs the uninterrupted first epoch
    rc_kill = rc_every + 1
    rc_dir2 = tempfile.mkdtemp(prefix='glt_ckpt_kill_')
    _, _, _, _ = rc_epoch(ckpt_dir=rc_dir2, kill_chunk=rc_kill)
    import jax as _jax
    tmpl_state, rc_tx = _train_lib.create_train_state(
        rc_model, _jax.random.PRNGKey(1), rc_tmpl)
    rc_fresh = glt.loader.ScanTrainer(rc_build(), rc_model, rc_tx,
                                      E2E_CLASSES, chunk_size=rc_k)
    rc_resumer = ChunkCheckpointer(rc_dir2)
    snap = rc_resumer.latest()
    _, rl, _ = rc_resumer.resume_epoch(rc_fresh, tmpl_state,
                                       snapshot=snap)
    assert np.array_equal(rl, rc_losses1), 'resume diverged'
    result['checkpoint_save_ms_p99'] = round(
        glt_metrics.histogram('checkpoint.save_ms')
        .percentiles()['p99'], 3)
    result['checkpoint_bytes'] = int(sbytes / max(1, saves))
    result['resume_replay_chunks'] = rc_kill - (snap.next_start // rc_k)
    result['recovery_overhead_pct'] = round(
        100.0 * (ck_wall - base_wall) / base_wall, 2)
    result['recovery_config'] = (
        f'N={rc_n}, deg={rc_deg}, F={rc_f}, batch {rc_batch} x '
        f'{rc_steps} steps, K={rc_k}, cadence {rc_every} chunks, '
        f'kill at chunk {rc_kill}, resume bit-identical')
  except Exception as e:
    result['recovery_overhead_pct'] = None
    result['recovery_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- chunk-staged remote scan (distributed/remote_scan.py) ----
  # The decoupled-topology gate (docs/remote_scan.md): a server-client
  # epoch over K-batch blocks (in-process RPC server — a CPU replica
  # of the sampling cluster) vs the collocated DistScanTrainer epoch
  # at the same scale: same seeds-per-step grid, fanouts, feature
  # width and model. Both walls time a WARMED epoch (compiles
  # amortized — the steady-state production shape). Fetch-bearing on
  # the server side only; the client epoch stays dispatch-clean.
  try:
    import jax.numpy as jnp
    import optax
    from benchmarks.bench_dist_loader import make_dist_fixture
    from graphlearn_tpu import metrics as glt_metrics
    from graphlearn_tpu.distributed import dist_client
    from graphlearn_tpu.distributed.dist_server import DistServer
    from graphlearn_tpu.distributed.rpc import RpcServer
    from graphlearn_tpu.models import GraphSAGE as _RSAGE
    from graphlearn_tpu.models import train as _rtrain
    rs_n, rs_deg, rs_f = 100_000, 10, 32
    rs_batch, rs_steps, rs_k, rs_classes = 256, 16, 4, 16
    rs_fanouts = [10, 5]
    rs_rng = np.random.default_rng(29)
    rs_rows = rs_rng.integers(0, rs_n, rs_n * rs_deg)
    rs_cols = rs_rng.integers(0, rs_n, rs_n * rs_deg)
    rs_feat = rs_rng.standard_normal((rs_n, rs_f)).astype(np.float32)
    rs_labels = rs_rng.integers(0, rs_classes, rs_n)
    rs_seeds = rs_rng.integers(0, rs_n, rs_batch * rs_steps)

    rs_ds = glt.data.Dataset()
    rs_ds.init_graph(np.stack([rs_rows, rs_cols]), graph_mode='CPU',
                     num_nodes=rs_n)
    rs_ds.init_node_features(rs_feat)
    rs_ds.init_node_labels(rs_labels)
    rs_srv = DistServer(rs_ds)
    rs_rpc = RpcServer(handlers={
        'create_block_producer': rs_srv.create_block_producer,
        'block_producer_num_batches': rs_srv.block_producer_num_batches,
        'block_produce': rs_srv.block_produce,
        'block_fetch': rs_srv.block_fetch,
        'destroy_block_producer': rs_srv.destroy_block_producer,
        'heartbeat': rs_srv.heartbeat,
        'exit': rs_srv.exit})
    dist_client.init_client(1, 1, 0, [(rs_rpc.host, rs_rpc.port)])
    rs_trainer = None
    try:
      rs_model = _RSAGE(hidden_dim=64, out_dim=rs_classes, num_layers=2)
      rs_tx = optax.adam(1e-3)
      rs_loader = glt.loader.NeighborLoader(
          rs_ds, rs_fanouts, rs_seeds, batch_size=rs_batch,
          shuffle=False)
      rs_template = _rtrain.batch_to_dict(next(iter(rs_loader)))
      rs_state, _ = _rtrain.create_train_state(
          rs_model, jax.random.PRNGKey(0), rs_template, optimizer=rs_tx)
      rs_opts = glt.distributed.RemoteDistSamplingWorkerOptions(
          server_rank=0)
      rs_trainer = glt.distributed.RemoteScanTrainer(
          rs_fanouts, rs_seeds, rs_model, rs_tx, rs_classes,
          batch_size=rs_batch, chunk_size=rs_k, worker_options=rs_opts,
          seed=0)
      rs_state, _, _ = rs_trainer.run_epoch(rs_state)     # warm epoch
      glt_metrics.reset('remote.')
      with glt.utils.count_dispatches() as rs_dc:
        rs_t0 = time.perf_counter()
        rs_state, rs_losses, _ = rs_trainer.run_epoch(rs_state)
        np.asarray(rs_losses)                             # drain
        rs_wall = time.perf_counter() - rs_t0
    finally:
      # shutdown BEFORE the client/server teardown, and also on a
      # failed section: a leaked heartbeat/stager thread would probe a
      # None client for the rest of the bench run
      if rs_trainer is not None:
        rs_trainer.shutdown()
      dist_client._client.close()
      dist_client._client = None
      rs_srv.exit()
      rs_rpc.shutdown()
    result['remote_scan_epoch_wall_s'] = round(rs_wall, 3)
    result['remote_scan_epoch_dispatches'] = sum(
        v for s, v in rs_dc.counts.items() if s.startswith('remote_'))
    pct = glt_metrics.histogram('remote.block_stage_ms').percentiles()
    if pct.get('p99') is not None:
      result['remote_block_stage_ms_p99'] = round(pct['p99'], 3)

    # collocated DistScanTrainer at the same scale: dp_ shards whose
    # per-shard batch keeps the global seeds-per-step grid equal
    rs_p = min(8, max(1, len(jax.devices())))
    while rs_batch % rs_p:
      rs_p -= 1
    _, rs_dds, rs_mesh = make_dist_fixture(
        rs_rows, rs_cols, rs_n, rs_p, feat_dim=rs_f, split_ratio=0.2,
        labels=rs_labels, feat_rng=rs_rng)
    rs_dloader = glt.distributed.DistNeighborLoader(
        rs_dds, rs_fanouts, rs_seeds, batch_size=rs_batch // rs_p,
        shuffle=False, drop_last=True, seed=0, mesh=rs_mesh)
    rs_dtrainer = glt.loader.DistScanTrainer(
        rs_dloader, rs_model, rs_tx, rs_classes, chunk_size=rs_k)
    rs_first = next(iter(rs_dloader))
    rs_dparams = rs_model.init(jax.random.PRNGKey(0),
                               np.asarray(rs_first.x)[0],
                               np.asarray(rs_first.edge_index)[0],
                               np.asarray(rs_first.edge_mask)[0])
    rs_dstate = _rtrain.TrainState(rs_dparams, rs_tx.init(rs_dparams),
                                   jnp.zeros((), jnp.int32))
    rs_dstate, _, _ = rs_dtrainer.run_epoch(rs_dstate)    # warm epoch
    rs_t0 = time.perf_counter()
    rs_dstate, rs_dlosses, _ = rs_dtrainer.run_epoch(rs_dstate)
    np.asarray(rs_dlosses)                                # drain
    rs_dwall = time.perf_counter() - rs_t0
    result['remote_vs_collocated_ratio'] = round(
        rs_wall / max(rs_dwall, 1e-9), 3)
    result['remote_scan_config'] = (
        f'N={rs_n}, deg={rs_deg}, F={rs_f}, fanouts {rs_fanouts}, '
        f'batch {rs_batch} x {rs_steps} steps, K={rs_k}; 1 in-proc '
        f'server (CPU replica) vs collocated mesh P={rs_p}')
  except Exception as e:
    result['remote_scan_epoch_wall_s'] = None
    result['remote_scan_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- hetero at scanned speed: typed remote block streams ----
  # The ISSUE 19 gate (docs/capacity_plans.md): the chunk-staged remote
  # epoch on TYPED block streams vs the per-batch remote hetero path —
  # the path hetero workloads were stuck on before CapacityPlans. Both
  # arms are bit-identical by contract (asserted below), both time a
  # WARMED second epoch, and the scanned arm must hold the homo
  # dispatch budget (ceil(steps/K) + 2). CPU replica of the sampling
  # cluster; the on-chip figures land with the TPU relay.
  try:
    import optax
    from graphlearn_tpu.distributed import dist_client
    from graphlearn_tpu.distributed.dist_server import DistServer
    from graphlearn_tpu.distributed.rpc import RpcServer
    from graphlearn_tpu.models import RGNN as _HRGNN
    from graphlearn_tpu.models import train as _htrain
    from graphlearn_tpu.typing import reverse_edge_type as _rev_et
    hs_ub = ('user', 'buys', 'item')
    hs_bu = ('item', 'rev_buys', 'user')
    hs_nu, hs_ni, hs_deg, hs_f = 20_000, 10_000, 8, 16
    hs_batch, hs_steps, hs_k, hs_classes = 128, 8, 4, 8
    hs_fanouts = {hs_ub: [4, 3], hs_bu: [4, 3]}
    hs_rng = np.random.default_rng(31)
    hs_rows = hs_rng.integers(0, hs_nu, hs_nu * hs_deg)
    hs_cols = hs_rng.integers(0, hs_ni, hs_nu * hs_deg)
    hs_ub_ei = np.stack([hs_rows, hs_cols])
    hs_seeds = hs_rng.integers(0, hs_nu, hs_batch * hs_steps)

    hs_ds = glt.data.Dataset(edge_dir='out')
    hs_ds.init_graph({hs_ub: hs_ub_ei, hs_bu: hs_ub_ei[::-1].copy()},
                     graph_mode='CPU',
                     num_nodes={hs_ub: hs_nu, hs_bu: hs_ni})
    hs_ds.init_node_features(
        {'user': hs_rng.standard_normal((hs_nu, hs_f)).astype(
            np.float32),
         'item': hs_rng.standard_normal((hs_ni, hs_f)).astype(
             np.float32)})
    hs_ds.init_node_labels(
        {'user': hs_rng.integers(0, hs_classes, hs_nu)})

    def _hs_to_dict(b):
      nsn = np.asarray(b.num_sampled_nodes['user']).reshape(-1)
      return dict(x=dict(b.x), edge_index=dict(b.edge_index),
                  edge_mask=dict(b.edge_mask), y=b.y['user'],
                  num_seed_nodes=nsn[0])

    hs_srv = DistServer(hs_ds)
    hs_rpc = RpcServer(handlers={
        'create_sampling_producer': hs_srv.create_sampling_producer,
        'producer_num_expected': hs_srv.producer_num_expected,
        'start_new_epoch_sampling': hs_srv.start_new_epoch_sampling,
        'fetch_one_sampled_message': hs_srv.fetch_one_sampled_message,
        'destroy_sampling_producer': hs_srv.destroy_sampling_producer,
        'create_block_producer': hs_srv.create_block_producer,
        'block_producer_num_batches':
            hs_srv.block_producer_num_batches,
        'block_produce': hs_srv.block_produce,
        'block_fetch': hs_srv.block_fetch,
        'destroy_block_producer': hs_srv.destroy_block_producer,
        'get_dataset_meta': hs_srv.get_dataset_meta,
        'heartbeat': hs_srv.heartbeat,
        'get_metrics': hs_srv.get_metrics,
        'exit': hs_srv.exit})
    dist_client.init_client(1, 1, 0, [(hs_rpc.host, hs_rpc.port)])
    hs_trainer = hs_loader = None
    try:
      hs_model = _HRGNN(etypes=(_rev_et(hs_ub), _rev_et(hs_bu)),
                        hidden_dim=32, out_dim=hs_classes,
                        num_layers=2, out_ntype='user')
      hs_tx = optax.adam(1e-3)
      hs_local = glt.loader.NeighborLoader(
          hs_ds, hs_fanouts, ('user', hs_seeds), batch_size=hs_batch,
          shuffle=False)
      hs_template = _hs_to_dict(next(iter(hs_local)))
      hs_state_pb, _ = _htrain.create_train_state(
          hs_model, jax.random.PRNGKey(0), hs_template,
          optimizer=hs_tx)

      # per-batch remote hetero arm (1 worker / prefetch 1: the only
      # deterministically-ordered per-batch configuration)
      hs_opts = glt.distributed.RemoteDistSamplingWorkerOptions(
          server_rank=0, num_workers=1, prefetch_size=1)
      hs_loader = glt.distributed.RemoteDistNeighborLoader(
          hs_fanouts, ('user', hs_seeds), batch_size=hs_batch,
          collect_features=True, worker_options=hs_opts, seed=0)
      hs_step, _ = _htrain.make_train_step(hs_model, hs_tx,
                                           hs_classes)
      for b in hs_loader:                                # warm epoch
        hs_state_pb, _, _ = hs_step(hs_state_pb, _hs_to_dict(b))
      hs_pb_losses = []
      hs_t0 = time.perf_counter()
      for b in hs_loader:
        hs_state_pb, loss, _ = hs_step(hs_state_pb, _hs_to_dict(b))
        hs_pb_losses.append(np.asarray(loss))
      hs_pb_wall = time.perf_counter() - hs_t0
      hs_loader.shutdown()
      hs_loader = None

      # typed chunk-staged arm from an identically initialized state
      hs_state_sc, _ = _htrain.create_train_state(
          hs_model, jax.random.PRNGKey(0), hs_template,
          optimizer=hs_tx)
      hs_trainer = glt.distributed.RemoteScanTrainer(
          hs_fanouts, ('user', hs_seeds), hs_model, hs_tx, hs_classes,
          batch_size=hs_batch, chunk_size=hs_k, seed=0,
          worker_options=glt.distributed
          .RemoteDistSamplingWorkerOptions(server_rank=0))
      hs_state_sc, _, _ = hs_trainer.run_epoch(hs_state_sc)  # warm
      with glt.utils.count_dispatches() as hs_dc:
        hs_t0 = time.perf_counter()
        hs_state_sc, hs_sc_losses, _ = hs_trainer.run_epoch(
            hs_state_sc)
        hs_sc_losses = np.asarray(hs_sc_losses)           # drain
        hs_sc_wall = time.perf_counter() - hs_t0
    finally:
      if hs_loader is not None:
        hs_loader.shutdown()
      if hs_trainer is not None:
        hs_trainer.shutdown()
      dist_client._client.close()
      dist_client._client = None
      hs_srv.exit()
      hs_rpc.shutdown()
    result['hetero_scan_epoch_wall_s'] = round(hs_sc_wall, 3)
    result['hetero_scan_per_batch_wall_s'] = round(hs_pb_wall, 3)
    result['hetero_scan_vs_per_batch_ratio'] = round(
        hs_sc_wall / max(hs_pb_wall, 1e-9), 3)
    result['hetero_scan_epoch_dispatches'] = sum(
        v for s, v in hs_dc.counts.items() if s.startswith('remote_'))
    result['hetero_scan_bit_identical'] = bool(np.array_equal(
        hs_sc_losses, np.asarray(hs_pb_losses).reshape(-1)))
    result['hetero_scan_config'] = (
        f'bipartite {hs_nu}u x {hs_ni}i, deg={hs_deg}, F={hs_f}, '
        f'2 etypes, fanouts [4,3]/[4,3], batch {hs_batch} x '
        f'{hs_steps} steps, K={hs_k}; 1 in-proc server (CPU replica), '
        'typed block streams vs per-batch remote hetero')
  except Exception as e:
    result['hetero_scan_epoch_wall_s'] = None
    result['hetero_scan_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- hetero per-ntype tiered exchange (storage/dist_scan.py) ----
  # The typed dist_oversub contract: TieredDistScanTrainer over
  # per-ntype TieredDistFeature stores (per-ntype hot prefixes +
  # staged exchange slabs, one spill dir per ntype) vs the identical
  # all-HBM hetero DistScanTrainer epoch — bit-identical losses, wall
  # ratio gated at the homo dist_oversub bar (~1.5x).
  try:
    import tempfile as _ht_tempfile

    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh as _HTMesh

    from graphlearn_tpu.models import RGNN as _HRGNN
    from graphlearn_tpu.models import train as _htrain
    from graphlearn_tpu.storage import (TieredDistFeature,
                                        TieredDistScanTrainer)
    from graphlearn_tpu.typing import GraphPartitionData as _HTGPD
    from graphlearn_tpu.typing import reverse_edge_type as _rev_et
    ht_e1, ht_e2 = ('u', 'to', 'v'), ('v', 'back', 'u')
    ht_n, ht_p, ht_f, ht_hot = 4_000, 2, 16, 256
    ht_batch, ht_steps, ht_k, ht_classes = 32, 8, 4, 8
    ht_fanouts = {ht_e1: [4, 3], ht_e2: [3, 2]}
    ht_rng = np.random.default_rng(37)
    ht_r1 = ht_rng.integers(0, ht_n, ht_n * 6)
    ht_c1 = ht_rng.integers(0, ht_n, ht_n * 6)
    ht_r2 = ht_rng.integers(0, ht_n, ht_n * 4)
    ht_c2 = ht_rng.integers(0, ht_n, ht_n * 4)
    ht_pb = {'u': (np.arange(ht_n) % ht_p).astype(np.int32),
             'v': ((np.arange(ht_n) + 1) % ht_p).astype(np.int32)}
    ht_parts = []
    for p in range(ht_p):
      m1 = ht_pb['u'][ht_r1] == p
      m2 = ht_pb['v'][ht_r2] == p
      ht_parts.append({
          ht_e1: _HTGPD(
              edge_index=np.stack([ht_r1[m1], ht_c1[m1]]),
              eids=np.arange(ht_r1.shape[0])[m1]),
          ht_e2: _HTGPD(
              edge_index=np.stack([ht_r2[m2], ht_c2[m2]]),
              eids=np.arange(ht_r2.shape[0])[m2])})
    ht_feat = {t: ht_rng.standard_normal((ht_n, ht_f)).astype(
        np.float32) for t in ('u', 'v')}
    ht_stores = {t: [(np.nonzero(ht_pb[t] == p)[0],
                      ht_feat[t][ht_pb[t] == p])
                     for p in range(ht_p)] for t in ('u', 'v')}
    ht_labels = {t: ht_rng.integers(0, ht_classes, ht_n)
                 for t in ('u', 'v')}
    ht_seeds = ht_rng.integers(0, ht_n, ht_p * ht_batch * ht_steps)
    ht_mesh = _HTMesh(np.array(jax.devices()[:ht_p]), ('g',))

    def _ht_loader(tiered):
      dg = glt.distributed.DistHeteroGraph(ht_p, 0, ht_parts, ht_pb)
      if tiered:
        base = _ht_tempfile.mkdtemp(prefix='glt_bench_htiered_')
        df = {t: TieredDistFeature(
            ht_p, ht_stores[t], ht_pb[t], mesh=ht_mesh,
            spill_dir=os.path.join(base, t), hot_prefix_rows=ht_hot,
            split_ratio=0.25) for t in ('u', 'v')}
      else:
        df = {t: glt.distributed.DistFeature(
            ht_p, ht_stores[t], ht_pb[t], ht_mesh, split_ratio=0.25)
            for t in ('u', 'v')}
      ds = glt.distributed.DistDataset(ht_p, 0, dg, df,
                                       node_labels=ht_labels)
      return glt.distributed.DistNeighborLoader(
          ds, ht_fanouts, ('u', ht_seeds), batch_size=ht_batch,
          shuffle=False, drop_last=False, seed=0, mesh=ht_mesh)

    ht_model = _HRGNN(etypes=(_rev_et(ht_e1), _rev_et(ht_e2)),
                      hidden_dim=32, out_dim=ht_classes, num_layers=2,
                      out_ntype='u')
    ht_tx = optax.adam(1e-3)

    def _ht_state():
      first = next(iter(_ht_loader(False)))
      one = lambda d: {k: np.asarray(v)[0] for k, v in d.items()}
      params = ht_model.init(jax.random.PRNGKey(0), one(first.x),
                             one(first.edge_index),
                             one(first.edge_mask))
      return _htrain.TrainState(params, ht_tx.init(params),
                                jnp.int32(0))

    ht_ref = glt.loader.DistScanTrainer(_ht_loader(False), ht_model,
                                        ht_tx, ht_classes,
                                        chunk_size=ht_k)
    ht_rstate = _ht_state()
    ht_rstate, _, _ = ht_ref.run_epoch(ht_rstate)         # warm epoch
    ht_t0 = time.perf_counter()
    ht_rstate, ht_rlosses, _ = ht_ref.run_epoch(ht_rstate)
    ht_rlosses = np.asarray(ht_rlosses)                   # drain
    ht_hbm_wall = time.perf_counter() - ht_t0

    ht_tr = TieredDistScanTrainer(_ht_loader(True), ht_model, ht_tx,
                                  ht_classes, chunk_size=ht_k)
    ht_tstate = _ht_state()
    ht_tstate, _, _ = ht_tr.run_epoch(ht_tstate)          # warm epoch
    ht_t0 = time.perf_counter()
    ht_tstate, ht_tlosses, _ = ht_tr.run_epoch(ht_tstate)
    ht_tlosses = np.asarray(ht_tlosses)                   # drain
    ht_tiered_wall = time.perf_counter() - ht_t0
    ht_tr.close()

    result['hetero_tiered_epoch_wall_s'] = round(ht_tiered_wall, 3)
    result['hetero_tiered_hbm_epoch_wall_s'] = round(ht_hbm_wall, 3)
    result['hetero_tiered_ratio'] = round(
        ht_tiered_wall / max(ht_hbm_wall, 1e-9), 3)
    result['hetero_tiered_bit_identical'] = bool(
        np.array_equal(ht_tlosses, ht_rlosses))
    result['hetero_tiered_config'] = (
        f'2 ntypes x {ht_n} nodes, 2 etypes, F={ht_f}, mesh P={ht_p}, '
        f'hot prefix {ht_hot} rows/ntype + per-ntype spill dirs, '
        f'fanouts [4,3]/[3,2], batch {ht_batch}/shard x {ht_steps} '
        f'steps, K={ht_k}')
  except Exception as e:
    result['hetero_tiered_epoch_wall_s'] = None
    result['hetero_tiered_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- multi-tenant fairness (distributed/tenancy.py) ----
  # The service-fabric gate (docs/multi_tenancy.md): one in-process
  # server with admission control + the weighted-fair block lane,
  # tenants trainA (w=2) and trainB (w=1) saturating it while an
  # interactive probe rides on top. Measures (a) DWRR fidelity — each
  # training tenant's block-throughput share vs its weight share,
  # (b) strict priority — the probe's p99 under contention vs solo,
  # and (c) visible backpressure — throttle rejections per produce-
  # ahead op against a one-frame in-flight quota with a lagging drain.
  # Raw block RPCs only (no trainers, no device work): the server lane
  # is the contended resource being characterized.
  try:
    import queue as _tn_queue
    import threading as _tn_threading

    from graphlearn_tpu.distributed import dist_client
    from graphlearn_tpu.distributed.dist_loader import _norm_num_neighbors
    from graphlearn_tpu.distributed.dist_server import DistServer
    from graphlearn_tpu.distributed.rpc import RpcServer
    from graphlearn_tpu.distributed.tenancy import (
        TenancyConfig, TenantSpec, with_backpressure)
    from graphlearn_tpu.sampler import SamplingConfig, SamplingType
    from graphlearn_tpu.utils import trace as _tn_trace

    tn_n, tn_deg, tn_f = 20_000, 10, 16
    tn_batch, tn_k, tn_steps = 64, 2, 40
    tn_fanouts = [5, 5]
    tn_rng = np.random.default_rng(31)
    tn_ds = glt.data.Dataset()
    tn_ds.init_graph(
        np.stack([tn_rng.integers(0, tn_n, tn_n * tn_deg),
                  tn_rng.integers(0, tn_n, tn_n * tn_deg)]),
        graph_mode='CPU', num_nodes=tn_n)
    tn_ds.init_node_features(
        tn_rng.standard_normal((tn_n, tn_f)).astype(np.float32))
    tn_ds.init_node_labels(tn_rng.integers(0, 8, tn_n))

    tn_weights = {'trainA': 2.0, 'trainB': 1.0}
    tn_srv = DistServer(tn_ds, tenancy=TenancyConfig(specs=[
        TenantSpec(tenant='trainA', priority='training', weight=2.0),
        TenantSpec(tenant='trainB', priority='training', weight=1.0),
        TenantSpec(tenant='ui', priority='interactive'),
        TenantSpec(tenant='bulkq', priority='bulk',
                   max_inflight_bytes=1)]))
    tn_rpc = RpcServer(handlers={
        'create_block_producer': tn_srv.create_block_producer,
        'block_produce': tn_srv.block_produce,
        'block_fetch': tn_srv.block_fetch,
        'destroy_block_producer': tn_srv.destroy_block_producer,
        'heartbeat': tn_srv.heartbeat,
        'exit': tn_srv.exit})
    dist_client.init_client(1, 1, 0, [(tn_rpc.host, tn_rpc.port)])
    tn_pids = {}
    try:
      tn_cfg = SamplingConfig(
          SamplingType.NODE, _norm_num_neighbors(tn_fanouts), tn_batch,
          False, False, False, True, False, False, 'out', 0)
      tn_seeds = tn_rng.integers(0, tn_n, tn_batch * tn_steps)
      for tenant, prio in (('trainA', 'training'),
                           ('trainB', 'training'),
                           ('ui', 'interactive'), ('bulkq', 'bulk')):
        tn_pids[tenant] = dist_client.request_server(
            0, 'create_block_producer', tn_seeds, tn_cfg, None,
            worker_key=f'bench/tn/{tenant}', tenant=tenant,
            priority=prio)
      tn_blocks = tn_steps // tn_k
      tn_errors = []

      def _tn_cycle(tenant, cursor):
        # one counter-addressed produce+fetch; the epoch wraps so a
        # worker can cycle the stream for as long as the phase runs
        ep, blk = divmod(cursor, tn_blocks)
        pid = tn_pids[tenant]
        with_backpressure(
            lambda: dist_client.request_server(
                0, 'block_produce', pid, ep, blk * tn_k, tn_k),
            describe=f'bench produce {tenant}', tenant=tenant)
        dist_client.request_server(
            0, 'block_fetch', pid, ep, blk * tn_k, tn_k)

      def _tn_pound(tenant, counts, offset, stride, stop):
        cursor = offset
        try:
          while not stop.is_set():
            _tn_cycle(tenant, cursor)
            counts[(tenant, offset)] += tn_k   # thread-private cell
            cursor += stride
        except Exception as e:
          tn_errors.append(e)

      def _tn_probe(lats, stop):
        cursor = 0
        try:
          while not stop.is_set():
            t0 = time.perf_counter()
            _tn_cycle('ui', cursor)
            lats.append((time.perf_counter() - t0) * 1e3)
            cursor += 1
            time.sleep(0.02)
        except Exception as e:
          tn_errors.append(e)

      def _tn_run(specs, seconds):
        stop = _tn_threading.Event()
        ts = [_tn_threading.Thread(target=fn, args=args + (stop,),
                                   daemon=True) for fn, args in specs]
        for t in ts:
          t.start()
        time.sleep(seconds)
        stop.set()
        for t in ts:
          t.join(timeout=60)
        if tn_errors:
          raise tn_errors[0]

      # solo: the interactive probe with the lane to itself
      tn_solo = []
      _tn_run([(_tn_probe, (tn_solo,))], 1.0)
      # contended: four saturating threads per training tenant (equal
      # offered load, deep enough that each tenant keeps a persistent
      # backlog — DRR shapes queued work, not arrivals) with the probe
      # riding on top
      tn_threads = 4
      tn_counts = {(t, i): 0 for t in tn_weights
                   for i in range(tn_threads)}
      tn_cont = []
      _tn_run([(_tn_pound, (t, tn_counts, i, tn_threads))
               for t in tn_weights for i in range(tn_threads)]
              + [(_tn_probe, (tn_cont,))], 4.0)
      if not tn_solo or not tn_cont:
        raise RuntimeError('interactive probe completed no cycles')
      tn_served = {t: sum(v for (tt, _), v in tn_counts.items()
                          if tt == t) for t in tn_weights}
      tn_total = sum(tn_served.values())
      tn_wsum = sum(tn_weights.values())
      tn_spread = max(
          abs(tn_served[t] / tn_total - tn_weights[t] / tn_wsum)
          / (tn_weights[t] / tn_wsum) for t in tn_weights)
      tn_solo99 = float(np.percentile(tn_solo, 99))
      tn_cont99 = float(np.percentile(tn_cont, 99))

      # visible backpressure: produce-ahead into bulkq's one-frame
      # quota; the drain thread fetches each staged block 30ms late,
      # so every produce after the first meets the quota, throttles,
      # and retries inside with_backpressure (never a timeout)
      tn_base = _tn_trace.counter_get('tenant.throttled')
      tn_attempts = min(16, tn_blocks)
      tn_q = _tn_queue.Queue()

      def _tn_drain():
        try:
          while True:
            i = tn_q.get(timeout=60)
            if i is None:
              return
            time.sleep(0.03)
            dist_client.request_server(
                0, 'block_fetch', tn_pids['bulkq'], 0, i * tn_k, tn_k)
        except Exception as e:
          tn_errors.append(e)

      tn_dr = _tn_threading.Thread(target=_tn_drain, daemon=True)
      tn_dr.start()
      for i in range(tn_attempts):
        with_backpressure(
            lambda i=i: dist_client.request_server(
                0, 'block_produce', tn_pids['bulkq'], 0, i * tn_k,
                tn_k),
            describe='bench produce bulkq', tenant='bulkq')
        tn_q.put(i)
      tn_q.put(None)
      tn_dr.join(timeout=60)
      if tn_errors:
        raise tn_errors[0]
      tn_throttled = _tn_trace.counter_get('tenant.throttled') - tn_base
    finally:
      for pid in tn_pids.values():
        try:
          dist_client.request_server(0, 'destroy_block_producer', pid)
        except Exception:
          pass
      dist_client._client.close()
      dist_client._client = None
      tn_srv.exit()
      tn_rpc.shutdown()
    result['tenant_fairness_spread'] = round(tn_spread, 3)
    result['tenant_p99_degradation_ms'] = round(
        max(0.0, tn_cont99 - tn_solo99), 3)
    result['tenant_throttle_rate'] = round(tn_throttled / tn_attempts, 3)
    result['tenant_config'] = (
        f'N={tn_n}, deg={tn_deg}, F={tn_f}, fanouts {tn_fanouts}, '
        f'batch {tn_batch}, K={tn_k}; trainA w=2 + trainB w=1 '
        f'({tn_threads} threads each) + interactive probe, 4s '
        f'contention; 1-frame quota x {tn_attempts} produce-ahead ops')
  except Exception as e:
    result['tenant_fairness_spread'] = None
    result['tenancy_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- serving tier (PR 7): offline materialization + online QPS ----
  # The serving sections run LAST by design: the serving path fetches
  # rows per batch (that IS the product — e2e latency includes the
  # fetch), and on the axon runtime the first fetch degrades later
  # dispatches (PERF.md), so nothing dispatch-sensitive may run after
  # this point (the rotation section below is serving-tier too).
  # A smaller dedicated graph keeps the padded full-neighbor table
  # bounded; the config key records the shape.
  try:
    import threading

    from graphlearn_tpu import metrics as glt_metrics
    from graphlearn_tpu.models import GraphSAGE
    from graphlearn_tpu.serving import EmbeddingMaterializer, ServingEngine
    sv_n, sv_deg, sv_f = 200_000, 8, 64
    sv_rng = np.random.default_rng(11)
    sv_rows = np.repeat(np.arange(sv_n), sv_deg)
    sv_cols = sv_rng.integers(0, sv_n, sv_rows.shape[0])
    sv_ds = glt.data.Dataset()
    sv_ds.init_graph(np.stack([sv_rows, sv_cols]), graph_mode='CPU',
                     num_nodes=sv_n)
    sv_ds.init_node_features(
        sv_rng.standard_normal((sv_n, sv_f)).astype(np.float32))
    sv_model = GraphSAGE(hidden_dim=128, out_dim=64, num_layers=2)
    sv_x0 = sv_ds.node_features.feature_array[:64]
    sv_ei0 = np.stack([np.arange(64, dtype=np.int32),
                       np.arange(64, dtype=np.int32)])
    sv_params = sv_model.init(jax.random.PRNGKey(0), sv_x0, sv_ei0,
                              np.ones(64, bool))
    mat = EmbeddingMaterializer(sv_ds, sv_model, sv_params,
                                block_size=1024, chunk_size=16,
                                neighbor_cap=sv_deg)
    from graphlearn_tpu.utils import count_dispatches
    with count_dispatches() as sv_dc:
      t0 = time.perf_counter()
      sv_emb = mat.materialize()
      jax.block_until_ready(sv_emb)
      sv_wall = time.perf_counter() - t0
    result['embed_epoch_wall_s'] = round(sv_wall, 3)
    result['embed_epoch_dispatches'] = sv_dc.total
    # online endpoint: sustained concurrent lookups for ~2s
    glt_metrics.reset('serving')
    engine = ServingEngine(mat.embedding_store(),
                           buckets=(64, 256, 1024), max_wait_ms=1.0)
    sv_stop = time.perf_counter() + 2.0
    sv_done = []
    sv_errs = []

    def sv_client(seed):
      # exceptions must reach the section's error record — a dead
      # client thread would otherwise record 7/8 traffic as a clean
      # (regressed-looking) QPS/latency round
      try:
        crng = np.random.default_rng(seed)
        n_ok = 0
        while time.perf_counter() < sv_stop:
          ids = crng.integers(0, sv_n, 16)
          engine.lookup(ids)
          n_ok += 1
        sv_done.append(n_ok)
      except BaseException as e:  # noqa: BLE001
        sv_errs.append(e)

    with engine:
      sv_t0 = time.perf_counter()
      threads = [threading.Thread(target=sv_client, args=(i,))
                 for i in range(8)]
      for th in threads:
        th.start()
      for th in threads:
        th.join()
      sv_span = time.perf_counter() - sv_t0
    if sv_errs:
      raise RuntimeError(f'{len(sv_errs)}/8 serving clients failed: '
                         f'{sv_errs[0]!r}')
    n_req = sum(sv_done)
    n_chips = max(len(jax.devices()), 1)
    result['serving_qps_per_chip'] = round(n_req / sv_span / n_chips, 1)
    pct = glt_metrics.histogram('serving.total_ms').percentiles()
    result['serving_p50_ms'] = round(pct['p50'], 3)
    result['serving_p99_ms'] = round(pct['p99'], 3)
    result['serving_config'] = (
        f'N={sv_n}, deg={sv_deg}, F={sv_f}, 2-layer SAGE h128->64, '
        'block 1024 x K16; 8 clients x 16-id lookups, buckets '
        '(64, 256, 1024), max_wait 1ms')
  except Exception as e:
    result['serving_error'] = f'{type(e).__name__}: {e}'[:200]

  # ---- zero-downtime sharded store rotation (serving/rotation.py) ----
  # The tentpole's serving half: rotate a RotatingShardedStore through
  # several materialized versions under live threaded traffic —
  # every request must be answered exactly once from ONE consistent
  # version, and the gate pair is the swap critical section's p99 and
  # the failed-request count (0, the zero-downtime contract).
  try:
    import tempfile
    import threading

    from graphlearn_tpu import metrics as glt_metrics
    from graphlearn_tpu.serving import RotatingShardedStore, ServingEngine
    rot_n, rot_f, rot_shards = 50_000, 64, 4
    rot_rng = np.random.default_rng(13)
    rot_base = rot_rng.standard_normal((rot_n, rot_f)).astype(np.float32)

    def rot_table(v):
      # version-tagged tables so a torn read would be detectable
      return rot_base + np.float32(v)

    glt_metrics.reset('serving.rotation')
    rot_root = tempfile.mkdtemp(prefix='glt_rotation_')
    rot_store = RotatingShardedStore(rot_root, rot_shards, rot_table(0),
                                     warm_rows=1024)
    rot_engine = ServingEngine(rot_store, buckets=(64, 256),
                               max_wait_ms=1.0)
    rot_stop = time.perf_counter() + 2.0
    rot_done, rot_errs = [], []

    def rot_client(seed):
      try:
        crng = np.random.default_rng(seed)
        n_ok = 0
        while time.perf_counter() < rot_stop:
          ids = crng.integers(0, rot_n, 16)
          rows = rot_engine.lookup(ids)
          # consistency probe: one version across the whole response
          vs = np.unique(np.round(rows[:, 0] - rot_base[ids, 0]))
          assert vs.size == 1, f'torn read across versions: {vs}'
          n_ok += 1
        rot_done.append(n_ok)
      except BaseException as e:  # noqa: BLE001
        rot_errs.append(e)

    with rot_engine:
      threads = [threading.Thread(target=rot_client, args=(i,))
                 for i in range(6)]
      for th in threads:
        th.start()
      n_rot = 0
      while time.perf_counter() < rot_stop - 0.3:
        time.sleep(0.35)
        rot_store.rotate(lambda: rot_table(rot_store.version + 1))
        n_rot += 1
      for th in threads:
        th.join()
    result['rotation_failed_requests'] = len(rot_errs)
    if rot_errs:
      raise RuntimeError(f'{len(rot_errs)} rotation clients failed: '
                         f'{rot_errs[0]!r}')
    pct = glt_metrics.histogram('serving.rotation_swap_ms').percentiles()
    result['rotation_swap_ms_p99'] = round(pct['p99'], 3)
    result['rotation_config'] = (
        f'[{rot_n}, {rot_f}] f32 table, {rot_shards} shards (warm 1024 '
        f'rows/shard, rest mmap), {n_rot} rotations under 6 clients x '
        '16-id lookups for 2s, buckets (64, 256)')
  except Exception as e:
    result['rotation_error'] = f'{type(e).__name__}: {e}'[:200]

  # the final device->host fetch, after every trace is captured
  # (PERF.md: the first fetch degrades later dispatches).
  # null (not false) when the ref runs never produced a loader — a
  # failed run must not read as 'ran clean, no truncation'
  try:
    result['hetero_ref_overflow'] = (
        bool(any(ldr.check_overflow() for ldr in ref_loaders))
        if len(ref_loaders) == len(ref_convs) else None)   # all or null
  except Exception as e:
    result['hetero_ref_overflow'] = f'{type(e).__name__}'
  print(json.dumps(result))


if __name__ == '__main__':
  import os
  import sys
  if '--validate' in sys.argv[1:]:
    # schema check only: no jax, no device, no axon probe
    args = [a for a in sys.argv[1:] if a != '--validate']
    sys.exit(validate_bench_files(args))
  if '--gate' in sys.argv[1:]:
    # round-over-round regression gate: no jax, no device
    args = [a for a in sys.argv[1:] if a != '--gate']
    sys.exit(gate_bench_files(args))
  try:
    if os.environ.get('PALLAS_AXON_POOL_IPS') and not _axon_relay_up():
      # clearly down: fail fast with a parseable record instead of
      # letting the axon dial hang this process forever
      ports = ','.join(str(p) for p in _relay_ports())
      print(json.dumps(_error_record(
          'backend-probe',
          f'axon relay (127.0.0.1 port {ports}) refused connection — '
          'host-side TPU driver/relay is down; jax init would hang. '
          "Recovery is host-side (PERF.md 'TPU-host failure mode').")),
            flush=True)
    else:
      main()
  except Exception as e:                         # noqa: BLE001
    print(json.dumps(_error_record('main', f'{type(e).__name__}: {e}')),
          flush=True)
