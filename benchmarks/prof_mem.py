"""Microbenchmarks: which gather/scatter shapes are fast on this TPU?"""
import time
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial


def timeit(name, fn, iters=20, warmup=3, bytes_moved=None):
  for _ in range(warmup):
    r = fn()
  jax.block_until_ready(r)
  t0 = time.perf_counter()
  rs = [fn() for _ in range(iters)]
  jax.block_until_ready(rs)
  dt = (time.perf_counter() - t0) / iters
  bw = f'  {bytes_moved/dt/1e9:8.1f} GB/s' if bytes_moved else ''
  print(f'{name:55s} {dt*1e3:9.3f} ms{bw}')
  return dt


def main():
  rng = np.random.default_rng(0)
  N = 1_000_000
  B = 768_000

  t1d = jnp.asarray(rng.integers(0, 2**31, N).astype(np.int32))
  idx = jnp.asarray(rng.integers(0, N, B).astype(np.int32))
  idx_sorted = jnp.sort(idx)

  g = jax.jit(lambda t, i: t[i])
  timeit('A scalar gather 768k from [1M]', lambda: g(t1d, idx))
  gs = jax.jit(lambda t, i: t.at[i].get(indices_are_sorted=True))
  timeit('A2 scalar gather 768k sorted hint', lambda: gs(t1d, idx_sorted))

  # B: scalar gather via row gather + lane select
  t2d = t1d.reshape(N // 128, 128)
  def via_rows(t, i):
    r, l = i // 128, i % 128
    rows = t[r]                       # [B, 128] row gather
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (i.shape[0], 128), 1)
              == l[:, None])
    return jnp.sum(jnp.where(onehot, rows, 0), axis=1)
  vr = jax.jit(via_rows)
  timeit('B row-gather[8k,128]+lane-select 768k', lambda: vr(t2d, idx),
         bytes_moved=B * 512)
  np.testing.assert_array_equal(np.asarray(vr(t2d, idx)),
                                np.asarray(g(t1d, idx)))

  # C: feature-style row gather [150k, 128] from [1M, 128]
  feat = jnp.asarray(rng.standard_normal((N, 128)).astype(np.float32))
  ridx = jnp.asarray(rng.integers(0, N, 153600).astype(np.int32))
  rg = jax.jit(lambda t, i: t[i])
  timeit('C row gather 153k from [1M,128] f32', lambda: rg(feat, ridx),
         bytes_moved=153600 * 512)

  # D: scatter set 768k scalars into [1M]
  vals = jnp.arange(B, dtype=jnp.int32)
  sc = jax.jit(lambda t, i, v: t.at[i].set(v, mode='drop'))
  timeit('D scalar scatter 768k into [1M]', lambda: sc(t1d, idx, vals))

  # D2: row scatter [150k,128] into [1M,128]
  rvals = jnp.ones((153600, 128), jnp.float32)
  rsc = jax.jit(lambda t, i, v: t.at[i].set(v, mode='drop'))
  timeit('D2 row scatter 153k into [1M,128]', lambda: rsc(feat, ridx, rvals),
         bytes_moved=153600 * 512)

  # E: sort 768k int32
  st = jax.jit(jnp.sort)
  timeit('E sort 768k int32', lambda: st(idx))
  st2 = jax.jit(lambda x: jax.lax.sort_key_val(x, x)[0])
  timeit('E2 sort_key_val 768k', lambda: st2(idx))

  # F: cumsum 768k
  cs = jax.jit(jnp.cumsum)
  timeit('F cumsum 768k int32', lambda: cs(vals))

  # G: Pallas row gather from [1M, 128] via scalar-prefetch index map
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  ROWS_PER_STEP = 8

  def gather_kernel(idx_ref, tbl_ref, out_ref):
    out_ref[:] = tbl_ref[:]

  def pallas_row_gather(tbl, ridx):
    nsteps = ridx.shape[0] // 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ridx.shape[0],),
        in_specs=[
            pl.BlockSpec((1, tbl.shape[1]), lambda i, idx_ref: (idx_ref[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, tbl.shape[1]), lambda i, idx_ref: (i, 0)),
    )
    return pl.pallas_call(
        gather_kernel,
        out_shape=jax.ShapeDtypeStruct((ridx.shape[0], tbl.shape[1]),
                                       tbl.dtype),
        grid_spec=grid_spec,
    )(ridx, tbl)

  pg = jax.jit(pallas_row_gather)
  try:
    timeit('G pallas row gather 153k from [1M,128]', lambda: pg(feat, ridx),
           bytes_moved=153600 * 512)
    np.testing.assert_array_equal(np.asarray(pg(feat, ridx)),
                                  np.asarray(rg(feat, ridx)))
    print('   pallas gather correct')
  except Exception as e:
    print('G pallas row gather FAILED:', repr(e)[:200])

  # H: pallas scalar-table gather: table [8192,128] fits VMEM; gather via
  # block: per grid step process 2048 indices with one-hot matmul rows?
  # (skip — MXU cost prohibitive; placeholder for row-gather from VMEM)

  # I: copy bandwidth sanity
  big = jnp.asarray(rng.standard_normal((4096, 4096)).astype(np.float32))
  cp = jax.jit(lambda x: x + 1.0)
  timeit('I elementwise 64MB f32', lambda: cp(big), bytes_moved=2 * 64e6)


if __name__ == '__main__':
  main()
