"""Probe: e2e train step on CALIBRATED exact-dedup batches.

Tree-mode fast path processes 938k slots (no dedup); a calibrated map
batch is ~145k slots — smaller collate gather and smaller model rows,
at the cost of segment aggregation instead of tree_dense reshapes.
Device-trace comparison at the bench config.
"""
import os
import shutil
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def run(loader_kw, model_kw, tag, dtype, ds, train_idx):
  import jax
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import GraphSAGE
  from graphlearn_tpu.models import train as train_lib
  loader = glt.loader.NeighborLoader(
      ds, bench.FANOUT, train_idx, batch_size=bench.BATCH, shuffle=True,
      drop_last=True, seed=0, seed_labels_only=True, **loader_kw)
  model = GraphSAGE(hidden_dim=bench.E2E_HIDDEN, out_dim=bench.E2E_CLASSES,
                    num_layers=len(bench.FANOUT), dtype=dtype, **model_kw)
  it = iter(loader)
  first = train_lib.batch_to_dict(next(it))
  state, tx = train_lib.create_train_state(model, jax.random.PRNGKey(0),
                                           first)
  step, _ = train_lib.make_train_step(model, tx, bench.E2E_CLASSES)
  state, loss, _ = step(state, first)
  for _ in range(2):
    state, loss, _ = step(state, train_lib.batch_to_dict(next(it)))
  jax.block_until_ready(loss)
  td = f'/tmp/glt_e2e_{tag}'
  shutil.rmtree(td, ignore_errors=True)
  jax.profiler.start_trace(td)
  losses = []
  for _ in range(8):
    state, loss, _ = step(state, train_lib.batch_to_dict(next(it)))
    losses.append(loss)
  jax.block_until_ready(losses)
  jax.profiler.stop_trace()
  progs = glt.utils.device_program_ms(td)
  tot = sum(ms for ms, _ in progs.values())
  print(f'{tag:22s} total {tot:7.2f} ms/step')
  for n, (ms, cnt) in sorted(progs.items(), key=lambda x: -x[1][0])[:4]:
    print(f'    {ms:8.3f} ms  {n[:64]}')
  return tot


def main():
  import jax.numpy as jnp
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import train as train_lib
  glt.utils.enable_compilation_cache()
  graph = bench.build_graph()
  rng = np.random.default_rng(2)
  ds = glt.data.Dataset(graph=graph)
  ds.init_node_features(rng.standard_normal(
      (bench.NUM_NODES, bench.E2E_FEAT_DIM), dtype=np.float32))
  ds.init_node_labels(rng.integers(0, bench.E2E_CLASSES, bench.NUM_NODES))
  train_idx = rng.integers(0, bench.NUM_NODES, bench.BATCH * 16)

  cal = glt.sampler.estimate_frontier_caps(graph, bench.FANOUT, bench.BATCH,
                                           num_probes=5, slack=1.5)
  print('cal caps:', cal)
  node_offs, edge_offs = train_lib.merge_hop_offsets(
      bench.BATCH, bench.FANOUT, frontier_caps=cal)
  print('node_offs:', node_offs, 'edge_offs:', edge_offs)

  # layered segment model (prefix trimming) on calibrated map batches
  run(dict(dedup='map', frontier_caps=cal),
      dict(hop_node_offsets=node_offs, hop_edge_offsets=edge_offs),
      'map_cal_layered', jnp.bfloat16, ds, train_idx)
  # blocked (merge_dense) aggregation: k-run reshape-mean + small scatter
  run(dict(dedup='map', frontier_caps=cal),
      dict(hop_node_offsets=node_offs, hop_edge_offsets=edge_offs,
           merge_dense=True, fanouts=tuple(bench.FANOUT)),
      'map_cal_mergedense', jnp.bfloat16, ds, train_idx)
  # reference fast path: tree + block + tree_dense
  no, eo = train_lib.tree_hop_offsets(bench.BATCH, bench.FANOUT)
  run(dict(dedup='tree', strategy='block'),
      dict(hop_node_offsets=no, hop_edge_offsets=eo, tree_dense=True,
           fanouts=tuple(bench.FANOUT)), 'tree_block_dense',
      jnp.bfloat16, ds, train_idx)


if __name__ == '__main__':
  main()
