"""Probe: does XLA interleave batch n+1's sample+collate with batch n's
train step when fused into one program? (loader/pipeline.py rationale)

Measures, at the bench e2e config (1M nodes, [15,10,5] @ 1024, SAGE h=256
tree_dense bf16, block sampling), with device-trace truth:
  serial: sample + collate + train as separate programs (sum of ms)
  fused:  OverlappedTrainer's program (ms/call)
Overlap won = fused_ms < serial_sum; ideal = max(train, sample+collate).

Run: python benchmarks/prof_overlap.py
"""
import os
import shutil
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # repo-root bench config/helpers  # noqa: E402

FANOUT = bench.FANOUT
BATCH = bench.BATCH


def main():
  import jax
  import jax.numpy as jnp
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import GraphSAGE
  from graphlearn_tpu.models import train as train_lib
  glt.utils.enable_compilation_cache()

  graph = bench.build_graph()
  rng = np.random.default_rng(2)
  feat = rng.standard_normal((bench.NUM_NODES, bench.E2E_FEAT_DIM),
                             dtype=np.float32)
  labels = rng.integers(0, bench.E2E_CLASSES, bench.NUM_NODES)
  ds = glt.data.Dataset(graph=graph)
  ds.init_node_features(feat)
  ds.init_node_labels(labels)
  iters = 10
  train_idx = rng.integers(0, bench.NUM_NODES, BATCH * (iters + 6))

  loader = glt.loader.NeighborLoader(
      ds, FANOUT, train_idx, batch_size=BATCH, shuffle=True,
      drop_last=True, seed=0, dedup='tree', strategy='block',
      seed_labels_only=True)
  no, eo = train_lib.tree_hop_offsets(BATCH, FANOUT)
  model = GraphSAGE(hidden_dim=bench.E2E_HIDDEN, out_dim=bench.E2E_CLASSES,
                    num_layers=len(FANOUT), hop_node_offsets=no,
                    hop_edge_offsets=eo, dtype=jnp.bfloat16,
                    tree_dense=True, fanouts=tuple(FANOUT))
  it = iter(loader)
  first = train_lib.batch_to_dict(next(it))
  state, tx = train_lib.create_train_state(model, jax.random.PRNGKey(0),
                                           first)

  trainer = glt.loader.OverlappedTrainer(loader, model, tx,
                                         bench.E2E_CLASSES)
  # compile + warmup outside the trace
  state, losses = trainer.run_epoch(state, max_steps=3)
  jax.block_until_ready(losses)

  trace_dir = '/tmp/glt_prof_overlap'
  shutil.rmtree(trace_dir, ignore_errors=True)
  jax.profiler.start_trace(trace_dir)
  state, losses = trainer.run_epoch(state, max_steps=iters)
  jax.block_until_ready(losses)
  jax.profiler.stop_trace()

  progs = glt.utils.device_program_ms(trace_dir)
  for n, (ms, cnt) in sorted(progs.items()):
    print(f'{n[:72]:74s} {ms:8.3f} ms x{cnt}')


if __name__ == '__main__':
  main()
