"""Accuracy/epoch-time matrix across sampling modes (VERDICT r2 item 3).

Runs the products gate (examples/train_sage_ogbn_products.py, now tuned
to plateau in the discriminative 0.70-0.85 band: p_intra=0.58,
feat_snr=0.1) under every sampling mode at IDENTICAL budgets, one
subprocess per mode (clean device state; the XLA compile cache is
shared), and prints a table for PERF.md.

Run: python benchmarks/accuracy_matrix.py [--num-nodes N] [--epochs E]
"""
import argparse
import json
import os
import subprocess
import sys

EXAMPLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples', 'train_sage_ogbn_products.py')

MODES = [
    ('exact (map+calibrated)', ['--dedup', 'map', '--calibrate']),
    ('tree', ['--dedup', 'tree']),
    ('tree+block', ['--dedup', 'tree', '--strategy', 'block']),
    ('padded16', ['--dedup', 'tree', '--padded-window', '16']),
    ('padded64', ['--dedup', 'tree', '--padded-window', '64']),
]


def run_one(args, name, extra, budgets, seed):
  """ONE training run at the largest budget, evaluated at every budget
  (--eval-epochs): each (mode, seed) trains once instead of once per
  budget."""
  emax = max(budgets)
  cmd = [sys.executable, EXAMPLE, '--num-nodes', str(args.num_nodes),
         '--epochs', str(emax),
         '--eval-epochs', ','.join(str(e) for e in budgets if e < emax),
         '--eval-batches', str(args.eval_batches),
         '--seed', str(seed), '--bf16-model'] + extra
  print(f'# running {name} e{emax} s{seed}', flush=True)
  out = subprocess.run(cmd, capture_output=True, text=True)
  line = None
  for ln in out.stdout.splitlines():
    if ln.startswith('{'):
      line = json.loads(ln)
  if line is None:
    print(f'# {name} s{seed} FAILED:\n'
          f'{out.stdout[-2000:]}\n{out.stderr[-2000:]}', flush=True)
  else:
    print(f'#   test_acc_at={line["test_acc_at"]} '
          f'epoch_s={line["epoch_time_s"]}', flush=True)
  return line


def main():
  import numpy as np
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=2_449_029)
  ap.add_argument('--epochs-list', default='4,8',
                  help='comma-separated training budgets (epochs); one '
                       'run per seed at the max, evaluated at each')
  ap.add_argument('--seeds', type=int, default=3,
                  help='training seeds per cell (the reference quotes '
                       '+-0.0036 over runs; single runs cannot support '
                       'mode-vs-mode conclusions)')
  ap.add_argument('--eval-batches', type=int, default=100)
  ap.add_argument('--modes', default=None,
                  help='comma-separated substrings selecting a subset '
                       'of MODES (default: all)')
  args = ap.parse_args()
  budgets = sorted(int(x) for x in args.epochs_list.split(','))
  modes = MODES
  if args.modes:
    keys = args.modes.split(',')
    modes = [(n, e) for n, e in MODES if any(k in n for k in keys)]

  cells = {}
  for name, extra in modes:
    accs = {e: [] for e in budgets}
    walls = []
    for seed in range(args.seeds):
      line = run_one(args, name, extra, budgets, seed)
      if line is None:
        continue
      for e in budgets:
        a = line['test_acc_at'].get(str(e))
        if a is not None:
          accs[e].append(a)
      walls.append(line['epoch_time_s'])
    cells[name] = (accs, walls)

  hdr = ' | '.join(f'{e} epochs (mean+-std, n={args.seeds})'
                   for e in budgets)
  print(f'\n| mode | {hdr} | epoch wall s |')
  print('|---' * (len(budgets) + 2) + '|')
  for name, _ in modes:
    accs, walls = cells[name]
    parts = [(f'{np.mean(accs[e]):.4f} +- {np.std(accs[e]):.4f}'
              if accs[e] else 'FAILED') for e in budgets]
    wall = f'{np.mean(walls):.1f}' if walls else '-'
    print(f'| {name} | ' + ' | '.join(parts) + f' | {wall} |')
  print(json.dumps({n: {'accs_at': v[0], 'epoch_s': v[1]}
                    for n, v in cells.items()}))


if __name__ == '__main__':
  main()
