"""Accuracy/epoch-time matrix across sampling modes (VERDICT r2 item 3).

Runs the products gate (examples/train_sage_ogbn_products.py, now tuned
to plateau in the discriminative 0.70-0.85 band: p_intra=0.58,
feat_snr=0.1) under every sampling mode at IDENTICAL budgets, one
subprocess per mode (clean device state; the XLA compile cache is
shared), and prints a table for PERF.md (shared driver:
benchmarks/matrix_driver.py).

Run: python benchmarks/accuracy_matrix.py [--num-nodes N] [--epochs E]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import matrix_driver  # noqa: E402

EXAMPLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples', 'train_sage_ogbn_products.py')

MODES = [
    ('exact (map+calibrated)', ['--dedup', 'map', '--calibrate']),
    ('tree', ['--dedup', 'tree']),
    ('tree+block', ['--dedup', 'tree', '--strategy', 'block']),
    ('padded16', ['--dedup', 'tree', '--padded-window', '16']),
    ('padded64', ['--dedup', 'tree', '--padded-window', '64']),
]


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=2_449_029)
  ap.add_argument('--epochs-list', default='4,8',
                  help='comma-separated training budgets (epochs); one '
                       'run per seed at the max, evaluated at each')
  ap.add_argument('--seeds', type=int, default=3,
                  help='training seeds per cell (the reference quotes '
                       '+-0.0036 over runs; single runs cannot support '
                       'mode-vs-mode conclusions)')
  ap.add_argument('--eval-batches', type=int, default=100)
  ap.add_argument('--modes', default=None,
                  help='comma-separated substrings selecting a subset '
                       'of MODES (default: all)')
  ap.add_argument('--extra', default='',
                  help='extra args passed through to the gate script, '
                       "e.g. '--batch-size 256' (reduced-scale CPU "
                       'runs need more steps/epoch than the default '
                       'products batch gives)')
  args = ap.parse_args()
  budgets = sorted(int(x) for x in args.epochs_list.split(','))
  modes = MODES
  if args.modes:
    keys = args.modes.split(',')
    modes = [(n, e) for n, e in MODES if any(k in n for k in keys)]
  extra_of = dict(modes)
  cells = [(n,) for n, _ in modes]

  def cmd_for(cell, seed):
    emax = max(budgets)
    return [sys.executable, EXAMPLE, '--num-nodes', str(args.num_nodes),
            '--epochs', str(emax),
            '--eval-epochs', ','.join(str(e) for e in budgets
                                      if e < emax),
            '--eval-batches', str(args.eval_batches),
            '--seed', str(seed), '--bf16-model'] + extra_of[cell[0]] + \
        args.extra.split()

  results = matrix_driver.drive(cells, cmd_for, budgets, args.seeds)
  matrix_driver.report(cells, results, budgets, ('mode',))


if __name__ == '__main__':
  main()
