"""Accuracy/epoch-time matrix across sampling modes (VERDICT r2 item 3).

Runs the products gate (examples/train_sage_ogbn_products.py, now tuned
to plateau in the discriminative 0.70-0.85 band: p_intra=0.58,
feat_snr=0.1) under every sampling mode at IDENTICAL budgets, one
subprocess per mode (clean device state; the XLA compile cache is
shared), and prints a table for PERF.md.

Run: python benchmarks/accuracy_matrix.py [--num-nodes N] [--epochs E]
"""
import argparse
import json
import os
import subprocess
import sys

EXAMPLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples', 'train_sage_ogbn_products.py')

MODES = [
    ('exact (map+calibrated)', ['--dedup', 'map', '--calibrate']),
    ('tree', ['--dedup', 'tree']),
    ('tree+block', ['--dedup', 'tree', '--strategy', 'block']),
    ('padded16', ['--dedup', 'tree', '--padded-window', '16']),
    ('padded64', ['--dedup', 'tree', '--padded-window', '64']),
]


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=2_449_029)
  ap.add_argument('--epochs', type=int, default=4)
  ap.add_argument('--eval-batches', type=int, default=100)
  args = ap.parse_args()

  rows = []
  for name, extra in MODES:
    cmd = [sys.executable, EXAMPLE, '--num-nodes', str(args.num_nodes),
           '--epochs', str(args.epochs), '--eval-batches',
           str(args.eval_batches), '--bf16-model'] + extra
    print(f'# running {name}: {" ".join(cmd)}', flush=True)
    out = subprocess.run(cmd, capture_output=True, text=True)
    line = None
    for ln in out.stdout.splitlines():
      if ln.startswith('{'):
        line = json.loads(ln)
    if line is None:
      print(f'# {name} FAILED:\n{out.stdout[-2000:]}\n{out.stderr[-2000:]}')
      rows.append((name, None))
      continue
    rows.append((name, line))
    print(f'# {name}: test_acc={line["test_acc"]} '
          f'epoch_s={line["epoch_time_s"]}', flush=True)

  print('\n| mode | test acc | final train acc | epoch wall s |')
  print('|---|---|---|---|')
  for name, r in rows:
    if r is None:
      print(f'| {name} | FAILED | - | - |')
    else:
      print(f'| {name} | {r["test_acc"]:.4f} | {r["final_train_acc"]:.4f}'
            f' | {r["epoch_time_s"]} |')


if __name__ == '__main__':
  main()
