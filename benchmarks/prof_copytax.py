"""A/B trace of the dense convs' run-mean layout (VERDICT r4 item 8).

PERF.md's byte audit attributes ~3.8 ms copy + ~3.7 ms reshape per
step to XLA materialization between aggregation stages; the prime
suspect is the [f*k, F] -> [f, k, F] run view (k = 15/10/5 is never
tile-aligned, so the 3D view relayouts). models.RUN_MEAN_IMPL toggles
the kernel: 'reshape' (status quo) vs 'window' (flat-layout
lax.reduce_window, no 3D view). This script traces the bench train
step under BOTH impls and prints the per-op-class tables + program
ms, so one run on the chip decides which lands as default.

Run on TPU: python benchmarks/prof_copytax.py [--variant exact|tree]
"""
import argparse
import shutil

import numpy as np


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--variant', default='exact', choices=['exact', 'tree'])
  ap.add_argument('--iters', type=int, default=10)
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import models as M
  import bench
  glt.utils.enable_compilation_cache()
  bench.E2E_ITERS = args.iters

  graph = bench.build_graph()
  rng = np.random.default_rng(2)
  feat = rng.standard_normal((bench.NUM_NODES, bench.E2E_FEAT_DIM),
                             dtype=np.float32)
  labels = rng.integers(0, bench.E2E_CLASSES, bench.NUM_NODES)
  ds = glt.data.Dataset(graph=graph)
  ds.init_node_features(feat)
  ds.init_node_labels(labels)
  train_idx = rng.integers(0, bench.NUM_NODES,
                           bench.BATCH * (args.iters + 6))
  cal_caps = None
  if args.variant == 'exact':
    cal_caps = glt.sampler.estimate_frontier_caps(
        graph, bench.FANOUT, bench.BATCH, num_probes=5, slack=1.5)

  for impl in ('reshape', 'window'):
    M.RUN_MEAN_IMPL = impl
    td = f'/tmp/glt_prof_copytax_{args.variant}_{impl}'
    shutil.rmtree(td, ignore_errors=True)
    tot, tr = bench._run_e2e(ds, train_idx, jnp.bfloat16, jax, td,
                             variant=args.variant, cal_caps=cal_caps)
    print(f'\n=== {args.variant} / RUN_MEAN_IMPL={impl}: '
          f'full {tot} ms, train program {tr} ms ===')
    for n, (ms, cnt) in glt.utils.device_op_ms(td, top=14,
                                               steps=args.iters).items():
      print(f'  {n[:56]:58s} {ms:8.3f} ms x{cnt}')


if __name__ == '__main__':
  import os
  import sys
  sys.path.insert(0, os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  main()
