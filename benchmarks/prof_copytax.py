"""A/B trace of the dense convs' flat-layout forks (VERDICT r4 item 8 +
ISSUE 13c).

PERF.md's byte audit attributes ~3.8 ms copy + ~3.7 ms reshape per
step to XLA materialization between aggregation stages; the prime
suspect is the [f*k, F] -> [f, k, F] run view (k = 15/10/5 is never
tile-aligned, so the 3D view relayouts). models.RUN_MEAN_IMPL toggles
the kernel: 'reshape' (status quo) vs 'window' (flat-layout
lax.reduce_window, no 3D view). This script traces the bench train
step under BOTH impls and prints the per-op-class tables + program
ms, so one run on the chip decides which lands as default — bench.py
now runs the same pair every round and auto-records the winner as
``run_mean_impl_decision``.

``--softmax-ab`` additionally A/Bs models.RUN_SOFTMAX_IMPL (the dense
GAT convs' f32 [f, k, H] softmax chain — ISSUE 13's further
flat-layout rewrite) on a tree_dense GAT train step: same per-op-class
tables, same decision discipline.

Run on TPU: python benchmarks/prof_copytax.py [--variant exact|tree]
                                              [--softmax-ab]
"""
import argparse
import shutil

import numpy as np


def _gat_softmax_ab(args):
  """Trace a tree_dense GAT train step under both RUN_SOFTMAX_IMPL
  settings (separate jit caches per impl: the flag is read at trace
  time, so each leg builds its model fns fresh)."""
  import jax
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import models as M
  from graphlearn_tpu.models import train as train_lib
  import bench

  graph = bench.build_graph()
  rng = np.random.default_rng(3)
  feat = rng.standard_normal((bench.NUM_NODES, bench.E2E_FEAT_DIM),
                             dtype=np.float32)
  ds = glt.data.Dataset(graph=graph)
  ds.init_node_features(feat)
  ds.init_node_labels(rng.integers(0, bench.E2E_CLASSES,
                                   bench.NUM_NODES))
  train_idx = rng.integers(0, bench.NUM_NODES,
                           bench.BATCH * (args.iters + 6))
  for impl in ('reshape', 'window'):
    M.RUN_SOFTMAX_IMPL = impl
    loader = glt.loader.NeighborLoader(
        ds, bench.FANOUT, train_idx, batch_size=bench.BATCH,
        shuffle=True, drop_last=True, seed=0, dedup='tree',
        strategy='block', seed_labels_only=True)
    no, eo = train_lib.tree_hop_offsets(bench.BATCH, bench.FANOUT)
    import jax.numpy as jnp
    model = glt.models.GAT(hidden_dim=128, out_dim=bench.E2E_CLASSES,
                           num_layers=len(bench.FANOUT), heads=2,
                           hop_node_offsets=no, hop_edge_offsets=eo,
                           dtype=jnp.bfloat16, tree_dense=True,
                           fanouts=tuple(bench.FANOUT))
    it = iter(loader)
    first = train_lib.batch_to_dict(next(it))
    state, tx = train_lib.create_train_state(model, jax.random.PRNGKey(0),
                                             first)
    step, _ = train_lib.make_train_step(model, tx, bench.E2E_CLASSES)

    def run_step():
      nonlocal state
      state, loss, _ = step(state, train_lib.batch_to_dict(next(it)))
      return loss

    state, loss, _ = step(state, first)   # compile
    td = f'/tmp/glt_prof_copytax_gat_{impl}'
    shutil.rmtree(td, ignore_errors=True)
    tot, tr = bench._traced_step_ms(jax, run_step, td, 'jit_train_step')
    print(f'\n=== gat tree_dense / RUN_SOFTMAX_IMPL={impl}: '
          f'full {tot} ms, train program {tr} ms ===')
    for n, (ms, cnt) in glt.utils.device_op_ms(td, top=14,
                                               steps=args.iters).items():
      print(f'  {n[:56]:58s} {ms:8.3f} ms x{cnt}')
  M.RUN_SOFTMAX_IMPL = 'reshape'


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--variant', default='exact', choices=['exact', 'tree'])
  ap.add_argument('--iters', type=int, default=10)
  ap.add_argument('--softmax-ab', action='store_true',
                  help='also A/B models.RUN_SOFTMAX_IMPL on a '
                       'tree_dense GAT step (ISSUE 13c)')
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import models as M
  import bench
  glt.utils.enable_compilation_cache()
  bench.E2E_ITERS = args.iters

  graph = bench.build_graph()
  rng = np.random.default_rng(2)
  feat = rng.standard_normal((bench.NUM_NODES, bench.E2E_FEAT_DIM),
                             dtype=np.float32)
  labels = rng.integers(0, bench.E2E_CLASSES, bench.NUM_NODES)
  ds = glt.data.Dataset(graph=graph)
  ds.init_node_features(feat)
  ds.init_node_labels(labels)
  train_idx = rng.integers(0, bench.NUM_NODES,
                           bench.BATCH * (args.iters + 6))
  cal_caps = None
  if args.variant == 'exact':
    cal_caps = glt.sampler.estimate_frontier_caps(
        graph, bench.FANOUT, bench.BATCH, num_probes=5, slack=1.5)

  for impl in ('reshape', 'window'):
    M.RUN_MEAN_IMPL = impl
    td = f'/tmp/glt_prof_copytax_{args.variant}_{impl}'
    shutil.rmtree(td, ignore_errors=True)
    tot, tr = bench._run_e2e(ds, train_idx, jnp.bfloat16, jax, td,
                             variant=args.variant, cal_caps=cal_caps)
    print(f'\n=== {args.variant} / RUN_MEAN_IMPL={impl}: '
          f'full {tot} ms, train program {tr} ms ===')
    for n, (ms, cnt) in glt.utils.device_op_ms(td, top=14,
                                               steps=args.iters).items():
      print(f'  {n[:56]:58s} {ms:8.3f} ms x{cnt}')

  if args.softmax_ab:
    _gat_softmax_ab(args)


if __name__ == '__main__':
  import os
  import sys
  sys.path.insert(0, os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  main()
