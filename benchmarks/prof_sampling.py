"""Profiling harness: where does the per-batch sampling time go?"""
import time
import numpy as np
import jax
import jax.numpy as jnp

import graphlearn_tpu as glt
from graphlearn_tpu.sampler import NodeSamplerInput
from graphlearn_tpu import ops

NUM_NODES = 1_000_000
AVG_DEG = 25
FANOUT = [15, 10, 5]
BATCH = 1024


def build_graph():
  rng = np.random.default_rng(0)
  e = NUM_NODES * AVG_DEG
  rows = rng.integers(0, NUM_NODES, e)
  cols = np.empty(e, np.int64)
  half = e // 2
  cols[:half] = rng.integers(0, NUM_NODES, half)
  cols[half:] = rng.zipf(1.5, e - half) % NUM_NODES
  topo = glt.data.Topology(np.stack([rows, cols]), num_nodes=NUM_NODES)
  return glt.data.Graph(topo, 'HBM')


def timeit(name, fn, iters=30, warmup=3):
  for _ in range(warmup):
    r = fn()
  jax.block_until_ready(r)
  t0 = time.perf_counter()
  results = []
  for _ in range(iters):
    results.append(fn())
  jax.block_until_ready(results)
  dt = (time.perf_counter() - t0) / iters
  print(f'{name:50s} {dt*1e3:9.3f} ms/iter')
  return dt


def main():
  graph = build_graph()
  sampler = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True)
  rng = np.random.default_rng(1)
  seeds_np = rng.integers(0, NUM_NODES, BATCH)

  out = sampler.sample_from_nodes(NodeSamplerInput(seeds_np), batch_cap=BATCH)
  print('edges per batch:', int(out.edge_mask.sum()))

  # full fused program, same seeds each time (device-resident args)
  fn = sampler._homo_fn(BATCH, tuple(FANOUT))
  seeds = jnp.asarray(np.asarray(seeds_np, np.int32))
  mask = jnp.ones((BATCH,), bool)
  key = jax.random.PRNGKey(7)
  timeit('fused full 3-hop program', lambda: fn(seeds, mask, key))

  indptr = jnp.asarray(graph.indptr)
  indices = jnp.asarray(graph.indices)

  # per-hop uniform_sample at each hop's frontier size
  caps = [BATCH, BATCH * 15, BATCH * 15 * 10]
  f0 = seeds
  m0 = mask
  for i, k in enumerate(FANOUT):
    b = caps[i]
    f = jnp.asarray(rng.integers(0, NUM_NODES, b).astype(np.int32))
    m = jnp.ones((b,), bool)
    timeit(f'uniform_sample hop{i} [B={b}, K={k}]',
           lambda f=f, m=m, k=k: ops.uniform_sample(indptr, indices, f, m, k,
                                                    key))

  # induce_next_map at each hop's size
  node_cap = BATCH + BATCH * 15 + BATCH * 150 + BATCH * 750
  state, uniq, umask, inv = ops.init_node_map(seeds, mask,
                                              capacity=node_cap,
                                              num_graph_nodes=NUM_NODES)
  timeit('init_node_map [B=1024]',
         lambda: ops.init_node_map(seeds, mask, capacity=node_cap,
                                   num_graph_nodes=NUM_NODES))
  for i, k in enumerate(FANOUT):
    b = caps[i]
    nbrs = jnp.asarray(rng.integers(0, NUM_NODES, (b, k)).astype(np.int32))
    nm = jnp.ones((b, k), bool)
    fidx = jnp.arange(b, dtype=jnp.int32)
    timeit(f'induce_next_map hop{i} [F={b}, K={k}]',
           lambda nbrs=nbrs, nm=nm, fidx=fidx: ops.induce_next_map(
               state, fidx, nbrs, nm))

  # raw gather benchmark: how fast is indices[idx] at hop-3 scale?
  idx = jnp.asarray(rng.integers(0, indices.shape[0], 768000))
  g = jax.jit(lambda i: indices[i])
  timeit('raw gather 768k from E=25M', lambda: g(idx))
  idx2 = jnp.asarray(rng.integers(0, NUM_NODES, 768000))
  g2 = jax.jit(lambda i: indptr[i])
  timeit('raw gather 768k from N=1M', lambda: g2(idx2))

  # raw scatter at table scale
  tbl = jnp.zeros((NUM_NODES,), jnp.int32)
  vals = jnp.arange(768000, dtype=jnp.int32)
  sc = jax.jit(lambda t, i, v: t.at[i].set(v, mode='drop'))
  timeit('raw scatter 768k into N=1M', lambda: sc(tbl, idx2, vals))

  # dispatch overhead: trivial program
  triv = jax.jit(lambda x: x + 1)
  x = jnp.zeros((8,))
  timeit('trivial dispatch x+1', lambda: triv(x), iters=100)

  # cumsum at hop3 size
  cs = jax.jit(lambda m: jnp.cumsum(m.reshape(-1)))
  mm = jnp.ones((768000,), jnp.int32)
  timeit('cumsum 768k', lambda: cs(mm))


if __name__ == '__main__':
  main()
