"""Hetero accuracy/epoch-time matrix: mode x conv x seed (VERDICT r4
item 4 — the typed counterpart of benchmarks/accuracy_matrix.py).

Runs the hetero gate (examples/igbh/train_rgnn_gate.py: typed
homophily, power-law targets, low feature SNR) for every
(conv, sampling-mode) cell at identical budgets, one subprocess per
run (clean device state; shared XLA compile cache), >=3 seeds, and
prints a markdown table for PERF.md. A semantics regression in typed
sampling or the dense hetero convs shows up as a mode-vs-mode accuracy
gap — the certification the homo mode matrix gives the homo engines.

Run: python benchmarks/hetero_accuracy_matrix.py [--n-paper N]
     [--epochs-list 4,8] [--seeds 3] [--cells sage/segment,...]
"""
import argparse
import json
import os
import subprocess
import sys

EXAMPLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples', 'igbh', 'train_rgnn_gate.py')

CELLS = [
    ('sage', 'segment'),
    ('sage', 'tree_dense'),
    ('sage', 'merge_dense'),
    ('gat', 'segment'),
    ('gat', 'tree_dense'),
    ('gat', 'merge_dense'),
    ('hgt', 'segment'),
    ('hgt', 'tree_dense'),
]


def run_one(args, conv, mode, budgets, seed):
  emax = max(budgets)
  cmd = [sys.executable, EXAMPLE, '--conv', conv, '--mode', mode,
         '--n-paper', str(args.n_paper),
         '--n-author', str(args.n_paper // 2),
         '--batch-size', str(args.batch_size),
         '--epochs', str(emax),
         '--eval-epochs', ','.join(str(e) for e in budgets if e < emax),
         '--eval-batches', str(args.eval_batches),
         '--seed', str(seed), '--bf16-model']
  if args.fanout:
    cmd += ['--fanout'] + args.fanout.split(',')
  if args.extra:
    cmd += args.extra.split()
  print(f'# running {conv}/{mode} e{emax} s{seed}', flush=True)
  out = subprocess.run(cmd, capture_output=True, text=True)
  line = None
  for ln in out.stdout.splitlines():
    if ln.startswith('{'):
      line = json.loads(ln)
  if line is None:
    print(f'# {conv}/{mode} s{seed} FAILED:\n'
          f'{out.stdout[-2000:]}\n{out.stderr[-2000:]}', flush=True)
  else:
    print(f'#   test_acc_at={line["test_acc_at"]} '
          f'epoch_s={line["epoch_time_s"]}', flush=True)
  return line


def main():
  import numpy as np
  ap = argparse.ArgumentParser()
  ap.add_argument('--n-paper', type=int, default=100_000)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', default='',
                  help="comma-separated fanout override, e.g. '15,10,5'")
  ap.add_argument('--epochs-list', default='4,8')
  ap.add_argument('--seeds', type=int, default=3)
  ap.add_argument('--eval-batches', type=int, default=50)
  ap.add_argument('--cells', default=None,
                  help="comma-separated conv/mode pairs, e.g. "
                       "'sage/segment,gat/tree_dense' (default: all)")
  ap.add_argument('--extra', default='',
                  help='extra args passed through to the gate script, '
                       "e.g. '--hidden 64 --feat-dim 32'")
  args = ap.parse_args()
  budgets = sorted(int(x) for x in args.epochs_list.split(','))
  cells_sel = CELLS
  if args.cells:
    want = {tuple(c.split('/')) for c in args.cells.split(',')}
    cells_sel = [c for c in CELLS if c in want]

  results = {}
  for conv, mode in cells_sel:
    accs = {e: [] for e in budgets}
    walls = []
    for seed in range(args.seeds):
      line = run_one(args, conv, mode, budgets, seed)
      if line is None:
        continue
      for e in budgets:
        a = line['test_acc_at'].get(str(e))
        if a is not None:
          accs[e].append(a)
      walls.append(line['epoch_time_s'])
    results[(conv, mode)] = (accs, walls)

  hdr = ' | '.join(f'{e} epochs (mean+-std, n={args.seeds})'
                   for e in budgets)
  print(f'\n| conv | mode | {hdr} | epoch wall s |')
  print('|---' * (len(budgets) + 3) + '|')
  for (conv, mode) in cells_sel:
    accs, walls = results[(conv, mode)]
    parts = [(f'{np.mean(accs[e]):.4f} +- {np.std(accs[e]):.4f}'
              if accs[e] else 'FAILED') for e in budgets]
    wall = f'{np.mean(walls):.1f}' if walls else '-'
    print(f'| {conv} | {mode} | ' + ' | '.join(parts) + f' | {wall} |')
  print(json.dumps({f'{c}/{m}': {'accs_at': v[0], 'epoch_s': v[1]}
                    for (c, m), v in results.items()}))


if __name__ == '__main__':
  main()
