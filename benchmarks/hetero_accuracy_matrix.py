"""Hetero accuracy/epoch-time matrix: mode x conv x seed (VERDICT r4
item 4 — the typed counterpart of benchmarks/accuracy_matrix.py).

Runs the hetero gate (examples/igbh/train_rgnn_gate.py: typed
homophily, power-law targets, low feature SNR) for every
(conv, sampling-mode) cell at identical budgets, one subprocess per
run (clean device state; shared XLA compile cache), >=3 seeds, and
prints a markdown table for PERF.md. A semantics regression in typed
sampling or the dense hetero convs shows up as a mode-vs-mode accuracy
gap — the certification the homo mode matrix gives the homo engines.

Run: python benchmarks/hetero_accuracy_matrix.py [--n-paper N]
     [--epochs-list 4,8] [--seeds 3] [--cells sage/segment,...]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import matrix_driver  # noqa: E402

EXAMPLE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples', 'igbh', 'train_rgnn_gate.py')

CELLS = [
    ('sage', 'segment'),
    ('sage', 'tree_dense'),
    ('sage', 'merge_dense'),
    ('gat', 'segment'),
    ('gat', 'tree_dense'),
    ('gat', 'merge_dense'),
    ('hgt', 'segment'),
    ('hgt', 'tree_dense'),
    ('hgt', 'merge_dense'),
]


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--n-paper', type=int, default=100_000)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', default='',
                  help="comma-separated fanout override, e.g. '15,10,5'")
  ap.add_argument('--epochs-list', default='4,8')
  ap.add_argument('--seeds', type=int, default=3)
  ap.add_argument('--eval-batches', type=int, default=50)
  ap.add_argument('--cells', default=None,
                  help="comma-separated conv/mode pairs, e.g. "
                       "'sage/segment,gat/tree_dense' (default: all)")
  ap.add_argument('--extra', default='',
                  help='extra args passed through to the gate script, '
                       "e.g. '--hidden 64 --feat-dim 32'")
  args = ap.parse_args()
  budgets = sorted(int(x) for x in args.epochs_list.split(','))
  cells = CELLS
  if args.cells:
    want = {tuple(c.split('/')) for c in args.cells.split(',')}
    cells = [c for c in CELLS if c in want]

  def cmd_for(cell, seed):
    conv, mode = cell
    emax = max(budgets)
    cmd = [sys.executable, EXAMPLE, '--conv', conv, '--mode', mode,
           '--n-paper', str(args.n_paper),
           '--n-author', str(args.n_paper // 2),
           '--batch-size', str(args.batch_size),
           '--epochs', str(emax),
           '--eval-epochs', ','.join(str(e) for e in budgets
                                     if e < emax),
           '--eval-batches', str(args.eval_batches),
           '--seed', str(seed), '--bf16-model']
    if args.fanout:
      cmd += ['--fanout'] + args.fanout.split(',')
    if args.extra:
      cmd += args.extra.split()
    return cmd

  results = matrix_driver.drive(cells, cmd_for, budgets, args.seeds)
  matrix_driver.report(cells, results, budgets, ('conv', 'mode'))


if __name__ == '__main__':
  main()
