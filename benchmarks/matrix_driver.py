"""Shared subprocess driver for the accuracy matrices (homo
benchmarks/accuracy_matrix.py and hetero hetero_accuracy_matrix.py):
one run per (cell, seed) at the largest budget, evaluated at every
budget via --eval-epochs, mean +- std markdown with the REAL per-cell
sample size (failed seeds shrink n, never silently inflate it), plus a
machine-readable JSON dump."""
import json
import subprocess


def run_cell(cmd, label):
  """One gate subprocess; returns its JSON line dict or None."""
  print(f'# running {label}', flush=True)
  out = subprocess.run(cmd, capture_output=True, text=True)
  line = None
  for ln in out.stdout.splitlines():
    if ln.startswith('{'):
      line = json.loads(ln)
  if line is None:
    print(f'# {label} FAILED:\n'
          f'{out.stdout[-2000:]}\n{out.stderr[-2000:]}', flush=True)
  else:
    print(f'#   test_acc_at={line["test_acc_at"]} '
          f'epoch_s={line["epoch_time_s"]}', flush=True)
  return line


def drive(cells, cmd_for, budgets, seeds):
  """{cell: (accs_at{budget: [..]}, walls[..])} over seeds x cells."""
  results = {}
  for cell in cells:
    accs = {e: [] for e in budgets}
    walls = []
    for seed in range(seeds):
      label = '/'.join(str(c) for c in cell) + \
          f' e{max(budgets)} s{seed}'
      line = run_cell(cmd_for(cell, seed), label)
      if line is None:
        continue
      for e in budgets:
        a = line['test_acc_at'].get(str(e))
        if a is not None:
          accs[e].append(a)
      walls.append(line['epoch_time_s'])
    results[cell] = (accs, walls)
  return results


def report(cells, results, budgets, head_cols):
  """Markdown table (real n per cell) + one JSON line."""
  import numpy as np
  hdr = ' | '.join(f'{e} epochs (mean+-std)' for e in budgets)
  print(f'\n| {" | ".join(head_cols)} | {hdr} | epoch wall s |')
  print('|---' * (len(budgets) + len(head_cols) + 1) + '|')
  for cell in cells:
    accs, walls = results[cell]
    parts = [(f'{np.mean(accs[e]):.4f} +- {np.std(accs[e]):.4f} '
              f'(n={len(accs[e])})' if accs[e] else 'FAILED')
             for e in budgets]
    wall = f'{np.mean(walls):.1f}' if walls else '-'
    lead = ' | '.join(str(c) for c in cell)
    print(f'| {lead} | ' + ' | '.join(parts) + f' | {wall} |')
  print(json.dumps({'/'.join(str(c) for c in cell):
                    {'accs_at': v[0], 'epoch_s': v[1]}
                    for cell, v in results.items()}))
