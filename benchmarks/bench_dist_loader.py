"""Benchmark: distributed loader scaling over mesh sizes.

Counterpart of /root/reference/benchmarks/api/bench_dist_neighbor_loader.py
(batches/s per worker count over its RPC mesh). Here the scaling axis is
the graph-partition mesh axis 'g': one SPMD program samples P per-shard
batches per step, so throughput is measured in SEED BATCHES (P * batch) per
second at P = 1, 2, 4, 8.

Runs on the virtual CPU device mesh by default (validates the scaling
SHAPE of the collective sampling path — absolute numbers are CPU-bound;
run on a real pod slice for chip figures).
"""
import argparse
import json
import sys
import time

import numpy as np


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=200_000)
  ap.add_argument('--avg-deg', type=int, default=15)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--fanout', type=int, nargs='+', default=[10, 5])
  ap.add_argument('--mesh-sizes', default='1,2,4,8')
  ap.add_argument('--iters', type=int, default=20)
  ap.add_argument('--cpu-devices', type=int, default=8)
  ap.add_argument('--tpu', action='store_true',
                  help='use the attached TPU devices instead of the '
                       'virtual CPU mesh (single-chip rigs only reach '
                       'mesh_size=1)')
  ap.add_argument('--compare-calibrated', action='store_true',
                  help='per mesh size, run the EXACT-dedup engine at '
                       'worst-case capacities vs calibrated '
                       'frontier_caps (estimate_frontier_caps on the '
                       'host CSR) and report the step-time ratio')
  args = ap.parse_args()

  import jax
  if not args.tpu:
    jax.config.update('jax_platforms', 'cpu')
    jax.config.update('jax_num_cpu_devices', args.cpu_devices)
  from jax.sharding import Mesh

  sys.path.insert(0, __file__.rsplit('/', 2)[0])
  import graphlearn_tpu as glt
  from graphlearn_tpu.typing import GraphPartitionData

  n = args.num_nodes
  rng = np.random.default_rng(0)
  rows = rng.integers(0, n, n * args.avg_deg)
  # bench.py's products-like degree mix: half uniform, half zipf head —
  # uniform-only cols have no dedup overlap, which would make the
  # exact-dedup comparisons vacuous
  e = n * args.avg_deg
  cols = np.empty(e, np.int64)
  cols[:e // 2] = rng.integers(0, n, e // 2)
  cols[e // 2:] = rng.zipf(1.5, e - e // 2) % n
  eids = np.arange(rows.shape[0])
  host_topo = None
  if args.compare_calibrated:
    host_topo = glt.data.Topology(np.stack([rows, cols]), num_nodes=n)

  for p in [int(x) for x in args.mesh_sizes.split(',')]:
    if p > len(jax.devices()):
      continue
    node_pb = (np.arange(n) % p).astype(np.int32)
    epb = node_pb[rows]
    parts = []
    for q in range(p):
      m = epb == q
      parts.append(GraphPartitionData(
          edge_index=np.stack([rows[m], cols[m]]), eids=eids[m]))
    mesh = Mesh(np.array(jax.devices()[:p]), ('g',))
    dg = glt.distributed.DistGraph(p, 0, parts, node_pb)
    seeds = rng.integers(0, n, (p, args.batch_size)).astype(np.int32)

    def timed(sampler):
      outs = [sampler.sample_from_nodes(seeds) for _ in range(3)]
      jax.block_until_ready([o.edge_mask for o in outs])
      t0 = time.perf_counter()
      outs = [sampler.sample_from_nodes(seeds)
              for _ in range(args.iters)]
      jax.block_until_ready([o.edge_mask for o in outs])
      return time.perf_counter() - t0, outs[-1]

    if args.compare_calibrated:
      from graphlearn_tpu.sampler.calibrate import estimate_frontier_caps
      caps = estimate_frontier_caps(host_topo, list(args.fanout),
                                    args.batch_size)
      full = glt.distributed.DistNeighborSampler(
          dg, list(args.fanout), mesh, seed=0, dedup='merge')
      cal = glt.distributed.DistNeighborSampler(
          dg, list(args.fanout), mesh, seed=0, dedup='merge',
          frontier_caps=caps)
      dt_full, _ = timed(full)
      dt_cal, out = timed(cal)
      print(json.dumps({
          'metric': 'dist_exact_calibrated_speedup',
          'mesh_size': p,
          'value': round(dt_full / dt_cal, 3),
          'full_ms_per_step': round(1e3 * dt_full / args.iters, 2),
          'calibrated_ms_per_step': round(1e3 * dt_cal / args.iters, 2),
          'frontier_caps': [int(c) for c in caps],
          'full_plan': full._capacities(args.batch_size),
          'calibrated_plan': cal.hop_caps(args.batch_size),
          'overflow': bool(np.any(np.asarray(out.metadata['overflow']))),
          'backend': jax.default_backend(),
      }), flush=True)
      continue

    sampler = glt.distributed.DistNeighborSampler(
        dg, list(args.fanout), mesh, seed=0)
    dt, _ = timed(sampler)
    print(json.dumps({
        'metric': 'dist_loader_seed_batches_per_sec',
        'mesh_size': p,
        'value': round(args.iters * p / dt, 2),
        'seeds_per_sec': round(args.iters * p * args.batch_size / dt, 1),
        'secs': round(dt, 4),
        'backend': jax.default_backend(),
    }), flush=True)


if __name__ == '__main__':
  main()
