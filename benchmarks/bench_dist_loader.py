"""Benchmark: distributed loader scaling over mesh sizes.

Counterpart of /root/reference/benchmarks/api/bench_dist_neighbor_loader.py
(batches/s per worker count over its RPC mesh). Here the scaling axis is
the graph-partition mesh axis 'g': one SPMD program samples P per-shard
batches per step, so throughput is measured in SEED BATCHES (P * batch) per
second at P = 1, 2, 4, 8.

Runs on the virtual CPU device mesh by default (validates the scaling
SHAPE of the collective sampling path — absolute numbers are CPU-bound;
run on a real pod slice for chip figures).
"""
import argparse
import json
import sys
import time

import numpy as np


def make_dist_fixture(rows, cols, num_nodes, p, feat_dim=None,
                      split_ratio=0.2, labels=None, feat_rng=None):
  """ONE partition/shard fixture builder for the dist benchmarks —
  main(), _scan_ab and bench.py's dist-scan section all build the same
  round-robin node book + per-partition edge/feature shards, and a
  drift between the arms would silently benchmark different datasets
  (the _make_timed precedent). With ``feat_dim`` returns
  ``(dist_graph, dist_dataset, mesh)``; without, feature shards are
  skipped and dataset is None (sampler-only benchmarks).

  Import-light on purpose: callers set JAX_PLATFORMS/XLA_FLAGS before
  the first jax import, so jax/glt load lazily here."""
  import jax
  from jax.sharding import Mesh

  import graphlearn_tpu as glt
  from graphlearn_tpu.typing import GraphPartitionData

  node_pb = (np.arange(num_nodes) % p).astype(np.int32)
  epb = node_pb[rows]
  eids = np.arange(rows.shape[0])
  parts, feats = [], []
  for q in range(p):
    m = epb == q
    parts.append(GraphPartitionData(
        edge_index=np.stack([rows[m], cols[m]]), eids=eids[m]))
    if feat_dim is not None:
      ids = np.nonzero(node_pb == q)[0]
      feats.append((ids.astype(np.int64),
                    feat_rng.standard_normal((ids.shape[0], feat_dim))
                    .astype(np.float32)))
  mesh = Mesh(np.array(jax.devices()[:p]), ('g',))
  if feat_dim is None:
    return glt.distributed.DistGraph(p, 0, parts, node_pb), None, mesh
  dg = glt.distributed.DistGraph(p, 0, parts, node_pb, epb)
  df = glt.distributed.DistFeature(p, feats, node_pb, mesh,
                                   split_ratio=split_ratio)
  ds = glt.distributed.DistDataset(p, 0, dg, df, node_labels=labels)
  return dg, ds, mesh


def run_scan_ab(make_loader, model, tx, num_classes, chunk_size,
                make_state, warmup=True):
  """ONE measurement protocol for the scanned-vs-per-step distributed
  epoch A/B — _scan_ab, bench.py's dist-scan section and
  __graft_entry__'s dryrun stage all run it, so a drift (a dropped
  warmup epoch, a missing block_until_ready) can't silently skew one
  arm of the PERF.md dispatch/wall claims.

  Per arm: optional compile epoch (``warmup``), then one measured epoch
  under utils.count_dispatches with block_until_ready inside the wall
  timer. ``make_state`` builds a fresh TrainState and is called ONCE
  per arm; the measured epoch continues from the warmup's RETURNED
  state because DistScanTrainer.run_epoch donates its input (a second
  make_state over the same params tree would read deleted buffers).
  Returns a dict with each arm's final state, losses (device arrays),
  DispatchCounter and wall seconds."""
  import time

  import jax

  import graphlearn_tpu as glt
  from graphlearn_tpu.utils import count_dispatches

  def _arm(run):
    state = make_state()
    if warmup:
      state, losses = run(state)
      jax.block_until_ready(losses)
    with count_dispatches() as dc:
      t0 = time.perf_counter()
      state, losses = run(state)
      jax.block_until_ready(losses)
      wall = time.perf_counter() - t0
    return state, losses, dc, wall

  ref = glt.loader.DistFusedEpochTrainer(make_loader(), model, tx,
                                         num_classes)
  st_step, l_step, dc_step, wall_step = _arm(
      lambda s: ref.run_epoch_steps(s))

  trainer = glt.loader.DistScanTrainer(make_loader(), model, tx,
                                       num_classes,
                                       chunk_size=chunk_size)

  def _scan(s):
    state, losses, _ = trainer.run_epoch(s)
    return state, losses

  st_scan, l_scan, dc_scan, wall_scan = _arm(_scan)
  return {
      'step_state': st_step, 'step_losses': l_step,
      'step_dispatches': dc_step, 'step_wall_s': wall_step,
      'scan_state': st_scan, 'scan_losses': l_scan,
      'scan_dispatches': dc_scan, 'scan_wall_s': wall_scan,
  }


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=200_000)
  ap.add_argument('--avg-deg', type=int, default=15)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--fanout', type=int, nargs='+', default=[10, 5])
  ap.add_argument('--mesh-sizes', default='1,2,4,8')
  ap.add_argument('--iters', type=int, default=20)
  ap.add_argument('--feat-dim', type=int, default=100,
                  help='feature width for the exchange-volume report '
                       '(100 = ogbn-products)')
  ap.add_argument('--split-ratio', type=float, default=0.2,
                  help='hot-cache share assumed by the feature '
                       'exchange-volume report (the hit-rate floor)')
  ap.add_argument('--cpu-devices', type=int, default=8)
  ap.add_argument('--tpu', action='store_true',
                  help='use the attached TPU devices instead of the '
                       'virtual CPU mesh (single-chip rigs only reach '
                       'mesh_size=1)')
  ap.add_argument('--compare-calibrated', action='store_true',
                  help='per mesh size, run the EXACT-dedup engine at '
                       'worst-case capacities vs calibrated '
                       'frontier_caps (estimate_frontier_caps on the '
                       'host CSR) and report the step-time ratio')
  ap.add_argument('--compare-hetero-calibrated', action='store_true',
                  help='per mesh size, run the TYPED exact engine at '
                       'worst-case capacities vs calibrated '
                       'per-(hop,etype) caps '
                       '(estimate_hetero_frontier_caps) on an '
                       'IGBH-shaped typed graph and report the '
                       'step-time ratio (round 5)')
  ap.add_argument('--scan', action='store_true',
                  help='per mesh size, A/B the PER-STEP collocated '
                       'training epoch against the scanned '
                       'DistScanTrainer epoch (dispatch counts + '
                       'CPU-mesh wall; loader/scan_epoch.py)')
  ap.add_argument('--scan-steps', type=int, default=8,
                  help='epoch length (optimizer steps) for --scan')
  ap.add_argument('--scan-chunk', type=int, default=4,
                  help='lax.scan chunk size K for --scan')
  args = ap.parse_args()

  if not args.tpu:
    # jax 0.4.x has no jax_num_cpu_devices config key — the XLA flag
    # must be in place before backend init (conftest.py's pattern)
    import os
    import re
    flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                   os.environ.get('XLA_FLAGS', ''))
    os.environ['XLA_FLAGS'] = (
        flags +
        f' --xla_force_host_platform_device_count={args.cpu_devices}'
    ).strip()
  import jax
  if not args.tpu:
    jax.config.update('jax_platforms', 'cpu')
    try:
      jax.config.update('jax_num_cpu_devices', args.cpu_devices)
    except AttributeError:
      pass   # jax 0.4.x: XLA_FLAGS above is the knob
  from jax.sharding import Mesh

  sys.path.insert(0, __file__.rsplit('/', 2)[0])
  import graphlearn_tpu as glt
  from graphlearn_tpu.typing import GraphPartitionData

  if args.compare_hetero_calibrated:
    _compare_hetero(args, jax, glt, GraphPartitionData, Mesh)
    return
  if args.scan:
    _scan_ab(args, jax, glt)
    return

  n = args.num_nodes
  rng = np.random.default_rng(0)
  rows = rng.integers(0, n, n * args.avg_deg)
  # bench.py's products-like degree mix: half uniform, half zipf head —
  # uniform-only cols have no dedup overlap, which would make the
  # exact-dedup comparisons vacuous
  e = n * args.avg_deg
  cols = np.empty(e, np.int64)
  cols[:e // 2] = rng.integers(0, n, e // 2)
  cols[e // 2:] = rng.zipf(1.5, e - e // 2) % n
  host_topo = None
  if args.compare_calibrated:
    host_topo = glt.data.Topology(np.stack([rows, cols]), num_nodes=n)

  for p in [int(x) for x in args.mesh_sizes.split(',')]:
    if p > len(jax.devices()):
      continue
    dg, _, mesh = make_dist_fixture(rows, cols, n, p)
    seeds = rng.integers(0, n, (p, args.batch_size)).astype(np.int32)

    timed = _make_timed(jax, seeds, args.iters,
                        lambda o: o.edge_mask)

    if args.compare_calibrated:
      from graphlearn_tpu.sampler.calibrate import estimate_frontier_caps
      caps = estimate_frontier_caps(host_topo, list(args.fanout),
                                    args.batch_size)
      full = glt.distributed.DistNeighborSampler(
          dg, list(args.fanout), mesh, seed=0, dedup='merge')
      cal = glt.distributed.DistNeighborSampler(
          dg, list(args.fanout), mesh, seed=0, dedup='merge',
          frontier_caps=caps)
      dt_full, _ = timed(full)
      dt_cal, out = timed(cal)
      print(json.dumps({
          'metric': 'dist_exact_calibrated_speedup',
          'mesh_size': p,
          'value': round(dt_full / dt_cal, 3),
          'full_ms_per_step': round(1e3 * dt_full / args.iters, 2),
          'calibrated_ms_per_step': round(1e3 * dt_cal / args.iters, 2),
          'frontier_caps': [int(c) for c in caps],
          'full_plan': full._capacities(args.batch_size),
          'calibrated_plan': cal.hop_caps(args.batch_size),
          'overflow': bool(np.any(np.asarray(out.metadata['overflow']))),
          'backend': jax.default_backend(),
      }), flush=True)
      continue

    sampler = glt.distributed.DistNeighborSampler(
        dg, list(args.fanout), mesh, seed=0)
    dt, _ = timed(sampler)
    # feature-exchange volume at this mesh size (analytic from the
    # static capacities, like the sampler's exchange report): the
    # collate-time DistFeature all_to_all MB/shard/batch under the
    # miss-only posture vs the full-width posture it replaced
    from graphlearn_tpu.distributed.dist_feature import \
        feature_exchange_mb
    node_cap = sampler._node_cap(sampler._capacities(args.batch_size))
    fdim = args.feat_dim
    fx_opt = feature_exchange_mb(node_cap, p, fdim, bucket_frac=2.0,
                                 wire_bytes=2,
                                 hit_rate=args.split_ratio)
    fx_full = feature_exchange_mb(node_cap, p, fdim, bucket_frac=None,
                                  wire_bytes=4)
    print(json.dumps({
        'metric': 'dist_loader_seed_batches_per_sec',
        'mesh_size': p,
        'value': round(args.iters * p / dt, 2),
        'seeds_per_sec': round(args.iters * p * args.batch_size / dt, 1),
        'secs': round(dt, 4),
        'feature_exchange_mb_per_batch': round(fx_opt, 3),
        'feature_exchange_mb_per_batch_fullwidth': round(fx_full, 3),
        'feature_exchange_reduction_x': round(fx_full / fx_opt, 1),
        'feature_exchange_config': (
            f'request_width={node_cap}, F={fdim}, bucket_frac=2.0, '
            f'split_ratio={args.split_ratio}, bf16 wire'),
        'backend': jax.default_backend(),
    }), flush=True)


def _scan_ab(args, jax, glt):
  """Per-step collocated training epoch vs DistScanTrainer's scanned
  epoch, per mesh size: instrumented dispatch counts (the wall-clock
  story on the remote-dispatch rig — PERF.md) plus CPU-mesh wall as a
  scheduling sanity check. Both arms run the SAME data-parallel update
  (pipeline.DistFusedEpochTrainer), so the A/B isolates epoch
  EXECUTION: ~5 dispatches/step vs ceil(steps/K) + 2 per epoch."""
  import optax
  from graphlearn_tpu.models import GraphSAGE
  from graphlearn_tpu.models import train as train_lib

  n = args.num_nodes
  rng = np.random.default_rng(0)
  rows = rng.integers(0, n, n * args.avg_deg)
  cols = rng.integers(0, n, n * args.avg_deg)
  ncls = 16
  labels = rng.integers(0, ncls, n)
  for p in [int(x) for x in args.mesh_sizes.split(',')]:
    if p > len(jax.devices()):
      continue
    _, ds, mesh = make_dist_fixture(
        rows, cols, n, p, feat_dim=args.feat_dim,
        split_ratio=args.split_ratio, labels=labels, feat_rng=rng)
    seeds = rng.integers(0, n, p * args.batch_size * args.scan_steps)

    def make_loader():
      return glt.distributed.DistNeighborLoader(
          ds, list(args.fanout), seeds, batch_size=args.batch_size,
          shuffle=False, drop_last=True, seed=0, mesh=mesh)

    model = GraphSAGE(hidden_dim=64, out_dim=ncls,
                      num_layers=len(args.fanout))
    tx = optax.adam(1e-3)
    first = next(iter(make_loader()))
    params = model.init(jax.random.PRNGKey(0), np.asarray(first.x)[0],
                        np.asarray(first.edge_index)[0],
                        np.asarray(first.edge_mask)[0])

    def fresh_state():
      import jax.numpy as jnp
      return train_lib.TrainState(params, tx.init(params),
                                  jnp.zeros((), jnp.int32))

    ab = run_scan_ab(make_loader, model, tx, ncls, args.scan_chunk,
                     fresh_state)
    dc_step, dc_scan = ab['step_dispatches'], ab['scan_dispatches']
    steps = int(np.asarray(ab['scan_losses']).shape[0])
    print(json.dumps({
        'metric': 'dist_scan_epoch_ab',
        'mesh_size': p,
        'steps': steps,
        'chunk': args.scan_chunk,
        'dist_epoch_dispatches': dc_step.total,
        'dist_scan_epoch_dispatches': dc_scan.total,
        'dispatch_reduction_x': round(
            dc_step.total / max(dc_scan.total, 1), 1),
        'dist_epoch_wall_s': round(ab['step_wall_s'], 4),
        'dist_scan_epoch_wall_s': round(ab['scan_wall_s'], 4),
        'wall_ratio': round(
            ab['step_wall_s'] / max(ab['scan_wall_s'], 1e-9), 2),
        'backend': jax.default_backend(),
    }), flush=True)


def _make_timed(jax, seeds, iters, ready_of):
  """Shared warmup+measure closure: ONE timing protocol for the homo
  and hetero comparisons (a drift here would skew the PERF.md
  speedup tables against each other)."""

  def timed(sampler):
    outs = [sampler.sample_from_nodes(seeds) for _ in range(3)]
    jax.block_until_ready([ready_of(o) for o in outs])
    t0 = time.perf_counter()
    outs = [sampler.sample_from_nodes(seeds) for _ in range(iters)]
    jax.block_until_ready([ready_of(o) for o in outs])
    return time.perf_counter() - t0, outs[-1]

  return timed


def _compare_hetero(args, jax, glt, GraphPartitionData, Mesh):
  """Typed worst-case vs calibrated per-(hop, etype) caps on the
  sharded engine — the hetero counterpart of --compare-calibrated
  (whose homo CPU-mesh ratio was 3.65x at the products config,
  PERF.md round 4). 3 typed hops: where the worst case compounds
  ACROSS etypes every hop."""
  n_p = args.num_nodes
  n_a = n_p // 2
  rng = np.random.default_rng(0)
  CITES = ('paper', 'cites', 'paper')
  WRITES = ('author', 'writes', 'paper')
  REV = ('paper', 'rev_writes', 'author')
  e_c = n_p * args.avg_deg
  c_rows = rng.integers(0, n_p, e_c)
  c_cols = np.empty(e_c, np.int64)
  c_cols[:e_c // 2] = rng.integers(0, n_p, e_c // 2)
  c_cols[e_c // 2:] = rng.zipf(1.5, e_c - e_c // 2) % n_p
  e_w = n_a * max(args.avg_deg // 3, 2)
  w_rows = rng.integers(0, n_a, e_w)
  w_cols = rng.zipf(1.5, e_w) % n_p
  edges = {CITES: (c_rows, c_cols), WRITES: (w_rows, w_cols),
           REV: (w_cols, w_rows)}
  fan = {et: list(args.fanout) for et in edges}
  host = {et: glt.data.Graph(
      glt.data.Topology(np.stack([r, c]),
                        num_nodes=(n_a if et[0] == 'author' else n_p)),
      'CPU') for et, (r, c) in edges.items()}
  caps = glt.sampler.estimate_hetero_frontier_caps(
      host, fan, {'paper': args.batch_size}, num_probes=4, slack=1.5)

  for p in [int(x) for x in args.mesh_sizes.split(',')]:
    if p > len(jax.devices()):
      continue
    pb_p = {t: (v % p).astype(np.int32) for t, v in
            (('paper', np.arange(n_p)), ('author', np.arange(n_a)))}
    parts = []
    for q in range(p):
      part = {}
      for et, (r, c) in edges.items():
        key_pb = pb_p[et[0]]
        m = key_pb[r] == q
        part[et] = GraphPartitionData(
            edge_index=np.stack([r[m], c[m]]),
            eids=np.flatnonzero(m))
      parts.append(part)
    mesh = Mesh(np.array(jax.devices()[:p]), ('g',))
    dg = glt.distributed.DistHeteroGraph(p, 0, parts, pb_p)
    seeds = rng.integers(0, n_p, (p, args.batch_size)).astype(np.int32)
    timed = _make_timed(jax, ('paper', seeds), args.iters,
                        lambda o: list(o.edge_mask.values()))

    full = glt.distributed.DistNeighborSampler(
        dg, fan, mesh, seed=0, dedup='merge')
    cal = glt.distributed.DistNeighborSampler(
        dg, fan, mesh, seed=0, dedup='merge', frontier_caps=caps)
    dt_full, _ = timed(full)
    dt_cal, out = timed(cal)
    _, _, nc_full = full._hetero_plan({'paper': args.batch_size})
    _, _, nc_cal = cal._hetero_plan({'paper': args.batch_size})
    print(json.dumps({
        'metric': 'dist_hetero_calibrated_speedup',
        'mesh_size': p,
        'value': round(dt_full / dt_cal, 3),
        'full_ms_per_step': round(1e3 * dt_full / args.iters, 2),
        'calibrated_ms_per_step': round(1e3 * dt_cal / args.iters, 2),
        'node_caps_full': {t: int(v) for t, v in nc_full.items()},
        'node_caps_calibrated': {t: int(v) for t, v in nc_cal.items()},
        'caps': {'/'.join(et): list(v) for et, v in caps.items()},
        'overflow': bool(np.any(np.asarray(out.metadata['overflow']))),
        'backend': jax.default_backend(),
    }), flush=True)


if __name__ == '__main__':
  main()
