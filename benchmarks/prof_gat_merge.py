"""Probe: GAT train step on calibrated exact batches — segment softmax
vs MergeGATConv's per-target k-run softmax (device-trace truth).
Bench config: 1M nodes, [15,10,5] @ 1024, GAT h=128 2 heads bf16.
"""
import os
import shutil
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def run(model_kw, tag, ds, train_idx, cal):
  import jax
  import jax.numpy as jnp
  import graphlearn_tpu as glt
  from graphlearn_tpu.models import GAT
  from graphlearn_tpu.models import train as train_lib
  loader = glt.loader.NeighborLoader(
      ds, bench.FANOUT, train_idx, batch_size=bench.BATCH, shuffle=True,
      drop_last=True, seed=0, dedup='map', frontier_caps=cal,
      seed_labels_only=True)
  no, eo = train_lib.merge_hop_offsets(bench.BATCH, bench.FANOUT,
                                       frontier_caps=cal)
  model = GAT(hidden_dim=128, out_dim=bench.E2E_CLASSES, num_layers=3,
              heads=2, dtype=jnp.bfloat16, hop_node_offsets=no,
              hop_edge_offsets=eo, **model_kw)
  it = iter(loader)
  first = train_lib.batch_to_dict(next(it))
  state, tx = train_lib.create_train_state(model, jax.random.PRNGKey(0),
                                           first)
  step, _ = train_lib.make_train_step(model, tx, bench.E2E_CLASSES)
  state, loss, _ = step(state, first)
  for _ in range(2):
    state, loss, _ = step(state, train_lib.batch_to_dict(next(it)))
  jax.block_until_ready(loss)
  STEPS = 6
  td = f'/tmp/glt_gat_{tag}'
  shutil.rmtree(td, ignore_errors=True)
  jax.profiler.start_trace(td)
  losses = []
  for _ in range(STEPS):
    state, loss, _ = step(state, train_lib.batch_to_dict(next(it)))
    losses.append(loss)
  jax.block_until_ready(losses)
  jax.profiler.stop_trace()
  progs = glt.utils.device_program_ms(td)
  tot = sum(ms for ms, _ in progs.values())
  tr = max((ms for nm, (ms, _) in progs.items()
            if nm.startswith('jit_train_step')), default=0)
  print(f'{tag:16s} total {tot:7.2f} ms/step (train program {tr:6.2f})')
  if os.environ.get('GLT_GAT_OPS'):
    for n, (ms, cnt) in glt.utils.device_op_ms(td, top=14,
                                               steps=STEPS).items():
      print(f'    {n[:64]:66s} {ms:8.3f} ms/step x{cnt}')


def main():
  import graphlearn_tpu as glt
  glt.utils.enable_compilation_cache()
  graph = bench.build_graph()
  rng = np.random.default_rng(2)
  ds = glt.data.Dataset(graph=graph)
  ds.init_node_features(rng.standard_normal(
      (bench.NUM_NODES, bench.E2E_FEAT_DIM), dtype=np.float32))
  ds.init_node_labels(rng.integers(0, bench.E2E_CLASSES, bench.NUM_NODES))
  train_idx = rng.integers(0, bench.NUM_NODES, bench.BATCH * 12)
  cal = glt.sampler.estimate_frontier_caps(graph, bench.FANOUT,
                                           bench.BATCH, num_probes=5,
                                           slack=1.5)
  run({}, 'gat_segment', ds, train_idx, cal)
  run(dict(merge_dense=True, fanouts=tuple(bench.FANOUT)),
      'gat_mergedense', ds, train_idx, cal)


if __name__ == '__main__':
  main()
