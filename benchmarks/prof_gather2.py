"""Autotune probe for the r13 kernel campaign: gather v2 (run-segmented
multi-row DMA, ops.gather_rows_hbm2) and the fused sample+gather hop
(ops.sample_hop_fused) vs their XLA paths, across the
``block_rows x run_span`` / ``window x block_seeds`` grids and several
id DISTRIBUTIONS (the v2 kernel's win condition is locality, so the
distribution axis is as load-bearing as the tile axes).

Run on TPU from the repo root: ``python benchmarks/prof_gather2.py``
(add ``--quick`` for a 2x2 grid smoke). NOTE: printed wall clocks are
DISPATCH times on the axon tunnel (PERF.md 'Timing on the axon
tunnel'); ground truth is the per-config `jax.profiler` device trace
each cell captures under /tmp/glt_prof_gather2_*. The table printer
reads those traces (utils.device_program_ms), so the numbers shown ARE
device ms when the TPU lane is present, dispatch-wall otherwise
(labelled).

Interpretation guide (what decides the routing flags):
  - gather v2 wins a cell when its device ms beats XLA take's on the
    SAME ids; the shipping default flips UnifiedTensor.use_pallas_v2
    only for a win on the 'sorted'/'runs' distributions (its target
    workload — staging slab gathers); a 'random' loss is expected (the
    sort + unsort adds work, PERF.md) and acceptable if trace-attributed.
  - fused hop wins when one staged-segment DMA per seed beats k element
    gathers; hub-heavy frontiers dilute the win (deg > window seeds pay
    k row DMAs) — the 'zipf' seed mix measures that dilution.
"""
import argparse
import shutil
import sys
import time

sys.path.insert(0, __file__.rsplit('/', 2)[0])

import numpy as np


def _dists(rng, n, b):
  """The id-distribution axis: each is a [b] int32 vector."""
  contig0 = rng.integers(0, n - b)
  return {
      # uniform random: v2's worst case (every slot its own DMA + sort)
      'random': rng.integers(0, n, b).astype(np.int32),
      # sorted unique: the staging/slab shape (presorted=True path)
      'sorted': np.sort(rng.choice(n, b, replace=False)).astype(np.int32),
      # duplicate-heavy: hot rows repeated (cache-miss fan-in shape)
      'dup': rng.choice(rng.integers(0, n, b // 16), b).astype(np.int32),
      # one contiguous span: the upper bound for run coverage
      'runs': np.arange(contig0, contig0 + b, dtype=np.int32),
  }


def _timed(jax, fn, trace_dir, prefix, iters):
  from graphlearn_tpu.utils import device_program_ms
  jax.block_until_ready(fn())
  shutil.rmtree(trace_dir, ignore_errors=True)
  jax.profiler.start_trace(trace_dir)
  t0 = time.perf_counter()
  outs = [fn() for _ in range(iters)]
  jax.block_until_ready(outs)
  wall_ms = (time.perf_counter() - t0) / iters * 1e3
  jax.profiler.stop_trace()
  for name, (ms, _) in device_program_ms(trace_dir).items():
    if name.startswith(prefix):
      return ms, 'device'
  return wall_ms, 'wall'


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-rows', type=int, default=1_000_000)
  ap.add_argument('--feat', type=int, default=128)
  ap.add_argument('--ids', type=int, default=131072)
  ap.add_argument('--iters', type=int, default=20)
  ap.add_argument('--quick', action='store_true')
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  from graphlearn_tpu import ops
  from graphlearn_tpu.ops.gather_pallas import _gather_rows_hbm2_impl

  n, f, b = args.num_rows, args.feat, args.ids
  rng = np.random.default_rng(0)
  table = jnp.asarray(rng.standard_normal((n, f)).astype(np.float32))
  dists = _dists(rng, n, b)
  on_tpu = jax.default_backend() == 'tpu'
  interp = not on_tpu   # CPU smoke runs the interpreter on tiny shapes
  if interp and not args.quick:
    print('backend is not TPU: forcing --quick (interpret-mode smoke)')
    args.quick = True
  if args.quick and interp:
    # interpret-mode DMA emulation pays per UNROLLED slot at trace time:
    # keep the smoke shapes tiny or the probe spends minutes compiling
    n, b = 2048, 128
    table = jnp.asarray(rng.standard_normal((n, f)).astype(np.float32))
    dists = _dists(rng, n, b)

  take = jax.jit(lambda t, i: jnp.take(t, i, axis=0))
  if args.quick:
    grid_blocks, grid_spans = (16, 64), (4, 8)
  else:
    grid_blocks, grid_spans = (64, 128, 256, 512), (1, 4, 8, 16, 32)

  print(f'backend={jax.default_backend()}  table=[{n}, {f}] f32  '
        f'ids={b}  iters={args.iters}')
  print('\n=== gather v2: device ms/call (XLA take baseline per dist) ===')
  for dname, ids_np in dists.items():
    ids = jnp.asarray(ids_np)
    base_ms, src = _timed(jax, lambda: take(table, ids),
                          f'/tmp/glt_prof_gather2_take_{dname}',
                          'jit_', args.iters)
    presorted = bool((np.diff(ids_np) >= 0).all())
    print(f'  [{dname}] xla_take: {base_ms:.3f} ms ({src}; '
          f'presorted={presorted})')
    for br in grid_blocks:
      for span in grid_spans:
        tag = f'{dname}_b{br}_s{span}'
        try:
          ms, src = _timed(
              jax,
              lambda br=br, span=span: _gather_rows_hbm2_impl(
                  table, ids, br, span, presorted, interp),
              f'/tmp/glt_prof_gather2_{tag}', 'jit_', args.iters)
          verdict = 'WIN' if ms < base_ms else 'lose'
          print(f'    v2 block_rows={br:4d} run_span={span:3d}: '
                f'{ms:8.3f} ms ({src})  {verdict} '
                f'x{base_ms / ms:.2f}')
        except Exception as e:  # noqa: BLE001 — record, keep probing
          print(f'    v2 block_rows={br:4d} run_span={span:3d}: FAILED '
                f'{type(e).__name__}: {str(e)[:120]}')

  # ---- fused hop grid --------------------------------------------------
  print('\n=== fused sample+gather hop (window x block_seeds grid) ===')
  e = n * 8 if not (args.quick and interp) else n * 4
  rows = rng.integers(0, n, e)
  cols = np.sort(rng.integers(0, n, e))  # arbitrary; rows sorted below
  order = np.argsort(rows, kind='stable')
  rows = rows[order]
  indptr = np.concatenate(
      [[0], np.cumsum(np.bincount(rows, minlength=n))]).astype(np.int32)
  ip = jnp.asarray(indptr)
  ind = jnp.asarray(cols[order].astype(np.int32))
  meta = jnp.stack([ip[:-1], ip[1:] - ip[:-1]], 1).astype(jnp.int32)
  sb = min(b, 16384) if not (args.quick and interp) else 64
  seed_mixes = {
      'uniform': rng.integers(0, n, sb).astype(np.int32),
      'zipf': (rng.zipf(1.5, sb) % n).astype(np.int32),  # hub-heavy
  }
  key = jax.random.fold_in(jax.random.PRNGKey(0), 1)
  k = 10
  mask = jnp.ones((sb,), bool)
  for mix, seeds_np in seed_mixes.items():
    seeds = jnp.asarray(seeds_np)
    base_ms, src = _timed(
        jax, lambda: ops.uniform_sample(ip, ind, seeds, mask, k, key,
                                        meta=meta),
        f'/tmp/glt_prof_fh_xla_{mix}', 'jit_uniform_sample', args.iters)
    print(f'  [{mix}] xla_hop (k={k}, {sb} seeds): {base_ms:.3f} ms '
          f'({src})')
    for window in ((128,) if args.quick else (128, 256, 512, 1024)):
      blocks = ops.build_indices128(ind, min_rows=window // 128 + 1)
      for bs in ((16,) if args.quick else (64, 128, 256)):
        try:
          ms, src = _timed(
              jax,
              lambda window=window, bs=bs, blocks=blocks:
              ops.sample_hop_fused(ip, ind, blocks, seeds, mask, k, key,
                                   meta=meta, window=window,
                                   block_seeds=bs, interpret=interp),
              f'/tmp/glt_prof_fh_{mix}_w{window}_b{bs}',
              'jit_sample_hop_fused', args.iters)
          verdict = 'WIN' if ms < base_ms else 'lose'
          print(f'    fused window={window:5d} block_seeds={bs:4d}: '
                f'{ms:8.3f} ms ({src})  {verdict} x{base_ms / ms:.2f}')
        except Exception as e:  # noqa: BLE001
          print(f'    fused window={window:5d} block_seeds={bs:4d}: '
                f'FAILED {type(e).__name__}: {str(e)[:120]}')


if __name__ == '__main__':
  main()
