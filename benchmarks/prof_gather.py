"""Profile the Pallas HBM row-gather kernel vs XLA's take on the TPU.

Run from the repo root: `python benchmarks/prof_gather.py`. NOTE: the
wall clocks this script prints are DISPATCH times on the axon tunnel
(block_until_ready returns at dispatch — PERF.md "Timing on the axon
tunnel"); ground truth comes from jax.profiler device traces. Trace-true
numbers on v5e-1 (1M x 128 f32 table, 131k random ids):

  xla_take:    1.20 ms/call device time  (~52 GB/s useful)   <- WINNER
  pallas_128:  1.41 ms/call
  pallas_256:  1.41 ms/call
  pallas_64:   1.62 ms/call
  pallas_32:   2.40 ms/call
  pallas_512:  Mosaic compile failure (semaphore budget)

XLA's gather is already DMA-pipelined on TPU; the per-row-DMA kernel does
not beat it, so UnifiedTensor does NOT auto-route through it
(use_pallas opt-in). Kept for rigs where the balance differs and as the
framework's Pallas reference kernel.
"""
import sys
import time

sys.path.insert(0, __file__.rsplit('/', 2)[0])

import numpy as np
import jax
import jax.numpy as jnp

from graphlearn_tpu.ops.gather_pallas import gather_rows_hbm

N, F, B = 1_000_000, 128, 131072


def main():
  # NO device->host fetch before the timed loops: the first D2H flips the
  # axon runtime into its degraded synchronous dispatch mode (PERF.md) and
  # every later timing measures per-call overhead, not the gather.
  # Correctness checks run AFTER all timing.
  rng = np.random.default_rng(0)
  table = jnp.asarray(rng.random((N, F), np.float32))
  ids_np = rng.integers(0, N, B).astype(np.int32)
  ids = jnp.asarray(ids_np)
  take = jax.jit(lambda t, i: jnp.take(t, i, axis=0))

  cases = [('xla_take', lambda: take(table, ids))]
  for g in (64, 128, 256):
    cases.append((f'pallas_{g}',
                  lambda g=g: gather_rows_hbm(table, ids, block_rows=g,
                                              force=True)))
  results = []
  for name, fn in cases:
    try:
      jax.block_until_ready(fn())
      t0 = time.perf_counter()
      outs = [fn() for _ in range(50)]
      jax.block_until_ready(outs)
      dt = time.perf_counter() - t0
      gb = 50 * B * F * 4 / dt / (1024 ** 3)
      results.append(f'{name}: {dt * 20:.3f} ms/call, {gb:.1f} GB/s')
    except Exception as e:  # noqa: BLE001 — report and continue profiling
      results.append(f'{name}: FAILED {type(e).__name__}: {str(e)[:200]}')

  small = gather_rows_hbm(table, ids[:256], block_rows=64, force=True)
  np.testing.assert_allclose(np.asarray(small),
                             np.asarray(table)[ids_np[:256]])
  print('backend:', jax.default_backend())
  print('correctness OK')
  for line in results:
    print(line)


if __name__ == '__main__':
  main()
