"""CLI front-end for the multichip dryrun (__graft_entry__.dryrun_multichip).

Runs the FULL distributed pipeline on an n-device mesh — sharded
sampling + feature exchange + data-parallel update, the calibrated-caps
and feature-cache A/Bs, and the scanned-distributed-epoch A/B
(DistScanTrainer bit-exact vs the per-step collocated loop, dispatch
budget asserted) — on virtual CPU devices by default, so the whole
mesh story is checkable on a laptop:

    python benchmarks/dryrun_multichip.py --devices 8

Pass --tpu to run on the attached accelerator devices instead (the
device count must then not exceed the real chip count).
"""
import argparse
import os
import sys


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--devices', type=int, default=8,
                  help='mesh size (virtual CPU devices unless --tpu)')
  ap.add_argument('--tpu', action='store_true',
                  help='use the attached accelerator devices (skips the '
                       'CPU-platform override)')
  args = ap.parse_args()
  if not args.tpu:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
  root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  sys.path.insert(0, root)
  import importlib.util
  spec = importlib.util.spec_from_file_location(
      '_glt_graft_entry', os.path.join(root, '__graft_entry__.py'))
  entry = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(entry)
  entry.dryrun_multichip(args.devices)


if __name__ == '__main__':
  main()
