"""Device-trace full-pipeline epoch at REAL products scale (VERDICT r4
item 5): bench.py's `epoch_time_s` extrapolates device-trace ms/batch
from the 1M-node bench synthetic x 192 products steps; this script
measures the SAME pipeline on the 2.45M-node products-matched gate
graph (examples/train_sage_ogbn_products.py make_synthetic — power-law
fit, p_intra 0.58) so `epoch_time_s_fullscale` is a measurement, not an
extrapolation. Traces TRACE_STEPS batches (a full 192-step trace is
gigabytes); ms/batch x 192 is still a device-trace number at the
actual scale/degree structure.

Run on TPU: python benchmarks/prof_epoch_fullscale.py
"""
import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_products_example():
  import graphlearn_tpu as glt
  return glt.utils.load_module(
      os.path.join(REPO, 'examples', 'train_sage_ogbn_products.py'))


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=2_449_029)
  ap.add_argument('--trace-steps', type=int, default=15)
  ap.add_argument('--batch', type=int, default=None,
                  help='override bench.BATCH (CPU smoke only)')
  ap.add_argument('--fanout', type=int, nargs='+', default=None)
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import graphlearn_tpu as glt
  import bench
  glt.utils.enable_compilation_cache()
  bench.E2E_ITERS = args.trace_steps
  if args.batch:
    bench.BATCH = args.batch
  if args.fanout:
    bench.FANOUT = args.fanout

  ex = _load_products_example()
  ei, feat, label, train_idx, _, _, ncls = ex.make_synthetic(
      args.num_nodes, 25, 47, 100, 0.58, 0.1, np.random.default_rng(0))
  ds = glt.data.Dataset()
  ds.init_graph(ei, num_nodes=feat.shape[0], graph_mode='HBM')
  ds.init_node_features(feat)
  ds.init_node_labels(label)
  steps_per_epoch = 196_615 // bench.BATCH   # products train split
  idx = np.random.default_rng(1).permutation(train_idx)[
      :bench.BATCH * (args.trace_steps + 6)]

  result = {'num_nodes': args.num_nodes, 'trace_steps': args.trace_steps,
            'steps_per_epoch': steps_per_epoch}
  cal_caps = glt.sampler.estimate_frontier_caps(
      ds.graph, bench.FANOUT, bench.BATCH, input_nodes=train_idx,
      num_probes=5, slack=1.5)
  result['calibrated_caps'] = cal_caps
  for variant, kw in (('exact', dict(cal_caps=cal_caps)),
                      ('tree', {})):
    tot, tr = bench._run_e2e(ds, idx, jnp.bfloat16, jax,
                             f'/tmp/glt_fullscale_{variant}',
                             variant=variant, **kw)
    if tot is None:
      result[f'{variant}_error'] = 'no trace events (non-TPU backend?)'
      continue
    result[f'{variant}_step_ms'] = round(float(tot), 3)
    result[f'{variant}_train_program_ms'] = (round(float(tr), 3)
                                             if tr else None)
    result[f'epoch_time_s_fullscale_{variant}'] = round(
        steps_per_epoch * tot / 1e3, 3)
  print(json.dumps(result), flush=True)


if __name__ == '__main__':
  main()
