"""Probe suite for the exact-dedup (map) inducer redesign (round 3).

Measures, with device-trace truth (PERF.md timing rules):
  - element gather/scatter rates vs TABLE size (is a small table faster,
    i.e. does XLA keep it in VMEM?)
  - XLA sort cost for 1-D [S] vs lane-parallel (R, 128) shapes
  - whether Mosaic lowers a dynamic gather over a VMEM-resident table
    inside a Pallas kernel, and at what speed

Run: python benchmarks/prof_dedup.py
"""
import functools
import json
import shutil

import jax
import jax.numpy as jnp
import numpy as np

TRACE_DIR = '/tmp/glt_prof_dedup'
S = 768 * 1024          # candidate stream size (bench hop-3 scale)
ITERS = 8


def _device_program_ms(trace_dir):
  from graphlearn_tpu.utils import device_program_ms
  return device_program_ms(trace_dir)


def named_jit(name, fn, *static):
  fn.__name__ = name
  return jax.jit(fn, static_argnames=static)


def main():
  rng = np.random.default_rng(0)
  probes = {}  # name -> (fn, args)

  # --- element gather from tables of varying size ---
  for logn in (13, 16, 20, 24):
    n = 1 << logn
    table = jnp.asarray(rng.integers(0, 1 << 30, n, dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, n, S, dtype=np.int32))
    def g(t, i):
      return t[i].sum()
    probes[f'gather_n{logn}'] = (named_jit(f'gather_n{logn}', g),
                                 (table, idx))

  # --- element scatter-set into tables of varying size ---
  for logn in (16, 20, 24):
    n = 1 << logn
    table = jnp.zeros((n,), jnp.int32)
    idx = jnp.asarray(rng.integers(0, n, S, dtype=np.int32))
    vals = jnp.asarray(rng.integers(0, 1 << 30, S, dtype=np.int32))
    def sc(t, i, v):
      return t.at[i].set(v).sum()
    probes[f'scatter_n{logn}'] = (named_jit(f'scatter_n{logn}', sc),
                                  (table, idx, vals))

  # --- sorts ---
  ids = jnp.asarray(rng.integers(0, 1 << 20, S, dtype=np.int32))
  probes['sort_1d'] = (named_jit('sort_1d', lambda x: jnp.sort(x).sum()),
                       (ids,))
  ids2 = ids.reshape(-1, 128)
  probes['sort_lanes'] = (named_jit(
      'sort_lanes', lambda x: jnp.sort(x, axis=0).sum()), (ids2,))
  ids2b = ids.reshape(-1, 512)
  probes['sort_lanes512'] = (named_jit(
      'sort_lanes512', lambda x: jnp.sort(x, axis=0).sum()), (ids2b,))
  probes['argsort_1d'] = (named_jit(
      'argsort_1d', lambda x: jnp.argsort(x).sum()), (ids,))
  # sort (key, payload) pair — what dedup+relabel actually needs
  pay = jnp.arange(S, dtype=jnp.int32)
  def sortpair(x, p):
    xs, ps = jax.lax.sort((x, p), num_keys=1)
    return xs.sum() + ps.sum()
  probes['sort_pair_1d'] = (named_jit('sort_pair_1d', sortpair), (ids, pay))
  def sortpair2(x, p):
    xs, ps = jax.lax.sort((x, p), dimension=0, num_keys=1)
    return xs.sum() + ps.sum()
  probes['sort_pair_lanes'] = (named_jit('sort_pair_lanes', sortpair2),
                               (ids2, pay.reshape(-1, 128)))

  # --- take_along_axis per-lane gather (Mosaic DynamicGather probe, XLA) ---
  tbl2 = jnp.asarray(rng.integers(0, 1 << 30, (8192, 128), dtype=np.int32))
  li = jnp.asarray(rng.integers(0, 8192, (S // 128, 128), dtype=np.int32))
  def tala(t, i):
    return jnp.take_along_axis(t, i, axis=0).sum()
  probes['take_along_lanes'] = (named_jit('take_along_lanes', tala),
                                (tbl2, li))

  # --- pallas VMEM-table gather probe ---
  try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    TN = 1 << 16  # 256KB table in VMEM

    def pk(table_ref, idx_ref, out_ref):
      t = table_ref[:]                     # [TN] table in VMEM (as value)
      idx = idx_ref[:]                     # [S/128, 128]
      out_ref[:] = jnp.take(t.reshape(-1), idx.reshape(-1),
                            axis=0).reshape(idx.shape)

    ptable = jnp.asarray(rng.integers(0, 1 << 30, TN, dtype=np.int32))
    pidx = jnp.asarray(
        rng.integers(0, TN, (S // 128, 128), dtype=np.int32))

    def pallas_gather(t, i):
      return pl.pallas_call(
          pk,
          out_shape=jax.ShapeDtypeStruct(i.shape, jnp.int32),
          in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                    pl.BlockSpec(memory_space=pltpu.VMEM)],
          out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
      )(t, i).sum()
    probes['pallas_vmem_take'] = (named_jit('pallas_vmem_take',
                                            pallas_gather),
                                  (ptable, pidx))
  except Exception as e:  # noqa: BLE001
    print(f'# pallas probe setup failed: {type(e).__name__}: {e}')

  # compile everything outside the trace; drop probes that fail to lower
  live = {}
  for name, (fn, args) in probes.items():
    try:
      out = fn(*args)
      jax.block_until_ready(out)
      live[name] = (fn, args)
    except Exception as e:  # noqa: BLE001
      print(f'# {name}: COMPILE/RUN FAILED: {type(e).__name__}: '
            f'{str(e)[:200]}')

  shutil.rmtree(TRACE_DIR, ignore_errors=True)
  jax.profiler.start_trace(TRACE_DIR)
  outs = []
  for name, (fn, args) in live.items():
    for _ in range(ITERS):
      outs.append(fn(*args))
  jax.block_until_ready(outs)
  jax.profiler.stop_trace()

  progs = _device_program_ms(TRACE_DIR)
  for name in live:
    ms = None
    for n, (m, _) in progs.items():
      if n == f'jit_{name}' or n.startswith(f'jit_{name}('):
        ms = m
    rate = S / ms / 1e3 if ms else float('nan')  # M elem/s
    print(f'{name:24s} {ms if ms is not None else -1:8.3f} ms   '
          f'{rate:8.1f} M elem/s')


if __name__ == '__main__':
  main()
