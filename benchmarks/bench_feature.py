"""Benchmark: feature-lookup throughput (GB/s) at varying hot-split ratios.

Mirrors /root/reference/benchmarks/api/bench_feature.py:27-62: sample
[15, 10, 5] batches of 1024 seeds on an ogbn-products-scale graph, then time
``feature[node_ids]`` and report GB/s of *useful* rows delivered. Run at
several ``split_ratio`` values to see the hot-cache effect; with the
miss-proportional mixed gather (data/unified_tensor.py) the host->device
traffic scales with (1 - hit_rate), not batch size.

TIMING: the all-hot path reports DEVICE-TRACE GB/s (wall clocks are
unreliable on the axon tunnel — PERF.md); mixed ratios inherently involve
host work + transfers, so their figure is wall-clock and tunnel-bound on
this rig (noted in the output as timing='wall').

Usage: python benchmarks/bench_feature.py [--split-ratios 0.2,1.0]
"""
import argparse
import json
import shutil
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit('/', 2)[0])

from bench import (AVG_DEG, BATCH, FANOUT, NUM_NODES,  # noqa: E402
                   _device_program_ms, build_graph)

TRACE_DIR = '/tmp/glt_feat_trace'

FEAT_DIM = 100  # ogbn-products feature width
ITERS = 20
WARMUP = 3


def log(msg):
  print(msg, file=sys.stderr, flush=True)


def main():
  p = argparse.ArgumentParser()
  p.add_argument('--split-ratios', default='0.0,0.2,1.0')
  p.add_argument('--num-nodes', type=int, default=NUM_NODES)
  p.add_argument('--iters', type=int, default=ITERS)
  args = p.parse_args()
  iters = args.iters

  import jax
  import graphlearn_tpu as glt
  from graphlearn_tpu.sampler import NodeSamplerInput
  glt.utils.enable_compilation_cache()

  log('building graph...')
  graph = build_graph()
  sampler = glt.sampler.NeighborSampler(graph, FANOUT, seed=0, fused=True)
  feat = np.random.default_rng(0).random(
      (args.num_nodes, FEAT_DIM), np.float32)
  log('degree reorder...')
  reordered, id2index = glt.data.sort_by_in_degree(feat, 1.0, graph.topo)

  rng = np.random.default_rng(1)
  seed_sets = [rng.integers(0, args.num_nodes, BATCH)
               for _ in range(WARMUP + iters)]
  # pre-sample the node id sets once (feature lookup is what's timed;
  # reference likewise excludes sampling from the clock,
  # bench_feature.py:52-58)
  node_sets = []
  for i, seeds in enumerate(seed_sets):
    out = sampler.sample_from_nodes(NodeSamplerInput(seeds),
                                    batch_cap=BATCH)
    node_sets.append((np.asarray(out.node), int(out.num_nodes)))
    log(f'presampled {i + 1}/{len(seed_sets)}')

  results = []
  for ratio in [float(r) for r in args.split_ratios.split(',')]:
    log(f'split_ratio={ratio}: uploading store...')
    store = glt.data.Feature(reordered, split_ratio=ratio,
                             id2index=id2index)
    # all-hot lookups never need host ids: keep the id sets device-resident
    # so dispatch stays pipelined (PERF.md — a host fetch mid-loop measures
    # the tunnel, not the chip). Mixed lookups inherently consume host ids.
    import jax.numpy as jnp
    lookup_sets = (node_sets if ratio < 1.0 else
                   [(jnp.asarray(ids), nv) for ids, nv in node_sets])
    outs = []
    for ids, _ in lookup_sets[:WARMUP]:
      outs.append(store[ids])
    jax.block_until_ready(outs)
    log(f'split_ratio={ratio}: timing...')
    all_hot = ratio >= 1.0
    if all_hot:
      shutil.rmtree(TRACE_DIR, ignore_errors=True)
      jax.profiler.start_trace(TRACE_DIR)
    t0 = time.perf_counter()
    outs, rows = [], 0
    for ids, nvalid in lookup_sets[WARMUP:]:
      outs.append(store[ids])
      rows += nvalid
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    timing = 'wall'
    if all_hot:
      jax.profiler.stop_trace()
      progs = _device_program_ms(TRACE_DIR)
      dev_ms = sum(ms * cnt for ms, cnt in progs.values())
      if dev_ms:
        dt = dev_ms / 1000.0
        timing = 'device-trace'
    gbs = rows * FEAT_DIM * 4 / dt / (1024 ** 3)
    hot = int(args.num_nodes * ratio)
    hits = sum(int((store.id2index[ids] < hot).sum())
               for ids, _ in node_sets[WARMUP:]) if ratio > 0 else 0
    total = sum(ids.shape[0] for ids, _ in node_sets[WARMUP:])
    results.append(dict(split_ratio=ratio,
                        gb_per_sec=round(gbs, 3),
                        hit_rate=round(hits / total, 3),
                        lookup_rows=rows, secs=round(dt, 4),
                        timing=timing))
    print(json.dumps({'metric': 'feature_lookup_gbps', **results[-1]}))
  return results


if __name__ == '__main__':
  main()
